"""CAS garbage collection: a byte budget for the disk tier.

The PR-9 ``DiskCAS`` never deletes a healthy entry — correct for a cache
fed by a bounded workload, unbounded for the fleet the ROADMAP describes.
This module closes that: ``scan`` walks the store into per-entry byte
sizes plus the garbage classes (orphaned payload sidecars whose meta never
committed, staging leftovers, foreign files), and ``collect`` brings the
store under a byte budget by deleting orphans first, then whole entries in
least-recently-used order.

**Eviction is always safe** because the CAS is a cache: the journal stays
the source of truth, every entry is reconstructible by re-running the
(pure) simulation, and a concurrent ``get`` of an evicted fingerprint is
just a miss. The only cost of any GC decision is a re-run.

**Recency** comes from the store's in-process access ledger — perf_counter
stamps taken on every get/put (``DiskCAS`` keeps them; the clock is
injectable, and tests/test_lint.py bans the wall clock from this package).
Entries never touched by THIS process (cold restarts) have no stamp and
evict first, ordered among themselves by file modification time — an
ordering-only fallback, never arithmetic against the process clock.

Deletion order inside one entry is meta FIRST (the commit point: the entry
becomes invisible in one unlink), payloads second — a crash mid-evict
leaves orphan sidecars, which are exactly what the next sweep's orphan
pass collects. The ``on_cas_evict`` fault probe sits in that window so the
SIGKILL matrix can prove it.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import shutil

from gol_tpu.cache import store as cas_store
from gol_tpu.resilience import STAGING_SUFFIX, faults

logger = logging.getLogger(__name__)

# Everything a committed entry may own, keyed off its fingerprint stem.
_ENTRY_SUFFIXES = (cas_store._META_SUFFIX, cas_store._PACKED_SUFFIX,
                   cas_store._STORE_SUFFIX)


@dataclasses.dataclass
class GCReport:
    """What one ``collect`` pass found (and, unless dry-run, did)."""

    dry_run: bool
    entries: int  # committed entries found
    bytes_total: int  # store footprint before (entries + garbage)
    bytes_after: int  # footprint after the pass (== bytes_total on dry-run)
    budget: int | None  # the byte budget enforced (None: orphans only)
    evicted: list  # fingerprints (to be) evicted, LRU first
    evicted_bytes: int
    orphans: list  # garbage paths (to be) removed
    orphan_bytes: int
    errors: int  # deletions that failed (logged)


def _path_size(path: str) -> int:
    try:
        if os.path.isdir(path):
            total = 0
            for root, _dirs, names in os.walk(path):
                for name in names:
                    try:
                        total += os.path.getsize(os.path.join(root, name))
                    except OSError:
                        pass
            return total
        return os.path.getsize(path)
    except OSError:
        return 0


def scan(directory: str):
    """Walk the store: ``(entries, mtimes, orphans)`` where ``entries``
    maps fingerprint -> total bytes (meta + payloads), ``mtimes`` maps
    fingerprint -> the meta file's mtime (the cold-entry ordering
    fallback), and ``orphans`` lists (path, bytes) of garbage — payloads
    without a committed meta, staging leftovers, and files that are not
    the CAS's at all (a foreign write into the cache volume is garbage to
    the budget even if this pass only reports it)."""
    entries: dict[str, int] = {}
    mtimes: dict[str, float] = {}
    orphans: list[tuple[str, int]] = []
    try:
        subdirs = sorted(os.listdir(directory))
    except OSError:
        return entries, mtimes, orphans
    for sub in subdirs:
        subpath = os.path.join(directory, sub)
        if not os.path.isdir(subpath):
            orphans.append((subpath, _path_size(subpath)))
            continue
        try:
            names = sorted(os.listdir(subpath))
        except OSError:
            continue
        metas = {n[: -len(cas_store._META_SUFFIX)]
                 for n in names if n.endswith(cas_store._META_SUFFIX)}
        for name in names:
            path = os.path.join(subpath, name)
            size = _path_size(path)
            if name.endswith(STAGING_SUFFIX):
                orphans.append((path, size))
                continue
            stem = suffix = None
            for sfx in _ENTRY_SUFFIXES:
                if name.endswith(sfx):
                    stem, suffix = name[: -len(sfx)], sfx
                    break
            if stem is None or not stem.startswith(sub):
                # Not a CAS filename shape (or filed under the wrong
                # prefix shard): foreign garbage.
                orphans.append((path, size))
                continue
            if stem not in metas:
                # A payload whose meta never committed (crash between
                # sidecar write and commit) or whose meta was evicted
                # mid-crash: invisible garbage.
                orphans.append((path, size))
                continue
            entries[stem] = entries.get(stem, 0) + size
            if suffix == cas_store._META_SUFFIX:
                try:
                    mtimes[stem] = os.path.getmtime(path)
                except OSError:
                    mtimes[stem] = 0.0
    return entries, mtimes, orphans


def eviction_order(entries: dict[str, int], mtimes: dict[str, float],
                   access: dict[str, float]) -> list[str]:
    """Fingerprints least-recently-used first: entries with no in-process
    access stamp lead (ordered by meta mtime among themselves — the only
    recency signal a cold entry has), stamped entries follow by stamp."""
    return sorted(
        entries,
        key=lambda fp: ((1, access[fp]) if fp in access
                        else (0, mtimes.get(fp, 0.0))),
    )


def collect(directory: str, budget: int | None, *, access=None,
            apply: bool = False, remove_entry=None,
            on_evict=None) -> GCReport:
    """One GC pass: sweep garbage, then evict LRU entries until the store
    fits ``budget`` bytes (None: garbage sweep only). ``apply=False`` (the
    ``gol gc`` default) reports what WOULD happen and touches nothing.

    ``access`` is the store's fingerprint -> perf_counter ledger (absent
    entries evict first); ``remove_entry(fp)`` deletes one entry honoring
    the meta-first order (defaults to a local implementation when no
    ``DiskCAS`` is supplying its own); ``on_evict(fp, bytes)`` observes
    each eviction (the counter feed)."""
    entries, mtimes, orphans = scan(directory)
    total = sum(entries.values()) + sum(b for _p, b in orphans)
    orphan_bytes = sum(b for _p, b in orphans)
    errors = 0
    if apply:
        for path, _size in orphans:
            try:
                if os.path.isdir(path):
                    shutil.rmtree(path)
                else:
                    os.unlink(path)
            except OSError as err:
                errors += 1
                logger.warning("cache GC: could not remove orphan %s: %s",
                               path, err)
    live = total - orphan_bytes
    evicted: list[str] = []
    evicted_bytes = 0
    if budget is not None:
        order = eviction_order(entries, mtimes, dict(access or {}))
        for fp in order:
            if live - evicted_bytes <= budget:
                break
            evicted.append(fp)
            evicted_bytes += entries[fp]
            if apply:
                if remove_entry is not None:
                    remove_entry(fp)
                else:
                    _remove_entry(directory, fp)
                if on_evict is not None:
                    on_evict(fp, entries[fp])
    after = total if not apply else (live - evicted_bytes)
    if apply and (orphans or evicted):
        logger.info(
            "cache GC in %s: removed %d orphan(s) (%d bytes), evicted %d "
            "entr(ies) (%d bytes); %d -> %d bytes%s",
            directory, len(orphans), orphan_bytes, len(evicted),
            evicted_bytes, total, after,
            f" (budget {budget})" if budget is not None else "")
    return GCReport(
        dry_run=not apply, entries=len(entries), bytes_total=total,
        bytes_after=after, budget=budget, evicted=evicted,
        evicted_bytes=evicted_bytes, orphans=[p for p, _b in orphans],
        orphan_bytes=orphan_bytes, errors=errors,
    )


def _remove_entry(directory: str, fp: str) -> None:
    """Delete one committed entry, meta FIRST (one unlink makes it
    invisible; leftovers are orphans the next sweep takes). The
    ``on_cas_evict`` fault boundary sits between the two phases."""
    subdir = os.path.join(directory, fp[:2])
    try:
        os.unlink(os.path.join(subdir, fp + cas_store._META_SUFFIX))
    except OSError:
        pass
    faults.on_cas_evict(fp)
    for sfx in (cas_store._PACKED_SUFFIX,):
        try:
            os.unlink(os.path.join(subdir, fp + sfx))
        except OSError:
            pass
    zarr = os.path.join(subdir, fp + cas_store._STORE_SUFFIX)
    if os.path.isdir(zarr):
        shutil.rmtree(zarr, ignore_errors=True)


__all__ = ["GCReport", "collect", "eviction_order", "scan"]
