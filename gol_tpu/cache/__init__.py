"""Content-addressed result cache: repeat traffic answered in O(1).

At millions-of-users traffic the same boards recur constantly (pattern
libraries, homework soups, benchmark loads), yet the engine's cost is
O(work) per submission regardless — the Casper framing (PAPERS.md): don't
move compute to data you already hold the answer for. This package keys
every finished result by a decomposition-independent fingerprint of the
*question* — ``fingerprint(board, convention, gen_limit, similarity
config)`` — and serves repeats from a tiered data plane:

1. **in-process LRU** (``store.MemoryLRU``) — bounded, O(1), dies with the
   process;
2. **on-disk CAS** (``store.DiskCAS``) — content-addressed files committed
   with the tree's atomic staging discipline (temp + fsync + ``os.replace``,
   as ``tune/plans.py``), CRC-gated on read: a torn or corrupted entry is
   loudly evicted and the engine re-runs — a poisoned cache can never serve
   bytes that fail their checksum. An optional TensorStore lane
   (``io/ts_store.py``) packs large exact-fit payloads 8x.
3. **fleet tier** — no new storage: the PR-8 router can rank workers by the
   *fingerprint* instead of the padding bucket (``gol fleet
   --cache-route``), so every repeat of a board lands on the one worker
   whose tiers already hold its answer — hot patterns are O(1) fleet-wide
   and spread across workers by fingerprint.

Durability contract: the cache is an **accelerator, never a source of
truth**. A cache hit is journaled as a normal DONE record (exactly-once and
replay semantics unchanged); losing any cache tier costs re-computation,
never correctness — journal replay always wins.
"""

from gol_tpu.cache.fingerprint import (  # noqa: F401
    board_digest,
    body_fingerprint,
    result_fingerprint,
)
from gol_tpu.cache.store import CacheEntry, DiskCAS, MemoryLRU  # noqa: F401
from gol_tpu.cache.tiered import ResultCache  # noqa: F401

__all__ = [
    "CacheEntry",
    "DiskCAS",
    "MemoryLRU",
    "ResultCache",
    "board_digest",
    "body_fingerprint",
    "result_fingerprint",
]
