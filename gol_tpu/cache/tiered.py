"""The tiered consult: memory LRU -> disk CAS, with obs counters.

``ResultCache`` is what the scheduler holds: one ``get`` walks the tiers
(promoting disk hits into memory), one ``put`` feeds both. Every outcome
rides the serving metrics registry so hit ratios merge fleet-wide exactly
like any other serving series:

- ``cache_hits_total`` (+ ``cache_hits_total_memory`` / ``_disk`` — the
  tier label) and ``cache_hit_bytes_total``;
- ``cache_misses_total``;
- ``cache_inflight_coalesced_total`` (fed by the scheduler's dedup);
- ``cache_stored_bytes_total``, ``cache_corrupt_evictions_total``,
  ``cache_store_errors_total``.

A failing CAS write or read NEVER raises into the serving path: the cost
of any cache defect is a log line, a counter, and a re-run.
"""

from __future__ import annotations

import logging

from gol_tpu.cache.store import CacheEntry, DiskCAS, MemoryLRU

logger = logging.getLogger(__name__)


class ResultCache:
    """Tiered fingerprint -> result cache (memory LRU over optional CAS)."""

    def __init__(
        self,
        memory_entries: int = 1024,
        cas_dir: str | None = None,
        metrics=None,
        payload: str = "packed",
        disk_bytes: int | None = None,
        guard=None,
    ):
        self.memory = MemoryLRU(memory_entries)
        self.metrics = metrics
        # The disk-pressure watchdog (resilience/diskguard.DiskGuard) or
        # None: under pressure the disk tier stops taking WRITES — the
        # memory tier and every read keep working, and recovery is
        # automatic when the guard's level clears.
        self.guard = guard
        self.cas = (
            DiskCAS(cas_dir, payload=payload, on_evict=self._on_evict,
                    max_bytes=disk_bytes, on_gc_evict=self._on_gc_evict)
            if cas_dir else None
        )

    def _inc(self, name: str, amount: float = 1) -> None:
        if self.metrics is not None:
            self.metrics.inc(name, amount)

    def _on_evict(self, fp: str, reason: str) -> None:
        self._inc("cache_corrupt_evictions_total")

    def _on_gc_evict(self, fp: str, nbytes: int) -> None:
        self._inc("cache_gc_evictions_total")
        self._inc("cache_gc_evicted_bytes_total", nbytes)

    def get(self, fp: str) -> tuple[CacheEntry, str] | None:
        """(entry, tier) on a hit — tier is ``memory`` or ``disk`` — else
        None (counted as a miss)."""
        entry = self.memory.get(fp)
        if entry is not None:
            self._hit(entry, "memory")
            return entry, "memory"
        if self.cas is not None:
            try:
                entry = self.cas.get(fp)
            except OSError as err:
                # Disk trouble on the read path degrades to a miss.
                logger.warning("cache CAS read failed for %s: %s: %s",
                               fp, type(err).__name__, err)
                entry = None
            if entry is not None:
                self.memory.put(fp, entry)  # promote: the hot set is hot
                self._hit(entry, "disk")
                return entry, "disk"
        self._inc("cache_misses_total")
        return None

    def _hit(self, entry: CacheEntry, tier: str) -> None:
        self._inc("cache_hits_total")
        self._inc("cache_hits_total_" + tier)
        self._inc("cache_hit_bytes_total", entry.grid.nbytes)

    def put(self, fp: str, entry: CacheEntry) -> None:
        """Feed both tiers; CAS failure is loud but non-fatal (ENOSPC on
        the cache volume must not fail jobs whose results are in hand).
        Under disk pressure (the watchdog's first degradation tier) the
        CAS write is SHED preemptively — the cache is the most
        re-creatable durable state on the partition, so it yields its
        bytes to the journal first."""
        self.memory.put(fp, entry)
        if self.cas is not None:
            if self.guard is not None and not self.guard.allow_cas_writes():
                self._inc("cas_writes_shed_total")
                return
            try:
                self.cas.put(fp, entry)
            except OSError as err:
                self._inc("cache_store_errors_total")
                logger.warning(
                    "cache CAS write failed for %s (results still served "
                    "from memory): %s: %s", fp, type(err).__name__, err,
                )
                return
        self._inc("cache_stored_bytes_total", entry.grid.nbytes)
