"""The cache's storage tiers: bounded in-process LRU + on-disk CAS.

``MemoryLRU`` answers the hot set in O(1) per lookup and dies with the
process. ``DiskCAS`` is the durable tier: one content-addressed file per
fingerprint, committed with the tree's staging discipline (temp file in the
final directory + fsync + ``os.replace`` — exactly ``tune/plans.py``), so a
crash mid-write leaves either no entry or a whole one, never a torn file
that parses. Reads are CRC-gated over the *decoded cells*: an entry whose
payload fails its checksum — disk corruption, a torn foreign write, a
digest collision — is evicted loudly and the caller re-runs the engine.
The CAS is an accelerator, never a source of truth: every entry is
reconstructible by re-running the (pure) simulation, so eviction is always
safe and recovery is never required.

Payload encodings (the meta JSON is always the commit point):

- ``packed`` (the default): the grid's wire frame (``io/wire.py`` — the
  packed binary format every serving hop speaks) in a ``.golp`` sidecar
  beside the meta, committed with the same staging discipline. ~8x
  smaller than text at any width, and a packed wire hit serves its
  payload words WITHOUT a decode→re-encode round trip (the sidecar bytes
  are already the response's word layout). Big-endian hosts fall back to
  ``text`` loudly, like the ts lane.
- ``text``: the grid rides inside the meta file in the tree's
  text-grid encoding — the same bytes the journal stores, one file per
  entry, zero extra dependencies. Always readable regardless of the
  configured payload (the migration lane: entries written before the
  packed default, and packed-lane write failures, read back forever).
- ``ts`` (optional): exact-fit payloads whose width packs (W % 32 == 0)
  write their bitpacked words to a TensorStore zarr beside the meta
  (``io/ts_store.py``). Anything the lane cannot take (unpackable width,
  TensorStore missing) falls back to ``text`` loudly.

On read the payload lane is chosen by the ENTRY's meta, not the store's
configured payload — every encoding reads back on every configuration,
and the CRC gate covers all of them identically (over the decoded
answer, so a poisoned payload evicts regardless of how it was stored).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import sys
import tempfile
import threading
import time
import zlib

import numpy as np

from gol_tpu.io import text_grid
from gol_tpu.resilience import STAGING_SUFFIX, fsio

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1
_META_SUFFIX = ".json"
_STORE_SUFFIX = ".zarr"
_PACKED_SUFFIX = ".golp"


@dataclasses.dataclass
class CacheEntry:
    """One cached answer (mirrors the engine's per-board result)."""

    grid: np.ndarray  # uint8 {0,1}, (height, width)
    generations: int
    exit_reason: str
    # The grid's packed wire words (io/wire.py row layout) when a lane had
    # them in hand — a packed engine readback on put, the packed sidecar
    # on get. Serving a packed wire response from this entry then skips
    # the re-pack. Never part of the canonical identity below: ``grid``
    # is the answer, words are a cached encoding of it.
    words: np.ndarray | None = None

    def canonical_bytes(self) -> bytes:
        """The whole decoded answer, canonically: row-major uint8 cell
        bytes plus the scalar fields. The CRC gate covers ALL of it — a
        poisoned ``generations`` or ``exit_reason`` is as wrong an answer
        as a poisoned cell."""
        scalars = f"|{int(self.generations)}|{self.exit_reason}".encode()
        return (
            np.ascontiguousarray(self.grid, dtype=np.uint8).tobytes()
            + scalars
        )


class MemoryLRU:
    """Bounded thread-safe LRU of fingerprint -> CacheEntry.

    ``max_bytes`` adds a grid-byte budget on top of the entry count (the
    tile memo's bound — 8192 entries of 256^2 tiles is half a GB, so an
    entry count alone is not a memory bound when entries are big); None
    keeps the PR-9 entries-only behavior byte-for-byte."""

    def __init__(self, max_entries: int = 1024, max_bytes: int | None = None):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self.evictions = 0
        self._bytes = 0
        self._lock = threading.Lock()
        self._entries: collections.OrderedDict[str, CacheEntry] = (
            collections.OrderedDict()
        )

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def grid_bytes(self) -> int:
        """Resident grid payload bytes (the budget ``max_bytes`` caps)."""
        with self._lock:
            return self._bytes

    def get(self, fp: str) -> CacheEntry | None:
        with self._lock:
            entry = self._entries.get(fp)
            if entry is not None:
                self._entries.move_to_end(fp)
            return entry

    def put(self, fp: str, entry: CacheEntry) -> None:
        with self._lock:
            old = self._entries.get(fp)
            if old is not None:
                self._bytes -= old.grid.nbytes
            self._entries[fp] = entry
            self._entries.move_to_end(fp)
            self._bytes += entry.grid.nbytes
            while len(self._entries) > self.max_entries or (
                self.max_bytes is not None
                and self._bytes > self.max_bytes
                and len(self._entries) > 1
            ):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.grid.nbytes
                self.evictions += 1

    def pop(self, fp: str) -> None:
        with self._lock:
            entry = self._entries.pop(fp, None)
            if entry is not None:
                self._bytes -= entry.grid.nbytes


class DiskCAS:
    """Content-addressed on-disk store: one entry per fingerprint.

    Layout: ``<dir>/<fp[:2]>/<fp>.json`` (+ ``<fp>.zarr`` on the ts lane).
    Writes are idempotent by construction — the same fingerprint always
    encodes the same bytes, so concurrent/repeated puts race harmlessly to
    identical content. ``on_evict(fp, reason)`` fires when a read finds a
    torn/corrupt/mismatched entry (the caller's loud-evict counter).
    """

    def __init__(self, directory: str, payload: str = "packed", on_evict=None,
                 max_bytes: int | None = None, on_gc_evict=None,
                 clock=time.perf_counter):
        if payload not in ("packed", "text", "ts"):
            raise ValueError(
                f"payload must be 'packed', 'text' or 'ts', got {payload!r}"
            )
        if max_bytes is not None and max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        self.directory = directory
        self.payload = payload
        self.on_evict = on_evict
        # The byte budget (gol serve --cache-disk-bytes) + the atime-LRU
        # ledger behind it: perf_counter stamps per fingerprint, taken on
        # every get/put (the clock is injectable; the wall clock is banned
        # from this package). None = the PR-9 unbounded tier.
        self.max_bytes = max_bytes
        self.on_gc_evict = on_gc_evict  # (fp, bytes) per budget eviction
        self._clock = clock
        self._access: dict[str, float] = {}
        # Reentrant: a put-triggered GC pass holds it end to end (one pass
        # at a time) while its per-entry removals re-enter for the ledger.
        self._gc_lock = threading.RLock()
        self._usage: int | None = None  # lazy: first enforcement scans once
        os.makedirs(directory, exist_ok=True)

    # -- paths --------------------------------------------------------------

    def _subdir(self, fp: str) -> str:
        return os.path.join(self.directory, fp[:2])

    def meta_path(self, fp: str) -> str:
        return os.path.join(self._subdir(fp), fp + _META_SUFFIX)

    def store_path(self, fp: str) -> str:
        return os.path.join(self._subdir(fp), fp + _STORE_SUFFIX)

    def packed_path(self, fp: str) -> str:
        return os.path.join(self._subdir(fp), fp + _PACKED_SUFFIX)

    # -- writes -------------------------------------------------------------

    def put(self, fp: str, entry: CacheEntry) -> None:
        """Write one entry durably; the meta JSON commit is the atomic step
        (a crash mid-payload leaves no meta — invisible garbage, exactly
        the checkpoint manifests' write-ahead rule)."""
        height, width = (int(x) for x in entry.grid.shape)
        meta = {
            "schema": SCHEMA_VERSION,
            "fingerprint": fp,
            "generations": int(entry.generations),
            "exit_reason": str(entry.exit_reason),
            "height": height,
            "width": width,
            "crc": zlib.crc32(entry.canonical_bytes()),
        }
        subdir = self._subdir(fp)
        os.makedirs(subdir, exist_ok=True)
        if self.payload == "packed" and sys.byteorder == "little":
            try:
                self._write_packed(fp, entry)
                meta["payload"] = "packed"
            except Exception as err:  # noqa: BLE001 - degrade, never fail
                logger.warning(
                    "cache CAS: packed payload for %s failed (%s: %s); "
                    "falling back to text", fp, type(err).__name__, err,
                )
        if self.payload == "ts" and width % 32 == 0 \
                and sys.byteorder == "little":
            try:
                self._write_ts(fp, entry, width)
                meta["payload"] = "ts"
            except Exception as err:  # noqa: BLE001 - optional lane
                logger.warning(
                    "cache CAS: TensorStore payload for %s failed (%s: %s); "
                    "falling back to text", fp, type(err).__name__, err,
                )
        if "payload" not in meta:
            meta["payload"] = "text"
            meta["grid"] = text_grid.encode(entry.grid).decode("ascii")
        fd, tmp = tempfile.mkstemp(
            dir=subdir, prefix=fp + ".", suffix=STAGING_SUFFIX
        )
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as f:
                fsio.write_stream(
                    f, json.dumps(meta, separators=(",", ":")) + "\n",
                    "cache CAS meta",
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.meta_path(fp))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._note_put(fp)

    def _write_packed(self, fp: str, entry: CacheEntry) -> None:
        """The packed sidecar: one wire frame (io/wire.py), staged +
        fsynced + renamed like every durable file in the tree. The meta
        JSON written after it stays the commit point — a crash between
        the two leaves an invisible orphan sidecar, overwritten by the
        next idempotent put."""
        from gol_tpu.io import wire

        height, width = (int(x) for x in entry.grid.shape)
        if entry.words is not None:
            frame = wire.encode_frame(
                {}, words=entry.words, width=width, height=height
            )
        else:
            frame = wire.encode_frame({}, grid=entry.grid)
        subdir = self._subdir(fp)
        fd, tmp = tempfile.mkstemp(
            dir=subdir, prefix=fp + ".", suffix=STAGING_SUFFIX
        )
        try:
            with os.fdopen(fd, "wb") as f:
                fsio.write_stream(f, frame, "cache CAS payload")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.packed_path(fp))
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _read_packed(self, fp: str, width: int, height: int):
        """(grid, words) from the packed sidecar; any defect raises (the
        caller's evict-and-re-run gate)."""
        from gol_tpu.io import wire

        with open(self.packed_path(fp), "rb") as f:
            frame = wire.decode_frame(f.read())
        if (frame.width, frame.height) != (width, height):
            raise ValueError(
                f"packed payload geometry {frame.height}x{frame.width} "
                f"does not match meta {height}x{width}"
            )
        return frame.grid(), frame.words

    def _write_ts(self, fp: str, entry: CacheEntry, width: int) -> None:
        import jax.numpy as jnp

        from gol_tpu.io import bitpack, ts_store

        words = bitpack.pack_words(
            np.ascontiguousarray(entry.grid, dtype=np.uint8)
        )
        ts_store.write_words(self.store_path(fp), jnp.asarray(words), width)

    # -- reads --------------------------------------------------------------

    def get(self, fp: str) -> CacheEntry | None:
        """Read + verify one entry; any defect evicts it loudly and answers
        None (the engine re-runs — correctness never rests on the cache)."""
        path = self.meta_path(fp)
        try:
            with open(path, "r", encoding="utf-8") as f:
                meta = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as err:
            self._evict(fp, f"unreadable meta ({type(err).__name__}: {err})")
            return None
        try:
            if meta["schema"] != SCHEMA_VERSION:
                raise ValueError(f"schema {meta['schema']}")
            if meta["fingerprint"] != fp:
                raise ValueError(
                    f"fingerprint mismatch (stored {meta['fingerprint']!r})"
                )
            width, height = int(meta["width"]), int(meta["height"])
            words = None
            if meta["payload"] == "packed":
                grid, words = self._read_packed(fp, width, height)
            elif meta["payload"] == "ts":
                grid = self._read_ts(fp, width, height)
            else:
                grid = text_grid.decode(
                    meta["grid"].encode("ascii"), width, height
                )
            if grid.shape != (height, width):
                raise ValueError(f"payload shape {grid.shape}")
            entry = CacheEntry(
                grid=grid,
                generations=int(meta["generations"]),
                exit_reason=str(meta["exit_reason"]),
                words=words,
            )
            if zlib.crc32(entry.canonical_bytes()) != int(meta["crc"]):
                raise ValueError("payload CRC mismatch")
        except Exception as err:  # noqa: BLE001 - every defect = evict+rerun
            self._evict(fp, f"{type(err).__name__}: {err}")
            return None
        with self._gc_lock:
            self._access[fp] = self._clock()  # the atime-LRU ledger
        return entry

    # -- the byte budget (cache/gc.py) --------------------------------------

    def access_ledger(self) -> dict[str, float]:
        """Fingerprint -> perf_counter last-access stamps (a copy)."""
        with self._gc_lock:
            return dict(self._access)

    def usage_bytes(self) -> int:
        """The store's on-disk footprint (entries + garbage), scanned once
        and tracked incrementally across puts — the ``cas_bytes`` gauge."""
        from gol_tpu.cache import gc as cas_gc

        with self._gc_lock:
            if self._usage is None:
                entries, _mtimes, orphans = cas_gc.scan(self.directory)
                self._usage = (sum(entries.values())
                               + sum(b for _p, b in orphans))
            return self._usage

    def _entry_bytes(self, fp: str) -> int:
        total = 0
        for path in (self.meta_path(fp), self.packed_path(fp)):
            try:
                total += os.path.getsize(path)
            except OSError:
                pass
        store = self.store_path(fp)
        if os.path.isdir(store):
            for root, _dirs, names in os.walk(store):
                for name in names:
                    try:
                        total += os.path.getsize(os.path.join(root, name))
                    except OSError:
                        pass
        return total

    def _note_put(self, fp: str) -> None:
        """Post-commit accounting: stamp the ledger, bump the running
        usage (a re-put of an existing entry overcounts here — harmless,
        the next GC scan recomputes exactly), enforce the budget."""
        with self._gc_lock:
            self._access[fp] = self._clock()
            if self._usage is not None:
                self._usage += self._entry_bytes(fp)
        if self.max_bytes is not None:
            over = self.usage_bytes() > self.max_bytes
            if over:
                self.gc(apply=True)

    def gc(self, budget: int | None = -1, apply: bool = False):
        """One GC pass over this store (cache/gc.collect): sweep orphans,
        evict LRU entries to ``budget`` bytes (-1: the store's own
        ``max_bytes``). Returns the GCReport; ``apply=False`` is dry-run."""
        from gol_tpu.cache import gc as cas_gc

        if budget == -1:
            budget = self.max_bytes
        with self._gc_lock:
            report = cas_gc.collect(
                self.directory, budget, access=self.access_ledger(),
                apply=apply, remove_entry=self.remove,
                on_evict=self.on_gc_evict,
            )
            if apply:
                self._usage = report.bytes_after
                for fp in report.evicted:
                    self._access.pop(fp, None)
        return report

    def remove(self, fp: str) -> None:
        """Delete one entry (eviction, not corruption): meta first — the
        single unlink that makes it invisible — then payloads; leftovers
        of a crash in between are orphans the next sweep collects."""
        from gol_tpu.cache import gc as cas_gc

        cas_gc._remove_entry(self.directory, fp)
        with self._gc_lock:
            self._access.pop(fp, None)

    def _read_ts(self, fp: str, width: int, height: int) -> np.ndarray:
        from gol_tpu.io import bitpack, ts_store

        words = np.asarray(ts_store.read_words(self.store_path(fp),
                                               width, height))
        return np.ascontiguousarray(bitpack.unpack_words(words, width))

    def _evict(self, fp: str, reason: str) -> None:
        logger.warning(
            "cache CAS: evicting corrupt entry %s (%s); the engine re-runs "
            "— a poisoned cache entry can never be served", fp, reason,
        )
        for path in (self.meta_path(fp), self.packed_path(fp)):
            try:
                os.unlink(path)
            except OSError:
                pass
        store = self.store_path(fp)
        if os.path.isdir(store):
            import shutil

            shutil.rmtree(store, ignore_errors=True)
        with self._gc_lock:
            self._access.pop(fp, None)
            self._usage = None  # rare: let the next enforcement rescan
        if self.on_evict is not None:
            self.on_evict(fp, reason)
