"""Result fingerprints: the cache key of one simulation question.

A result is reusable iff the *question* matches exactly: the board, the
loop-accounting convention, the generation limit, and the similarity-exit
configuration. Everything else — padding bucket, batch slot, kernel flavor,
pipeline depth, which worker ran it — is decomposition: the engine contract
(test-pinned since PR 2) makes the answer bit-identical across all of them,
so none of it may reach the key. Two properties follow:

- **decomposition independence** — the board digest reuses the checkpoint
  identity's positional limb math (``resilience/checkpoint.positional_
  digest``: each cell contributes ``value * mix(row, col)``, summed mod
  2^64), so the SAME board digests identically whether it arrives as a
  plain ndarray, a C- or F-ordered view, or a sharded jax array — and a
  job padded into different buckets under different tuned plans still hits.
- **collision hardening** — a 64-bit positional sum alone is too weak to
  gate byte-identity on, so the digest also folds in the CRC32 of the
  canonical row-major cell bytes. The CAS layer re-verifies a stored
  payload's CRC on every read regardless (a colliding OR corrupted entry
  is evicted loudly and the engine re-runs).

``body_fingerprint`` computes the same key from a raw ``POST /jobs`` JSON
body — jax-free on purpose, so the fleet router (which owns no device) can
rank workers by fingerprint without loading an engine.
"""

from __future__ import annotations

import zlib

import numpy as np

from gol_tpu.config import Convention, GameConfig
from gol_tpu.resilience.checkpoint import positional_digest, state_blocks

SCHEMA_VERSION = 1


def board_digest(board) -> str:
    """Decomposition-independent digest of a board's cells.

    ``board`` may be a numpy array or a (single-process) jax array — sharded
    forms digest block-by-block through the same positional math, so the
    digest never depends on how the caller happened to lay the cells out.
    """
    blocks = state_blocks(board)
    positional = positional_digest(blocks)
    # Canonical row-major bytes for the CRC fold: reassemble sharded forms.
    if len(blocks) == 1 and blocks[0][0][0] == 0 and blocks[0][0][2] == 0:
        cells = blocks[0][1]
    else:
        h, w = board.shape
        cells = np.zeros((h, w), np.uint8)
        for (r0, r1, c0, c1), piece in blocks:
            cells[r0:r1, c0:c1] = piece
    crc = zlib.crc32(np.ascontiguousarray(cells, dtype=np.uint8).tobytes())
    return f"{positional & ((1 << 64) - 1):016x}{crc:08x}"


def result_fingerprint(
    board,
    convention: str = Convention.C,
    gen_limit: int = GameConfig().gen_limit,
    check_similarity: bool = True,
    similarity_frequency: int = GameConfig().similarity_frequency,
) -> str:
    """The cache key: board digest + every config axis that changes the
    answer. Geometry is part of the key (two boards with equal digests but
    different declared extents must never alias); the schema version makes
    any future key-rule change a clean fleet-wide miss."""
    h, w = board.shape
    sim = f"s{int(similarity_frequency)}" if check_similarity else "nosim"
    return (
        f"v{SCHEMA_VERSION}-{board_digest(board)}-{h}x{w}"
        f"-{convention}-g{int(gen_limit)}-{sim}"
    )


def job_fingerprint(job) -> str:
    """``result_fingerprint`` of a serve ``Job`` (the scheduler's consult)."""
    return result_fingerprint(
        job.board,
        convention=job.convention,
        gen_limit=job.gen_limit,
        check_similarity=job.check_similarity,
        similarity_frequency=job.similarity_frequency,
    )


def body_fingerprint(body: dict) -> str:
    """The same key from a raw ``POST /jobs`` body (router-side, jax-free).

    Applies the worker's own field defaults (``Job`` / ``GameConfig``) so
    router and worker derive identical keys for identical submissions.
    Raises ``ValueError``/``TypeError``/``KeyError`` on bodies too
    malformed to key — callers fall back to bucket routing (the worker's
    full validation still answers the client).
    """
    from gol_tpu.io import text_grid

    width, height = int(body["width"]), int(body["height"])
    if width <= 0 or height <= 0:
        raise ValueError(f"dimensions must be positive, got {height}x{width}")
    check = body.get("check_similarity", True)
    if not isinstance(check, bool):
        raise TypeError(
            f"check_similarity must be a JSON boolean, got "
            f"{type(check).__name__}"
        )
    board = text_grid.decode(
        str(body["cells"]).encode("ascii"), width, height
    )
    return result_fingerprint(
        board,
        convention=str(body.get("convention", Convention.C)),
        gen_limit=int(body.get("gen_limit", GameConfig().gen_limit)),
        check_similarity=check,
        similarity_frequency=int(
            body.get("similarity_frequency", GameConfig().similarity_frequency)
        ),
    )


def packed_body_fingerprint(raw: bytes) -> str:
    """A routing key from a raw PACKED ``POST /jobs`` body — WITHOUT
    unpacking the payload.

    The packed lane of ``body_fingerprint``: the router's ``--cache-route``
    needs a deterministic per-(board, config) label to rank workers by, and
    the whole point of the packed format is that the router never decodes
    boards — so the board's contribution is the frame's own payload CRC +
    byte length (read from the header and the body size; the words are a
    deterministic function of the cells, so every packed resend of a board
    keys identically) instead of the cell-level positional digest.

    The key is therefore format-scoped (``v1p-`` prefix): a board submitted
    packed and the SAME board submitted as text may rank onto different
    workers — a one-time locality miss, never a correctness issue, since
    the worker-side cache fingerprints the DECODED board identically for
    both formats. Raises ``ValueError`` (via ``wire.WireError``) on frames
    too malformed to key — callers fall back to bucket routing.
    """
    from gol_tpu.io import wire

    width, height, meta = wire.peek(raw)
    if width <= 0 or height <= 0:
        raise ValueError(f"dimensions must be positive, got {height}x{width}")
    check = meta.get("check_similarity", True)
    if not isinstance(check, bool):
        raise TypeError(
            f"check_similarity must be a JSON boolean, got "
            f"{type(check).__name__}"
        )
    crc = wire.payload_crc(raw)
    sim = (
        f"s{int(meta.get('similarity_frequency', GameConfig().similarity_frequency))}"
        if check else "nosim"
    )
    # The board's contribution is the payload CRC alone: the payload LENGTH
    # is already pinned by the height/width axes below, and folding the
    # frame length would smuggle meta-only fields (priority, deadline_s —
    # QoS, which body_fingerprint pins OUT of the key) into the routing
    # key, re-routing exactly the repeat traffic --cache-route exists for.
    return (
        f"v{SCHEMA_VERSION}p-{crc:08x}-{height}x{width}"
        f"-{meta.get('convention', Convention.C)}"
        f"-g{int(meta.get('gen_limit', GameConfig().gen_limit))}-{sim}"
    )
