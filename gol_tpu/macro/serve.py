"""Macro jobs on the serving stack.

A macro job is a sparse job with ``"macro": true`` in its submitted (and
journaled) spec: same ``rle`` + universe extents contract, same
``batcher.SPARSE_KERNEL`` bucket and scheduler lanes — the flag only
changes WHICH engine ``sparse.serve.run_batch`` hands the board to. The
results are byte-identical to the sparse lane's (that is the macro
engine's contract), so the flag is an execution hint, not a semantic
axis: replaying a journal with the flag flipped would produce the same
answer, only slower or faster.

The memo is process-global like the sparse tile memo, but keyed per leaf
size (one hash-consed ``NodeStore`` + ``MacroMemo`` per tile edge):
node identity is only meaningful within one store, and jobs with
different tiles cannot share trees. Mounting a CAS directory makes the
content tier a cross-restart, cross-job knowledge base — every deep run
warms every later one.
"""

from __future__ import annotations

import logging

from gol_tpu.macro.advance import MacroMemo
from gol_tpu.macro.engine import simulate_macro
from gol_tpu.macro.node import NodeStore
from gol_tpu.obs import trace as obs_trace

logger = logging.getLogger(__name__)

_MEMOS: dict[int, MacroMemo] = {}
_MEMO_ENTRIES = 8192
_CAS_DIR: str | None = None


def memo(tile: int) -> MacroMemo:
    """The worker-wide macro memo for one leaf size (built on first
    use)."""
    m = _MEMOS.get(tile)
    if m is None:
        m = MacroMemo(NodeStore(tile), entries=_MEMO_ENTRIES,
                      cas_dir=_CAS_DIR)
        _MEMOS[tile] = m
    return m


def configure(entries: int | None = None, cas_dir: str | None = None) -> None:
    """Reset the worker-wide memos (tests, and servers mounting a CAS
    tier beside their journal partition)."""
    global _MEMO_ENTRIES, _CAS_DIR
    _MEMO_ENTRIES = entries or 8192
    _CAS_DIR = cas_dir
    _MEMOS.clear()


def run_job(job):
    """Run one macro job to completion (pure function of the journaled
    spec — safe to re-run on retry, and the memo makes the re-run
    cheap)."""
    from gol_tpu.serve.jobs import JobResult
    from gol_tpu.sparse.serve import board_for

    board = board_for(job)
    with obs_trace.span("macro.job", job=job.id,
                        universe=f"{job.height}x{job.width}",
                        tile=job.tile):
        result = simulate_macro(board, job.config, memo(job.tile))
    return JobResult(
        grid=None,
        generations=result.generations,
        exit_reason=result.exit_reason,
        rle=result.board.to_rle(),
        population=result.board.population(),
        universe=(job.height, job.width),
        tiles_simulated=result.stats.leaf_cases,
        cell_updates=result.stats.leaf_gen_steps * (2 * job.tile) ** 2,
        occupancy=result.board.occupancy(),
    )
