"""Hashlife lane: hash-consed macrocell engine for astronomically deep
time.

Where every other lane is O(generations), this one memoizes the time
axis itself: a hash-consed quadtree over the sparse lane's tiles
(``node``), a content-addressed centered-advance memo whose leaf base
cases batch through the compiled tile runners (``advance``), and a
superstep driver that reaches arbitrary generation counts — early-exit
parity included — in O(log) guarded jumps (``engine``).
"""

from gol_tpu.macro.advance import MacroMemo, MacroStats, advance
from gol_tpu.macro.engine import (
    MACRO_AUTO_GENS,
    MacroPlaneError,
    MacroResult,
    advance_universe,
    auto_macro,
    simulate_macro,
)
from gol_tpu.macro.node import MacroNode, MacroUniverse, NodeStore

__all__ = [
    "MACRO_AUTO_GENS",
    "MacroMemo",
    "MacroNode",
    "MacroPlaneError",
    "MacroResult",
    "MacroStats",
    "MacroUniverse",
    "NodeStore",
    "advance",
    "advance_universe",
    "auto_macro",
    "simulate_macro",
]
