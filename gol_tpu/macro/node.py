"""Hash-consed macrocell quadtree over the sparse lane's tile index.

The time axis of the sparse engine's space-elision argument: a node is a
``tile * 2^level``-square region of the universe, a leaf (level 0) is ONE
sparse tile, and every node is **interned** — two stamps of the same
subtree anywhere on the board (or in any two jobs on the same process)
are one Python object. Identity therefore means cell-equality, which is
what makes the macrocell advance memo (gol_tpu/macro/advance.py) a dict
lookup instead of a byte comparison.

Interning keys are decomposition-independent by construction: a leaf is
keyed by ``cache/fingerprint.board_digest`` of its cells (the result
cache's positional limb math + CRC fold — the same identity the
checkpoint and result-cache layers trust), and an internal node by the
identities of its four children — so HOW a board was assembled (dense
split, RLE stamp, advance result, CAS reload) never changes which node
it is.

Boards are built from and flattened back to ``sparse.SparseBoard``:
leaves ARE board tiles, aligned to the board's tile grid, so the two
engines exchange state without a dense canvas ever existing.

Numpy-only on purpose (no jax import): trees are built by the CLI and
serve admission paths before any engine loads; the device work happens
in advance.py through the existing compiled tile runners.
"""

from __future__ import annotations

import numpy as np

from gol_tpu.cache.fingerprint import board_digest
from gol_tpu.sparse.board import MIN_TILE, SparseBoard


class MacroNode:
    """One canonical quadtree node (never constructed directly — always
    through a ``NodeStore``, which is what makes identity meaningful).

    ``level`` 0 is a leaf holding a read-only ``(leaf, leaf)`` uint8 cell
    array; level ``m`` holds four level ``m-1`` children (nw, ne, sw, se)
    and spans ``leaf * 2^m`` cells. ``population`` is the live-cell count
    of the whole subtree (O(1) — summed once at intern time)."""

    __slots__ = ("level", "population", "cells", "nw", "ne", "sw", "se",
                 "_digest", "_bbox")

    def __init__(self, level, population, cells=None,
                 nw=None, ne=None, sw=None, se=None):
        self.level = level
        self.population = population
        self.cells = cells
        self.nw = nw
        self.ne = ne
        self.sw = sw
        self.se = se
        self._digest = None
        self._bbox = -1  # unset marker (None is a real value: empty)

    def size(self, leaf: int) -> int:
        """Cell edge of the region this node spans."""
        return leaf << self.level

    def to_dense(self, leaf: int) -> np.ndarray:
        """The node's cells as one dense array (CAS payloads and digests
        — callers gate the size; flattening to a board walks leaves
        instead)."""
        if self.level == 0:
            return self.cells
        half = self.size(leaf) // 2
        out = np.zeros((half * 2, half * 2), np.uint8)
        out[:half, :half] = self.nw.to_dense(leaf)
        out[:half, half:] = self.ne.to_dense(leaf)
        out[half:, :half] = self.sw.to_dense(leaf)
        out[half:, half:] = self.se.to_dense(leaf)
        return out

    def digest(self, leaf: int) -> str:
        """Content digest of the node's cells (cached — interning makes
        the cache exact: one node, one digest)."""
        if self._digest is None:
            self._digest = board_digest(
                np.ascontiguousarray(self.to_dense(leaf))
            )
        return self._digest

    def bbox(self, leaf: int):
        """Live bounding box in node-local cell coords:
        ``(min_row, min_col, max_row, max_col)`` inclusive, or None when
        the subtree is empty. Cached per node (interning shares it)."""
        if self._bbox != -1:
            return self._bbox
        if self.population == 0:
            self._bbox = None
            return None
        if self.level == 0:
            rows, cols = np.nonzero(self.cells)
            self._bbox = (int(rows.min()), int(cols.min()),
                          int(rows.max()), int(cols.max()))
            return self._bbox
        half = self.size(leaf) // 2
        lo_r = lo_c = None
        hi_r = hi_c = None
        for child, dr, dc in ((self.nw, 0, 0), (self.ne, 0, half),
                              (self.sw, half, 0), (self.se, half, half)):
            b = child.bbox(leaf)
            if b is None:
                continue
            r0, c0, r1, c1 = b[0] + dr, b[1] + dc, b[2] + dr, b[3] + dc
            lo_r = r0 if lo_r is None else min(lo_r, r0)
            lo_c = c0 if lo_c is None else min(lo_c, c0)
            hi_r = r1 if hi_r is None else max(hi_r, r1)
            hi_c = c1 if hi_c is None else max(hi_c, c1)
        self._bbox = (lo_r, lo_c, hi_r, hi_c)
        return self._bbox

    def __repr__(self) -> str:
        return (f"MacroNode(level={self.level}, "
                f"population={self.population})")


class NodeStore:
    """The intern tables: content -> THE node for that content.

    One store per process in serving (gol_tpu/macro/serve.py) so
    identical subtrees across jobs share nodes; tests build their own.
    ``leaf_size`` is the board tile edge — it must be even (the leaf
    base-case advance in advance.py needs an ``leaf/2``-step margin) and
    every board entering this store must agree on it."""

    def __init__(self, leaf_size: int):
        if leaf_size < MIN_TILE:
            raise ValueError(
                f"macro leaf size must be >= {MIN_TILE}, got {leaf_size}"
            )
        if leaf_size % 2:
            raise ValueError(
                f"macro leaf size must be even (the leaf advance needs an "
                f"leaf/2 halo margin), got {leaf_size}"
            )
        self.leaf_size = leaf_size
        self._leaves: dict[str, MacroNode] = {}
        self._nodes: dict[tuple, MacroNode] = {}
        self._empty: dict[int, MacroNode] = {}
        self._zero = np.zeros((leaf_size, leaf_size), np.uint8)
        self._zero.setflags(write=False)

    # -- interning ---------------------------------------------------------

    def leaf(self, cells: np.ndarray) -> MacroNode:
        """THE leaf for these cells (content-keyed via board_digest, the
        same collision-hardened identity the result cache gates on)."""
        cells = np.ascontiguousarray(np.asarray(cells, dtype=np.uint8))
        if cells.shape != (self.leaf_size, self.leaf_size):
            raise ValueError(
                f"leaf cells must be {self.leaf_size}^2, got {cells.shape}"
            )
        population = int(cells.sum())
        if population == 0:
            return self.empty(0)
        key = board_digest(cells)
        node = self._leaves.get(key)
        if node is None:
            cells = cells.copy()
            cells.setflags(write=False)
            node = MacroNode(0, population, cells=cells)
            node._digest = key
            self._leaves[key] = node
        return node

    def node(self, nw: MacroNode, ne: MacroNode, sw: MacroNode,
             se: MacroNode) -> MacroNode:
        """THE node with these four children (identity-keyed: children
        are already canonical, so object ids ARE content ids)."""
        level = nw.level + 1
        if not (ne.level == sw.level == se.level == nw.level):
            raise ValueError("macro node children must share a level")
        population = (nw.population + ne.population
                      + sw.population + se.population)
        if population == 0:
            return self.empty(level)
        key = (level, id(nw), id(ne), id(sw), id(se))
        node = self._nodes.get(key)
        if node is None:
            node = MacroNode(level, population, nw=nw, ne=ne, sw=sw, se=se)
            self._nodes[key] = node
        return node

    def empty(self, level: int) -> MacroNode:
        """THE all-dead node of a level (one per level per store)."""
        node = self._empty.get(level)
        if node is None:
            if level == 0:
                node = MacroNode(0, 0, cells=self._zero)
            else:
                child = self.empty(level - 1)
                node = MacroNode(level, 0, nw=child, ne=child,
                                 sw=child, se=child)
            self._empty[level] = node
        return node

    def interned_nodes(self) -> int:
        """Distinct nodes alive in the tables (obs gauge fodder)."""
        return len(self._leaves) + len(self._nodes) + len(self._empty)

    def from_dense(self, grid: np.ndarray) -> MacroNode:
        """Intern a dense ``(leaf * 2^m)``-square array as a node — the
        CAS-reload path (advance results come back as cell payloads and
        must land on the SAME canonical nodes a live process holds)."""
        grid = np.asarray(grid, dtype=np.uint8)
        edge = grid.shape[0]
        if grid.shape != (edge, edge) or edge % self.leaf_size:
            raise ValueError(
                f"dense macro region must be a square multiple of the "
                f"{self.leaf_size}-cell leaf, got {grid.shape}"
            )
        if edge == self.leaf_size:
            return self.leaf(grid)
        half = edge // 2
        return self.node(
            self.from_dense(grid[:half, :half]),
            self.from_dense(grid[:half, half:]),
            self.from_dense(grid[half:, :half]),
            self.from_dense(grid[half:, half:]),
        )

    # -- centered subnode (the t=0 "advance") ------------------------------

    def centered(self, node: MacroNode) -> MacroNode:
        """The center half-size subnode — what a 0-step advance returns,
        and one leg of the stillness test (advance-by-1 == centered iff
        the window is a fixed point)."""
        if node.level < 1:
            raise ValueError("centered needs a level >= 1 node")
        if node.level == 1:
            half = self.leaf_size // 2
            cells = np.zeros((self.leaf_size, self.leaf_size), np.uint8)
            cells[:half, :half] = node.nw.cells[half:, half:]
            cells[:half, half:] = node.ne.cells[half:, :half]
            cells[half:, :half] = node.sw.cells[:half, half:]
            cells[half:, half:] = node.se.cells[:half, :half]
            return self.leaf(cells)
        return self.node(node.nw.se, node.ne.sw, node.sw.ne, node.se.nw)


class MacroUniverse:
    """A sparse board held as a canonical quadtree plus its placement.

    ``root`` spans tiles ``[oy, oy + 2^level) x [ox, ox + 2^level)`` of
    the board's tile grid (offsets may go negative after padding
    expansion — the tree is plane-semantics scratch space; only the
    flatten clips back to the universe). Instances are treated as
    immutable by the engine: every advance returns a new universe
    sharing the store."""

    def __init__(self, store: NodeStore, height: int, width: int,
                 root: MacroNode, oy: int, ox: int):
        self.store = store
        self.height = height
        self.width = width
        self.root = root
        self.oy = oy
        self.ox = ox

    @property
    def tile(self) -> int:
        return self.store.leaf_size

    @classmethod
    def from_board(cls, store: NodeStore, board: SparseBoard
                   ) -> "MacroUniverse":
        """Build the canonical tree over a board's live-tile bounding box
        (geometry-first: dead regions outside the bbox are never
        visited — they become THE canonical empty nodes)."""
        if board.tile != store.leaf_size:
            raise ValueError(
                f"board tile {board.tile} != store leaf {store.leaf_size}"
            )
        if not board.tiles:
            return cls(store, board.height, board.width, store.empty(1), 0, 0)
        tys = [ty for ty, _ in board.tiles]
        txs = [tx for _, tx in board.tiles]
        oy, ox = min(tys), min(txs)
        span = max(max(tys) - oy, max(txs) - ox) + 1
        level = 1
        while (1 << level) < span:
            level += 1
        live = board.tiles

        def build(lv: int, ty: int, tx: int) -> MacroNode:
            if lv == 0:
                arr = live.get((ty, tx))
                return store.leaf(arr) if arr is not None else store.empty(0)
            h = 1 << (lv - 1)
            if not any(ty <= y < ty + (1 << lv) and tx <= x < tx + (1 << lv)
                       for y, x in live):
                return store.empty(lv)
            return store.node(
                build(lv - 1, ty, tx), build(lv - 1, ty, tx + h),
                build(lv - 1, ty + h, tx), build(lv - 1, ty + h, tx + h),
            )

        return cls(store, board.height, board.width,
                   build(level, oy, ox), oy, ox)

    def population(self) -> int:
        """O(1) — read off the root, never flattened (deep-time census
        queries read this at generation 10^9 without materializing)."""
        return self.root.population

    def bbox_cells(self):
        """Live bbox in universe cell coords (inclusive), None if empty."""
        b = self.root.bbox(self.tile)
        if b is None:
            return None
        t = self.tile
        return (b[0] + self.oy * t, b[1] + self.ox * t,
                b[2] + self.oy * t, b[3] + self.ox * t)

    def expanded(self) -> "MacroUniverse":
        """One ring of empty padding: a new root one level up whose
        CENTER is this root (the auto-expanding padding of the superstep
        driver — advance returns the center half, so capacity must be
        grown before each jump, never during)."""
        s, r = self.store, self.root
        if r.level < 1:
            raise ValueError("cannot expand a leaf root")
        e = s.empty(r.level - 1)
        root = s.node(
            s.node(e, e, e, r.nw), s.node(e, e, r.ne, e),
            s.node(e, r.sw, e, e), s.node(r.se, e, e, e),
        )
        shift = 1 << (r.level - 1)
        return MacroUniverse(s, self.height, self.width, root,
                             self.oy - shift, self.ox - shift)

    def to_board(self) -> SparseBoard:
        """Flatten back to the sparse lane's occupancy index (live leaves
        only; tiles land on the same grid they came from)."""
        board = SparseBoard(self.height, self.width, self.tile)

        def walk(node: MacroNode, ty: int, tx: int) -> None:
            if node.population == 0:
                return
            if node.level == 0:
                board.set_tile((ty, tx), node.cells.copy())
                return
            h = 1 << (node.level - 1)
            walk(node.nw, ty, tx)
            walk(node.ne, ty, tx + h)
            walk(node.sw, ty + h, tx)
            walk(node.se, ty + h, tx + h)

        walk(self.root, self.oy, self.ox)
        return board
