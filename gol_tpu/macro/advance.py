"""Memoized centered advance: the macrocell RESULT, content-addressed.

``advance(memo, node, t)`` returns the center half-size node exactly
``t`` generations later, for any ``0 <= t <= size/4`` — the light-cone
bound: the center's dependence region grown by ``t`` stays inside the
node, so the answer is a pure function of the node's own cells and
memoizable under content identity alone. Non-power-of-two ``t`` rides
the standard split ``t1 = min(t, size/8), t2 = t - t1`` through the
classic 9-subnode recursion, so the superstep driver never needs a
power-of-two schedule to stay exact.

Two memo tiers, the ``sparse/memo.py`` shape verbatim:

- **object tier** — ``(node, t) -> result`` keyed by node *identity*,
  which hash-consing (node.py) makes equivalent to content identity.
  This is the classic hashlife memo: repeated space AND time collapse
  to dict hits.
- **content tier** — ``MemoryLRU`` over an optional CRC-verified
  ``DiskCAS`` (cache/store.py, text payload), keyed by the node's
  ``board_digest`` + ``t`` + leaf size for nodes up to a byte cap. The
  CAS is the cross-restart, cross-job knowledge base: a restarted
  worker re-interns the same tree and hits the results a dead process
  paid for, and ``gol gc`` budgets the directory like every other CAS.
  Bigger nodes are cheap to recompute from their cached halves, so
  capping the payload size keeps entries small without losing the win.

Leaf base cases (level-1 nodes, one ``2*leaf``-square window) batch
through the existing compiled tile runner
(``engine.make_tile_step_runner``, padded up ``batcher.pad_batch``'s
ladder): the device does every stencil step, the host does only hashing
— the same division of labor as the sparse engine, one level up.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from gol_tpu.cache.store import CacheEntry, DiskCAS, MemoryLRU
from gol_tpu.macro.node import MacroNode, NodeStore
from gol_tpu.obs import registry as obs_registry

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1

_EXIT_TAG = "macro"  # exit_reason marker: this entry is a macro advance

# Content-tier byte cap, as a node cell edge: results of nodes above
# this never enter the LRU/CAS tiers (a 2048^2 operand's result is a
# 1 MB payload — past that, entries crowd out the small results that
# actually repeat, and a big node's advance is 13 memoized sub-advances
# anyway). The object tier has no cap — it holds references, not copies.
CAS_MAX_EDGE = 2048

# Memory-tier budget (the sparse memo's default, same reasoning: the
# byte cap, not the entry count, is what bounds a worker's footprint).
DEFAULT_MEMO_BYTES = 128 << 20
DEFAULT_MEMO_ENTRIES = 8192


@dataclasses.dataclass
class MacroStats:
    """Work accounting of one macro run (SparseStats' deep-time analog:
    achieved work is memoized advances and leaf kernel steps, not
    generations — the whole point is generations >> work)."""

    generations: int = 0
    supersteps: int = 0  # top-level jumps the driver decomposed into
    node_hits: int = 0  # object-tier memo hits
    node_misses: int = 0
    cas_hits: int = 0  # content-tier hits (memory LRU or disk CAS)
    leaf_cases: int = 0  # level-1 base cases computed on device
    leaf_gen_steps: int = 0  # single-generation tile steps dispatched


class MacroMemo:
    """Tiered advance memo bound to one ``NodeStore``.

    The store binding is load-bearing: content-tier hits must land on
    the SAME canonical nodes the live process interns, so payloads are
    re-interned through ``store.from_dense`` on the way in."""

    def __init__(self, store: NodeStore,
                 entries: int = DEFAULT_MEMO_ENTRIES,
                 cas_dir: str | None = None,
                 max_bytes: int = DEFAULT_MEMO_BYTES):
        self.store = store
        self.results: dict[tuple, MacroNode] = {}  # (node, t) -> result
        self.memory = MemoryLRU(entries, max_bytes=max_bytes)
        self.cas = (
            DiskCAS(cas_dir, payload="text", on_evict=self._on_evict)
            if cas_dir else None
        )

    def _on_evict(self, fp: str, reason: str) -> None:
        obs_registry.default().inc("macro_memo_corrupt_evictions_total")

    def key(self, node: MacroNode, t: int) -> str:
        """The content-tier fingerprint of one advance question."""
        leaf = self.store.leaf_size
        return f"m{SCHEMA_VERSION}-{node.digest(leaf)}-{t}-{leaf}"

    def _content_eligible(self, node: MacroNode) -> bool:
        return node.size(self.store.leaf_size) <= CAS_MAX_EDGE

    def get(self, node: MacroNode, t: int,
            stats: MacroStats | None = None) -> MacroNode | None:
        reg = obs_registry.default()
        result = self.results.get((node, t))
        if result is not None:
            reg.inc("macro_node_hits_total")
            if stats:
                stats.node_hits += 1
            return result
        reg.inc("macro_node_misses_total")
        if stats:
            stats.node_misses += 1
        if not self._content_eligible(node):
            return None
        key = self.key(node, t)
        entry = self.memory.get(key)
        if entry is None and self.cas is not None:
            try:
                entry = self.cas.get(key)
            except OSError as err:
                logger.warning("macro memo CAS read failed for %s: %s: %s",
                               key, type(err).__name__, err)
                entry = None
            if entry is not None:
                self.memory.put(key, entry)
        if entry is None:
            reg.inc("macro_memo_misses_total")
            return None
        reg.inc("macro_memo_hits_total")
        if stats:
            stats.cas_hits += 1
        result = self.store.from_dense(entry.grid)
        self.results[(node, t)] = result
        reg.set_gauge("macro_memo_bytes", self.memory.grid_bytes)
        return result

    def put(self, node: MacroNode, t: int, result: MacroNode) -> None:
        self.results[(node, t)] = result
        if not self._content_eligible(node):
            return
        entry = CacheEntry(
            grid=np.ascontiguousarray(
                result.to_dense(self.store.leaf_size)
            ),
            generations=t,
            exit_reason=_EXIT_TAG,
        )
        key = self.key(node, t)
        self.memory.put(key, entry)
        obs_registry.default().set_gauge(
            "macro_memo_bytes", self.memory.grid_bytes
        )
        if self.cas is not None:
            try:
                self.cas.put(key, entry)
            except OSError as err:
                logger.warning(
                    "macro memo CAS write failed for %s (memo still serves "
                    "from memory): %s: %s", key, type(err).__name__, err,
                )


def _sub9(store: NodeStore, n: MacroNode) -> list[list[MacroNode]]:
    """The nine overlapping half-size subnodes of the classic recursion
    (corners, edge-centers, center), each one level down."""
    nw, ne, sw, se = n.nw, n.ne, n.sw, n.se
    return [
        [nw,
         store.node(nw.ne, ne.nw, nw.se, ne.sw),
         ne],
        [store.node(nw.sw, nw.se, sw.nw, sw.ne),
         store.node(nw.se, ne.sw, sw.ne, se.nw),
         store.node(ne.sw, ne.se, se.nw, se.ne)],
        [sw,
         store.node(sw.ne, se.nw, sw.se, se.sw),
         se],
    ]


def _combine4(store: NodeStore, r) -> list[MacroNode]:
    """Stitch the 9 sub-results (which tile the center 3/4 region) into
    the four overlapping half-size windows the second half-jump runs on."""
    return [
        store.node(r[0][0], r[0][1], r[1][0], r[1][1]),
        store.node(r[0][1], r[0][2], r[1][1], r[1][2]),
        store.node(r[1][0], r[1][1], r[2][0], r[2][1]),
        store.node(r[1][1], r[1][2], r[2][1], r[2][2]),
    ]


def _batch_leaf_advance(memo: MacroMemo, nodes: list[MacroNode], t: int,
                        stats: MacroStats | None = None
                        ) -> list[MacroNode]:
    """Advance level-1 nodes (one ``2*leaf`` window each) by ``t``
    generations on device, batched.

    ``t <= leaf/2`` — the zero-halo validity margin: the runner assumes
    a dead ring, so correctness erodes one cell per step from the window
    edge; the center ``leaf``-square stays exact for exactly leaf/2
    steps, which is the level-1 light-cone bound. Distinct uncached
    windows batch through one padded runner dispatch per generation
    (``batcher.pad_batch`` rungs — the same compiled-program ladder the
    sparse engine and the serve batcher ride)."""
    store = memo.store
    L = store.leaf_size
    if t > L // 2:
        raise ValueError(f"leaf advance capped at {L // 2} steps, got {t}")
    out: dict[int, MacroNode] = {}
    pending: list[MacroNode] = []
    seen: set[int] = set()
    for node in nodes:
        if id(node) in out or id(node) in seen:
            continue
        if node.population == 0:
            out[id(node)] = store.empty(0)
            continue
        if t == 0:
            result = memo.get(node, 0, stats)
            if result is None:
                result = store.centered(node)
                memo.put(node, 0, result)
            out[id(node)] = result
            continue
        result = memo.get(node, t, stats)
        if result is not None:
            out[id(node)] = result
        else:
            seen.add(id(node))
            pending.append(node)
    if pending:
        import jax
        import jax.numpy as jnp

        from gol_tpu import engine
        from gol_tpu.serve import batcher

        if stats:
            stats.leaf_cases += len(pending)
        half = L // 2
        for lo in range(0, len(pending), batcher.MAX_BATCH):
            chunk = pending[lo:lo + batcher.MAX_BATCH]
            rung = batcher.pad_batch(len(chunk))
            blocks = np.zeros((rung, 2 * L + 2, 2 * L + 2), np.uint8)
            for i, node in enumerate(chunk):
                blocks[i, 1:-1, 1:-1] = node.to_dense(L)
            runner = engine.make_tile_step_runner(2 * L, rung)
            for _ in range(t):
                interiors, _alive, _changed = runner(jnp.asarray(blocks))
                inner = np.asarray(jax.device_get(interiors),
                                   dtype=np.uint8)
                blocks = np.zeros_like(blocks)
                blocks[:, 1:-1, 1:-1] = inner
                if stats:
                    stats.leaf_gen_steps += len(chunk)
            for i, node in enumerate(chunk):
                leaf = store.leaf(
                    blocks[i, 1 + half:1 + half + L, 1 + half:1 + half + L]
                )
                memo.put(node, t, leaf)
                out[id(node)] = leaf
    return [out[id(node)] for node in nodes]


def _advance_level2(memo: MacroMemo, node: MacroNode, t: int,
                    stats: MacroStats | None) -> MacroNode:
    """The recursion floor: both half-jumps are level-1 base cases, so
    ALL device work in the whole tree funnels through the two batched
    calls here."""
    store = memo.store
    t1 = min(t, store.leaf_size // 2)
    t2 = t - t1
    subs = _sub9(store, node)
    flat = [n for row in subs for n in row]
    r = _batch_leaf_advance(memo, flat, t1, stats)
    grid = [r[0:3], r[3:6], r[6:9]]
    q = _combine4(store, grid)
    p = _batch_leaf_advance(memo, q, t2, stats)
    return store.node(p[0], p[1], p[2], p[3])


def advance(memo: MacroMemo, node: MacroNode, t: int,
            stats: MacroStats | None = None) -> MacroNode:
    """The centered ``t``-step result of a level >= 2 node,
    ``0 <= t <= size/4`` (``t = 0`` is the centered subnode — the
    geometric no-op the stillness test compares against)."""
    store = memo.store
    if node.level < 2:
        raise ValueError(
            f"advance needs a level >= 2 node, got level {node.level}"
        )
    cap = store.leaf_size << (node.level - 2)
    if not 0 <= t <= cap:
        raise ValueError(
            f"level-{node.level} advance capped at {cap} steps, got {t}"
        )
    if t == 0:
        return store.centered(node)
    if node.population == 0:
        return store.empty(node.level - 1)
    result = memo.get(node, t, stats)
    if result is not None:
        return result
    if node.level == 2:
        result = _advance_level2(memo, node, t, stats)
    else:
        half_cap = cap // 2
        t1 = min(t, half_cap)
        t2 = t - t1
        subs = _sub9(store, node)
        r = [[advance(memo, n, t1, stats) for n in row] for row in subs]
        q = _combine4(store, r)
        result = store.node(*(advance(memo, n, t2, stats) for n in q))
    memo.put(node, t, result)
    return result
