"""The macrocell superstep driver: arbitrary ``--gens`` in O(log) jumps.

Every other engine in the tree is O(generations) in time; this driver
decomposes an arbitrary generation count (non-powers-of-two included)
into exponential jumps through the memoized centered advance
(gol_tpu/macro/advance.py), with auto-expanding padding — each jump
returns the center half of its root, so capacity is grown (one ring of
THE canonical empty node, near-free under hash-consing) before every
jump.

**Plane vs torus.** The sparse and dense lanes are toroidal; macrocell
is plane-semantics. The two agree exactly as long as no live cell ever
enters the universe's outermost cell ring (a ring cell's neighborhood —
and influence — wraps). Before each jump of ``s`` generations the live
bounding box grown by ``s`` (the light-cone bound on growth) must stay
inside that ring; jumps shrink to fit, and when not even a single step
fits the driver raises ``MacroPlaneError`` with the fix (a larger
``--universe``, or the sparse lane, which wraps natively) instead of
silently diverging.

**Early-exit parity.** The sparse engine's per-generation loop exits on
emptiness and on the periodic similarity check, with convention-specific
accounting (sparse/engine._run_c/_run_cuda — the oracle contract). Both
predicates are *monotone* along a plane evolution — an empty board stays
empty, and a board equal to its predecessor is a fixed point forever —
so the exact first-empty / first-still generation is recovered by
bisection over memoized states (O(log^2) advances, mostly memo hits),
and the exit generation/reason/board reproduce the per-generation loop
byte-for-byte. Stillness itself is decided by node identity:
``advance(root, 1) is advance(root, 0)`` — hash-consing makes the
fixed-point test a pointer comparison.
"""

from __future__ import annotations

import dataclasses

from gol_tpu.config import Convention, DEFAULT_CONFIG, GameConfig
from gol_tpu.macro.advance import MacroMemo, MacroStats, advance
from gol_tpu.macro.node import MacroUniverse, NodeStore
from gol_tpu.obs import registry as obs_registry, trace as obs_trace
from gol_tpu.sparse.board import SparseBoard
from gol_tpu.sparse.engine import EXIT_EMPTY, EXIT_GEN_LIMIT, EXIT_SIMILAR

# Above this generation limit the CLI's auto lane prefers macrocell over
# the per-generation sparse loop (when the placement admits plane
# semantics for the whole run). The shipped default is deliberately
# conservative — macro pays tree-build + hashing overhead that a short
# run never amortizes; a plan-cached per-host value overrides it
# (tune.select.macro_auto_gens consults the plan store; this constant is
# the bundled-default/last-resort fallback).
MACRO_AUTO_GENS = 10_000


class MacroPlaneError(ValueError):
    """The run's live cells reached the universe edge ring, where torus
    and plane semantics diverge — the macro lane cannot proceed
    exactly."""


@dataclasses.dataclass
class MacroResult:
    """Final state of a macro run (the SparseResult analog — same
    board/generations/exit vocabulary, deep-time stats)."""

    board: SparseBoard
    generations: int
    exit_reason: str
    stats: MacroStats


def _prepared(u: MacroUniverse, t: int) -> MacroUniverse:
    """Expand until the root can answer a ``t``-step advance: level >= 2,
    ``t`` within the light-cone cap, and the live bbox grown by ``t``
    inside the root's CENTER half (the advance only returns the center)."""
    while u.root.level < 2:
        u = u.expanded()
    while True:
        cap = u.tile << (u.root.level - 2)
        ok = t <= cap
        if ok and u.root.population:
            b = u.bbox_cells()
            t_edge = u.tile
            q = 1 << (u.root.level - 2)
            r0 = (u.oy + q) * t_edge
            c0 = (u.ox + q) * t_edge
            r1 = (u.oy + 3 * q) * t_edge
            c1 = (u.ox + 3 * q) * t_edge
            ok = (b[0] - t >= r0 and b[1] - t >= c0
                  and b[2] + t < r1 and b[3] + t < c1)
        if ok:
            return u
        u = u.expanded()


def advance_universe(u: MacroUniverse, memo: MacroMemo, t: int,
                     stats: MacroStats | None = None) -> MacroUniverse:
    """One ``t``-generation jump of a whole universe (pads, advances,
    re-anchors the half-size result where the old center was)."""
    u = _prepared(u, t)
    root = advance(memo, u.root, t, stats)
    q = 1 << (u.root.level - 2)
    return MacroUniverse(u.store, u.height, u.width, root,
                         u.oy + q, u.ox + q)


def _safe_jump(u: MacroUniverse) -> int:
    """The largest jump whose light cone provably stays off the torus
    seam: bbox distance to the edge ring, from the current state."""
    b = u.bbox_cells()
    return min(b[0] - 1, b[1] - 1,
               u.height - 2 - b[2], u.width - 2 - b[3])


def _plane_error(u: MacroUniverse, g: int) -> MacroPlaneError:
    b = u.bbox_cells()
    return MacroPlaneError(
        f"macro engine: live cells reach the universe edge at generation "
        f"{g} (bbox rows {b[0]}..{b[2]}, cols {b[1]}..{b[3]} of "
        f"{u.height}x{u.width}) where toroidal wrap and plane semantics "
        f"diverge; enlarge --universe so the pattern keeps a margin, or "
        f"use --engine sparse (which wraps natively)"
    )


class _Run:
    """One simulation's state cache: generation -> universe, advanced
    lazily via guarded exponential jumps (power-of-two sized, so the
    bisections downstream re-ask mostly-memoized questions)."""

    def __init__(self, u0: MacroUniverse, memo: MacroMemo,
                 stats: MacroStats):
        self.states = {0: u0}
        self.memo = memo
        self.stats = stats

    def state_at(self, g: int) -> MacroUniverse:
        base = max(k for k in self.states if k <= g)
        u = self.states[base]
        while base < g:
            if u.root.population == 0:
                self.states[g] = u
                return u
            s = min(g - base, _safe_jump(u))
            if s < 1:
                raise _plane_error(u, base)
            s = 1 << (s.bit_length() - 1)  # largest power of two that fits
            with obs_trace.span("macro.advance", jump=s, generation=base):
                u = advance_universe(u, self.memo, s, self.stats)
            self.stats.supersteps += 1
            base += s
            self.states[base] = u
        return u

    def still_at(self, g: int) -> bool:
        """``board(g) == board(g-1)``, by node identity: both one-step
        and zero-step results are computed in the SAME padded window, so
        hash-consing turns board equality into ``is``."""
        u = self.state_at(g - 1)
        if u.root.population == 0:
            return True
        if _safe_jump(u) < 1:
            raise _plane_error(u, g - 1)
        u = _prepared(u, 1)
        one = advance(self.memo, u.root, 1, self.stats)
        zero = advance(self.memo, u.root, 0, self.stats)
        return one is zero


def _bisect_first(lo: int, hi: int, pred) -> int:
    """Smallest g in (lo, hi] with pred(g), given monotone pred,
    pred(hi) True and pred(lo) conceptually False."""
    while hi - lo > 1:
        mid = (lo + hi) // 2
        if pred(mid):
            hi = mid
        else:
            lo = mid
    return hi


def simulate_macro(
    board: SparseBoard,
    config: GameConfig = DEFAULT_CONFIG,
    memo: MacroMemo | None = None,
    checkpoints=(),
    on_checkpoint=None,
) -> MacroResult:
    """Run a full macro simulation, byte-identical to ``simulate_sparse``
    — cells, generation count, exit reason, all three exits, both
    conventions — wherever plane semantics hold (else MacroPlaneError).

    ``checkpoints`` is an iterable of generation numbers; for each one
    within the generation limit, ``on_checkpoint(gen, SparseBoard)`` is
    called with the exact state at that generation (the byte-gate hook,
    and the deep-time sampling API)."""
    if memo is None:
        memo = MacroMemo(NodeStore(board.tile))
    if memo.store.leaf_size != board.tile:
        raise ValueError(
            f"memo leaf {memo.store.leaf_size} != board tile {board.tile}"
        )
    reg = obs_registry.default()
    stats = MacroStats()
    with obs_trace.span("macro.simulate",
                        shape=f"{board.height}x{board.width}",
                        tile=board.tile, live_tiles=board.live_tiles,
                        convention=config.convention):
        result = _simulate(board, config, memo, stats,
                           tuple(checkpoints), on_checkpoint)
    reg.inc("macro_runs_total")
    reg.inc("macro_generations_total", result.generations)
    reg.inc("macro_supersteps_total", stats.supersteps)
    reg.set_gauge("macro_interned_nodes", memo.store.interned_nodes())
    return result


def _simulate(board, config, memo, stats, checkpoints, on_checkpoint
              ) -> MacroResult:
    run = _Run(MacroUniverse.from_board(memo.store, board), memo, stats)
    G = config.gen_limit
    f = config.similarity_frequency
    check = config.check_similarity
    cuda = config.convention == Convention.CUDA

    def finish(out_board: SparseBoard, gens: int, reason: str
               ) -> MacroResult:
        stats.generations = gens
        if on_checkpoint is not None:
            for c in sorted(set(checkpoints)):
                if 0 <= c <= G:
                    on_checkpoint(c, run.state_at(c).to_board())
        return MacroResult(out_board, gens, reason, stats)

    u0 = run.states[0]
    if u0.root.population == 0:
        # The conventions disagree on an initially-empty board: C's loop
        # never runs (EMPTY); CUDA steps it — gen_limit 0 wins first,
        # then a frequency-1 similarity check fires before the emptiness
        # break (sparse/engine._run_cuda's check ordering).
        if not cuda:
            return finish(u0.to_board(), 0, EXIT_EMPTY)
        if G == 0:
            return finish(u0.to_board(), 0, EXIT_GEN_LIMIT)
        if check and f == 1:
            return finish(u0.to_board(), 0, EXIT_SIMILAR)
        return finish(u0.to_board(), 0, EXIT_EMPTY)
    if G == 0:
        return finish(u0.to_board(), 0, EXIT_GEN_LIMIT)

    end = run.state_at(G)
    if end.root.population == 0:
        # Emptiness beats the similarity exit in both conventions: a
        # board still nonempty never fired "unchanged", and once empty,
        # C's loop condition exits before another step while CUDA's
        # break fires in the dying iteration itself.
        g_e = _bisect_first(0, G,
                            lambda g: run.state_at(g).root.population == 0)
        if not cuda:
            return finish(run.state_at(g_e).to_board(), g_e, EXIT_EMPTY)
        # CUDA's break precedes the swap: the reported board is the last
        # NON-empty generation, one before the empty one.
        return finish(run.state_at(g_e - 1).to_board(), g_e - 1, EXIT_EMPTY)
    if check and run.still_at(G):
        # First still generation, then the first similarity CHECK at or
        # after it (the check fires every `f` generations); both
        # conventions report generation g_check - 1 with the still board.
        g0 = _bisect_first(0, G, run.still_at)
        g_sim = f * ((g0 + f - 1) // f)
        if g_sim <= G:
            return finish(run.state_at(g0).to_board(), g_sim - 1,
                          EXIT_SIMILAR)
    return finish(end.to_board(), G, EXIT_GEN_LIMIT)


def auto_macro(height: int, width: int, tile: int, gen_limit: int,
               pattern_bbox, gens_threshold: int | None = None) -> bool:
    """The auto lane's sparse/macro pick, consulted only AFTER auto
    already chose sparse: macro wins when the run is deep enough to
    amortize the tree (the tuned/plan-cached crossover) AND the initial
    placement provably keeps the whole run off the torus seam
    (conservative: bbox + gen_limit inside the edge ring — auto must
    never pick a lane that can raise mid-run).

    ``pattern_bbox`` is (min_row, min_col, max_row, max_col) of the
    initial live cells in universe coordinates, or None (unknown =
    stay sparse)."""
    if tile % 2 or pattern_bbox is None:
        return False
    if gens_threshold is None:
        try:
            from gol_tpu.tune import select

            gens_threshold = select.macro_auto_gens(MACRO_AUTO_GENS)
        except Exception:  # noqa: BLE001 - cache trouble = default
            gens_threshold = MACRO_AUTO_GENS
    if gen_limit < gens_threshold:
        return False
    r0, c0, r1, c1 = pattern_bbox
    margin = min(r0 - 1, c0 - 1, height - 2 - r1, width - 2 - c1)
    return margin >= gen_limit
