"""Network fault injection: the adversarial half of the fleet's story.

Every failure this tree survived before PR 14 was one a test process chose
to inject at the disk or process level (``resilience/faults.py``: torn
payload writes, SIGKILL at checkpoint boundaries). The network path between
client, router, and workers — the hops real deployments lose first — had
never been exercised. This package closes that gap:

- ``chaos/plan.py``  — a declarative, SEEDED fault schedule (``ChaosPlan``)
  in the same ``k=v,k=v`` grammar as PR 1's ``FaultPlan``: added latency,
  connection refusal/reset mid-exchange, slow-loris reads, truncated
  responses, and bit-flipped payload bytes, each with its own probability.
- ``chaos/proxy.py`` — a jax-free in-process HTTP-aware proxy
  (``ChaosProxy``/``ProxyPool``) that fronts any worker or router socket
  and injects the plan's faults per exchange. Mountable under
  ``gol fleet --chaos PLAN`` (the router's data path to its workers) and
  programmatically in tests and the chaos bench lane.

The package is stdlib-only (the router imports it; the router owns no
device) and perf_counter-only (tests/test_lint.py extends the wall-clock
ban here). Production fleets without ``--chaos`` never import a proxy and
route exactly as before.
"""

from gol_tpu.chaos.plan import ChaosPlan, ChaosSchedule
from gol_tpu.chaos.proxy import ChaosProxy, ProxyPool

__all__ = ["ChaosPlan", "ChaosProxy", "ChaosSchedule", "ProxyPool"]
