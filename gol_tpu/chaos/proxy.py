"""The in-process chaos proxy: one hostile network hop, on demand.

``ChaosProxy`` fronts one upstream HTTP endpoint (a ``gol serve`` worker,
a fleet router — any socket speaking the stack's HTTP) and injects the
faults a seeded ``ChaosPlan`` schedules, per exchange. It understands just
enough HTTP/1.1 to find message boundaries — header block plus a
``Content-Length`` body, which is all this stack ever sends — so faults
land at *meaningful* points: a reset after the request was delivered is a
genuinely ambiguous submit, a truncation tears a response that already
framed its length, a bit flip lands inside a ``GOLP`` frame's CRC-covered
words payload (the flip the PR-11 gate must catch) or a JSON body's tail.

One proxy is one listening socket plus a thread per client connection;
``ProxyPool`` lazily mounts one proxy per distinct upstream URL (the
``gol fleet --chaos`` hook: the router resolves every data-path forward
through ``pool.url_for``, so worker respawns get fresh proxies
transparently). Faults are counted per kind in ``stats()`` — the chaos
matrix asserts the schedule actually fired, not merely that traffic
survived an idle proxy.

Health/supervision traffic stays OFF this path on purpose: the fleet's
health loop probes workers directly, so chaos exercises the data plane's
defenses (breakers, retries, deadlines, CRC gates) without also blinding
the supervisor that is part of those defenses.

Clocks: ``time.perf_counter``/``time.sleep`` only (test_lint's wall-clock
ban covers this package).
"""

from __future__ import annotations

import logging
import socket
import struct
import threading
import time
from urllib.parse import urlsplit

from gol_tpu.chaos.plan import ChaosPlan, ChaosSchedule, FAULT_KINDS

logger = logging.getLogger(__name__)

_GOLP_HEADER = struct.Struct("<4sHHIII")  # magic..meta_len (CRC not needed)


def _read_http_message(rfile) -> tuple[bytes, bytes] | None:
    """One HTTP message (request or response) -> (head bytes incl. the
    blank line, body bytes by Content-Length), or None on a clean EOF
    before any byte. The stack always frames bodies with Content-Length
    (both handlers set it; urllib sets it on every POST), so no chunked
    support is needed — an unframed message reads as an empty body."""
    head = bytearray()
    while True:
        line = rfile.readline(65536)
        if not line:
            if not head:
                return None
            raise ConnectionError("peer closed mid-header")
        head += line
        if line in (b"\r\n", b"\n"):
            break
    length = 0
    for raw in bytes(head).split(b"\r\n"):
        name, _, value = raw.partition(b":")
        if name.strip().lower() == b"content-length":
            try:
                length = int(value.strip())
            except ValueError:
                length = 0
    body = rfile.read(length) if length else b""
    if length and len(body) != length:
        raise ConnectionError("peer closed mid-body")
    return bytes(head), body


def _flip_bit(body: bytes, draw: float) -> bytes:
    """Flip ONE bit of the payload region of ``body`` (position chosen by
    the schedule's deterministic ``draw``). A ``GOLP`` frame flips inside
    its words payload — the bytes the header CRC covers, so the flip is
    catchable by construction; anything else flips in the trailing half
    (a JSON body's cells/grid tail). Too-small bodies pass untouched."""
    start = len(body) // 2
    if body[:4] == b"GOLP" and len(body) >= _GOLP_HEADER.size:
        meta_len = _GOLP_HEADER.unpack(body[:_GOLP_HEADER.size])[5]
        payload_at = _GOLP_HEADER.size + 4 + meta_len  # + the CRC field
        if payload_at < len(body):
            start = payload_at
    span = len(body) - start
    if span <= 0:
        return body
    offset = start + min(span - 1, int(draw * span))
    bit = int(draw * span * 8) % 8
    out = bytearray(body)
    out[offset] ^= 1 << bit
    return bytes(out)


def _rst_close(sock: socket.socket) -> None:
    """Close with a pending RST instead of a FIN: the reset the plan's
    ``refuse``/``reset`` classes mean (SO_LINGER 0 aborts the connection)."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    """One faulty hop in front of one upstream ``host:port``."""

    def __init__(self, upstream: str, plan: ChaosPlan,
                 schedule: ChaosSchedule | None = None,
                 host: str = "127.0.0.1", timeout: float = 120.0):
        parts = urlsplit(upstream if "//" in upstream
                         else f"http://{upstream}")
        if not parts.hostname or not parts.port:
            raise ValueError(f"chaos proxy upstream {upstream!r} needs an "
                             "explicit host:port")
        self.upstream = (parts.hostname, parts.port)
        self.plan = plan
        self.schedule = schedule if schedule is not None else plan.schedule()
        self.timeout = timeout
        self._stats_lock = threading.Lock()
        self._stats = {kind: 0 for kind in FAULT_KINDS}
        self._stats["exchanges"] = 0
        self._closed = False
        self._listener = socket.create_server((host, 0))
        self._listener.settimeout(1.0)
        self._thread = threading.Thread(
            target=self._accept_loop, name="gol-chaos-proxy", daemon=True
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    @property
    def url(self) -> str:
        host = self._listener.getsockname()[0]
        return f"http://{host}:{self.port}"

    def stats(self) -> dict:
        with self._stats_lock:
            return dict(self._stats)

    def _count(self, kind: str) -> None:
        with self._stats_lock:
            self._stats[kind] = self._stats.get(kind, 0) + 1

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        self._thread.join(timeout=5)

    # -- the data path ------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed
            threading.Thread(
                target=self._serve_client, args=(conn,),
                name="gol-chaos-conn", daemon=True,
            ).start()

    def _serve_client(self, client: socket.socket) -> None:
        client.settimeout(self.timeout)
        try:
            rfile = client.makefile("rb")
            while not self._closed:
                if not self._exchange(client, rfile):
                    return
        except (OSError, ConnectionError):
            pass  # a torn peer is business as usual here
        finally:
            try:
                client.close()
            except OSError:
                pass

    def _exchange(self, client: socket.socket, rfile) -> bool:
        """Relay one request/response pair, injecting this exchange's
        fault. Returns False when the connection is finished (EOF or a
        connection-terminating fault)."""
        try:
            probe = rfile.peek(1)
        except (OSError, ValueError):
            return False
        if not probe:
            return False  # clean keep-alive EOF: no exchange, no roll
        fault, bit_draw, flip_request = self.schedule.next_fault()
        self._count("exchanges")
        if fault == "refuse":
            # Before the request is consumed: it was never delivered.
            self._count(fault)
            _rst_close(client)
            return False
        msg = _read_http_message(rfile)
        if msg is None:
            return False
        req_head, req_body = msg
        if fault == "bitflip" and flip_request and req_body:
            self._count(fault)
            req_body = _flip_bit(req_body, bit_draw)
            fault = None  # the flip IS this exchange's fault
        up = socket.create_connection(self.upstream, timeout=self.timeout)
        try:
            up.sendall(req_head + req_body)
            up_file = up.makefile("rb")
            resp = _read_http_message(up_file)
            if resp is None:
                raise ConnectionError("upstream closed without a response")
            resp_head, resp_body = resp
        except (OSError, ConnectionError):
            # A real upstream failure (worker mid-respawn, say): surface
            # it as a reset, exactly what a lost backend looks like.
            up.close()
            _rst_close(client)
            return False
        up.close()
        return self._relay_response(client, resp_head, resp_body, fault,
                                    bit_draw)

    def _relay_response(self, client: socket.socket, head: bytes,
                        body: bytes, fault: str | None,
                        bit_draw: float) -> bool:
        if fault == "latency":
            self._count(fault)
            time.sleep(self.plan.latency_ms / 1000.0)
        elif fault == "reset":
            self._count(fault)
            client.sendall(head + body[: len(body) // 2])
            _rst_close(client)
            return False
        elif fault == "truncate":
            self._count(fault)
            client.sendall(head + body[: len(body) // 2])
            try:
                client.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            client.close()
            return False
        elif fault == "slowloris":
            self._count(fault)
            client.sendall(head)
            chunk = self.plan.slow_chunk
            for i in range(0, len(body), chunk):
                client.sendall(body[i:i + chunk])
                time.sleep(self.plan.slow_ms / 1000.0)
            return True
        elif fault == "bitflip":
            if body:
                self._count(fault)
                body = _flip_bit(body, bit_draw)
        client.sendall(head + body)
        return True


class ProxyPool:
    """Lazily one ``ChaosProxy`` per distinct upstream URL.

    The router's ``--chaos`` mount point: every data-path forward resolves
    its target through ``url_for``, so a worker that respawns on a new
    port transparently gets a new faulty hop. Schedules are salted by
    creation order — deterministic fault sequences per proxy even though
    ports differ run to run."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._proxies: dict[str, ChaosProxy] = {}
        self._created = 0  # monotonic salt: prune() must never reuse one
        self._closed = False

    def url_for(self, upstream_url: str) -> str:
        key = upstream_url.rstrip("/")
        with self._lock:
            if self._closed:
                return upstream_url
            proxy = self._proxies.get(key)
            if proxy is None:
                proxy = ChaosProxy(key, self.plan,
                                   schedule=self.plan.schedule(
                                       salt=self._created))
                self._created += 1
                self._proxies[key] = proxy
                logger.info("chaos: proxy %s fronts %s", proxy.url, key)
            return proxy.url

    def prune(self, live_upstreams) -> None:
        """Close proxies whose upstream is gone. A supervised respawn
        moves a worker to a fresh port and ``url_for`` mounts a fresh hop
        for it — without pruning, the DEAD port's listener socket and
        accept thread would idle forever (one leak per respawn, unbounded
        over an autoscaling soak). The fleet health tick calls this with
        the live membership URLs every cadence."""
        keep = {u.rstrip("/") for u in live_upstreams if u}
        with self._lock:
            if self._closed:
                return
            dead = [(key, proxy) for key, proxy in self._proxies.items()
                    if key not in keep]
            for key, _ in dead:
                del self._proxies[key]
        for key, proxy in dead:
            logger.info("chaos: pruned proxy for dead upstream %s", key)
            proxy.close()

    def proxies(self) -> dict[str, ChaosProxy]:
        with self._lock:
            return dict(self._proxies)

    def stats(self) -> dict:
        """Fault counts summed across every mounted proxy."""
        totals: dict[str, int] = {}
        for proxy in self.proxies().values():
            for kind, count in proxy.stats().items():
                totals[kind] = totals.get(kind, 0) + count
        return totals

    def close(self) -> None:
        with self._lock:
            self._closed = True
            proxies = list(self._proxies.values())
            self._proxies.clear()
        for proxy in proxies:
            proxy.close()


__all__ = ["ChaosProxy", "ProxyPool"]
