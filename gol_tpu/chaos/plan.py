"""Declarative, seeded network-fault schedules (the ``--chaos`` grammar).

A ``ChaosPlan`` is the network counterpart of ``resilience/faults.FaultPlan``
and speaks the same ``k=v,k=v`` spec grammar (unknown keys are loud errors —
a typo'd injection must never silently test nothing). Where a FaultPlan
counts discrete events ("fail the Nth write"), network faults are
probabilistic by nature: each key below is the per-exchange probability of
one fault class, and the whole schedule is driven by ONE seeded
``random.Random`` so a plan replays identically run to run — the chaos
matrix and the smoke assert against deterministic fault sequences.

Fault classes (checked in this fixed order per exchange; the first that
fires wins — every class is rolled every exchange so the decision sequence
depends only on the seed, never on which classes happened to fire):

- ``refuse=P``    the connection is reset before the request is read: the
                  closest an accepting proxy can get to a refused/killed
                  backend (the client sees a reset/disconnect with zero
                  response bytes).
- ``reset=P``     reset MID-exchange: the request is delivered whole, half
                  the response is relayed, then a hard RST — the ambiguous
                  failure (the worker may have accepted and journaled).
- ``truncate=P``  the response is cleanly closed after half its body — a
                  torn payload with a well-formed start.
- ``slowloris=P`` the response body trickles out in ``slow_chunk``-byte
                  pieces with ``slow_ms`` between them.
- ``bitflip=P``   one payload bit of the exchange flips in transit (request
                  or response body, alternating): for ``GOLP`` frames the
                  flip lands INSIDE the CRC-covered words payload, so the
                  PR-11 gate must catch every one (pinned by tests). The
                  TEXT wire has no integrity field — a flip there is only
                  caught when it breaks structure; one that lands on a
                  cell byte ('0' <-> '1') is a well-formed wrong board no
                  layer can detect, which is why the chaos matrix pins
                  this class on the packed lane and the README tells
                  operators to run ``--wire packed`` on lossy links.
- ``latency=P``   ``latency_ms`` of added delay before the response relays.

Parameters: ``seed=N`` (default 0), ``latency_ms=N`` (default 100),
``slow_ms=N`` (per-chunk delay, default 20), ``slow_chunk=N`` (default 256).

Clocks: none here (the proxy owns timing); the module is import-light so
the jax-free router can parse a plan in microseconds.
"""

from __future__ import annotations

import dataclasses
import random
import threading

# The fixed roll order (and the vocabulary of fault names the proxy's
# stats counters use). "none" is the no-fault outcome.
FAULT_KINDS = ("refuse", "reset", "truncate", "slowloris", "bitflip",
               "latency")

_PROB_KEYS = set(FAULT_KINDS)
_INT_KEYS = {"seed", "latency_ms", "slow_ms", "slow_chunk"}


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """One declarative fault mix. Frozen: schedules carry the mutable RNG."""

    seed: int = 0
    refuse: float = 0.0
    reset: float = 0.0
    truncate: float = 0.0
    slowloris: float = 0.0
    bitflip: float = 0.0
    latency: float = 0.0
    latency_ms: int = 100
    slow_ms: int = 20
    slow_chunk: int = 256

    def __post_init__(self):
        for key in _PROB_KEYS:
            p = getattr(self, key)
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"chaos plan {key} must be a probability in [0, 1], "
                    f"got {p}"
                )
        if self.latency_ms < 0 or self.slow_ms < 0:
            raise ValueError("chaos plan delays must be >= 0 ms")
        if self.slow_chunk < 1:
            raise ValueError(
                f"chaos plan slow_chunk must be >= 1, got {self.slow_chunk}"
            )

    @classmethod
    def parse(cls, spec: str) -> "ChaosPlan":
        """``k=v,k=v`` spec -> plan; unknown keys are loud errors (the
        FaultPlan.parse contract)."""
        kwargs: dict = {}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(f"chaos plan entry {part!r} is not k=v")
            if key in _PROB_KEYS:
                kwargs[key] = float(value)
            elif key in _INT_KEYS:
                kwargs[key] = int(value)
            else:
                raise ValueError(f"unknown chaos plan key {key!r}")
        return cls(**kwargs)

    def any_faults(self) -> bool:
        return any(getattr(self, key) > 0.0 for key in _PROB_KEYS)

    def schedule(self, salt: int = 0) -> "ChaosSchedule":
        """A fresh deterministic decision stream for this plan. ``salt``
        derives independent-but-reproducible streams for multiple proxies
        sharing one plan (ProxyPool salts by creation index — worker
        boot ORDER is deterministic even when ports are not)."""
        return ChaosSchedule(self, salt=salt)


class ChaosSchedule:
    """The mutable half: one seeded RNG rolling the plan, thread-safe
    (proxy connection threads share it). Every exchange consumes exactly
    ``len(FAULT_KINDS)`` + 2 rolls (the per-fault coin plus the bit-flip
    position/direction draws), so the Nth exchange's decision is a pure
    function of (seed, salt, N) regardless of which faults fired before."""

    def __init__(self, plan: ChaosPlan, salt: int = 0):
        self.plan = plan
        # One stable int per (seed, salt): tuple seeding is hash-based
        # (deprecated) and an odd-constant mix keeps salted streams
        # independent without it.
        self._rng = random.Random(plan.seed * 1_000_003 + salt)
        self._lock = threading.Lock()
        self.exchanges = 0

    def next_fault(self) -> tuple[str | None, float, bool]:
        """Roll one exchange -> (fault kind or None, bit position draw in
        [0, 1), flip-the-request flag). The two extra draws are consumed
        every exchange (alignment), used only by the bitflip class."""
        with self._lock:
            self.exchanges += 1
            fired = None
            for kind in FAULT_KINDS:
                roll = self._rng.random()
                if fired is None and roll < getattr(self.plan, kind):
                    fired = kind
            bit_draw = self._rng.random()
            flip_request = self._rng.random() < 0.5
            return fired, bit_draw, flip_request


__all__ = ["ChaosPlan", "ChaosSchedule", "FAULT_KINDS"]
