"""Two-phase toroidal halo exchange.

The reference exchanges halos with 16 persistent MPI requests — N/S rows, E/W
columns via an MPI_Type_vector column datatype, and 4 corner singles
(src/game_mpi.c:340-383, src/game_mpi_collective.c:287-326). On TPU the whole
exchange is two ``ppermute`` phases per axis inside the compiled step:

  phase 1  rows:    each shard sends its last interior row to its south
                    neighbor and its first to its north neighbor
  phase 2  columns: the same east/west, but over the *row-extended* (h+2, w)
                    block — so the received columns already contain the
                    diagonal neighbors' corner cells and no separate corner
                    messages exist.

Phase 2 covering the corners for free is the reference's own CUDA trick
(halo_cols runs over the extended index range 0..width+1, src/game_cuda.cu:
64-74); here it also replaces the reference's 8 corner requests.

On a mesh axis of size 1 the torus wrap degenerates to a local edge copy (what
the CUDA halo kernels do on a single device, src/game_cuda.cu:52-74), so the
same engine serves 1x1 .. RxC meshes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from gol_tpu.obs import registry as obs_registry
from gol_tpu.parallel.mesh import Topology, ROW_AXIS, COL_AXIS


def _account_exchange(*operands) -> None:
    """Record the per-exchange wire volume in the global obs registry.

    This function runs at TRACE time (the ppermutes live inside compiled
    programs; Python never sees the executed exchanges), so the honest
    accounting is per *traced* exchange site: a counter of sites and a
    gauge of bytes shipped per execution of the most recently traced one.
    Shapes/dtypes are static under tracing, so the numbers are exact.
    """
    reg = obs_registry.default()
    bytes_per = sum(
        int(np.prod(op.shape)) * np.dtype(op.dtype).itemsize
        for op in operands
    )
    reg.inc("halo_exchange_sites_traced_total")
    reg.set_gauge("halo_exchange_bytes", bytes_per)
    reg.inc("halo_exchange_traced_bytes_total", bytes_per)


def ring_perms(size: int) -> tuple[list, list]:
    forward = [(i, (i + 1) % size) for i in range(size)]
    backward = [(i, (i - 1) % size) for i in range(size)]
    return forward, backward


def ghost_slices(
    x: jnp.ndarray, axis: int, axis_name: str | None, size: int, depth: int = 1
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The two ``depth``-wide ghost slices along ``axis`` (torus wrap across
    shards). ``depth > 1`` is the wide-ghost-zone trade: one exchange feeds
    ``depth`` generations (shard extent must be >= depth)."""
    first = jax.lax.slice_in_dim(x, 0, depth, axis=axis)
    last = jax.lax.slice_in_dim(x, x.shape[axis] - depth, x.shape[axis], axis=axis)
    if axis_name is None or size == 1:
        # Wrap is local: my own far edge is my ghost (src/game_cuda.cu:52-74).
        return last, first
    forward, backward = ring_perms(size)
    _account_exchange(last, first)
    # Sending my last slice "forward" delivers my predecessor's last slice
    # to me: the ghost before my first row/col.
    ghost_before = jax.lax.ppermute(last, axis_name, forward)
    ghost_after = jax.lax.ppermute(first, axis_name, backward)
    return ghost_before, ghost_after


def _extend(x: jnp.ndarray, axis: int, axis_name: str | None, size: int) -> jnp.ndarray:
    """Add the two ghost slices along ``axis`` (torus wrap across shards)."""
    ghost_before, ghost_after = ghost_slices(x, axis, axis_name, size)
    return jnp.concatenate([ghost_before, x, ghost_after], axis=axis)


def boundary_columns(x: jnp.ndarray, top: jnp.ndarray, bot: jnp.ndarray):
    """West/east boundary columns over the row-extended range (h+2 each).

    Built after the row phase so the ghost rows' corner cells ride along in
    the column exchange (the src/game_cuda.cu:64-74 two-phase trick).
    """
    west = jnp.concatenate([top[:, 0], x[:, 0], bot[:, 0]])
    east = jnp.concatenate([top[:, -1], x[:, -1], bot[:, -1]])
    return west, east


def exchange_columns(west_col, east_col, topology: Topology, transform=None):
    """Column-phase exchange: returns the (ghost_west, ghost_east) columns.

    ``transform=(pack, unpack)`` optionally compresses the wire format (the
    packed path ships bit columns, 32x smaller than its word columns — the
    exact-boundary analog of the reference's derived column datatype,
    src/game_mpi.c:335-338).
    """
    cols = topology.shape[1]
    if not (topology.distributed and cols > 1):
        # Torus wrap is local: my own far edge is my ghost.
        return east_col, west_col
    pack, unpack = transform if transform is not None else (lambda v: v, lambda v: v)
    forward, backward = ring_perms(cols)
    east_wire, west_wire = pack(east_col), pack(west_col)
    _account_exchange(east_wire, west_wire)  # post-pack: the actual wire bytes
    ghost_west = unpack(jax.lax.ppermute(east_wire, COL_AXIS, forward))
    ghost_east = unpack(jax.lax.ppermute(west_wire, COL_AXIS, backward))
    return ghost_west, ghost_east


def assemble_band_ghosts(top, bot, gwest, geast, band):
    """Ghost operand set for a per-shard band kernel of ``band``-row bands.

    Returns ``(gtop8, gbot8, gmid, gwrap)``: the ghost rows embedded in
    8-row-aligned blocks (the 32-bit sublane granule — ghost above in row 7,
    ghost below in row 0), the per-row (west, east) carry columns for the
    shard's own rows, and per-band wrap-row carries. ``gwest``/``geast``
    cover extended rows -1..h, so shard row q's carries sit at index q+1;
    band i's wrap rows are extended rows i*band (above) and i*band+band+1
    (below), giving ``gwrap[i] = (west_top, east_top, west_bot, east_bot)``
    — the kernel reads only those four carries per band, so shipping whole
    per-row columns for the up/down planes would be 2*(band-1) unread rows.
    """
    h = gwest.shape[0] - 2
    if h % band != 0:
        # Out-of-range gathers clamp silently in JAX; a partial last band
        # would read its bottom wrap carries from the wrong row.
        raise ValueError(f"band {band} must divide the shard height {h}")
    zeros7 = jnp.zeros((7, top.shape[1]), top.dtype)
    gtop8 = jnp.concatenate([zeros7, top], axis=0)
    gbot8 = jnp.concatenate([bot, zeros7], axis=0)
    gmid = jnp.stack([gwest[1 : h + 1], geast[1 : h + 1]], axis=1)
    starts = jnp.arange(0, h, band)  # band i's top wrap row, extended index
    gwrap = jnp.stack(
        [gwest[starts], geast[starts], gwest[starts + band + 1], geast[starts + band + 1]],
        axis=1,
    )
    return gtop8, gbot8, gmid, gwrap


def exchange(local: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """Return the (h+2, w+2) halo-extended block for a (h, w) shard."""
    rows, cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    col_axis = COL_AXIS if topology.distributed else None
    extended = _extend(local, 0, row_axis, rows)
    # Column phase runs over the row-extended block: corners ride along.
    return _extend(extended, 1, col_axis, cols)
