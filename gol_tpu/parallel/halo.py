"""Two-phase toroidal halo exchange.

The reference exchanges halos with 16 persistent MPI requests — N/S rows, E/W
columns via an MPI_Type_vector column datatype, and 4 corner singles
(src/game_mpi.c:340-383, src/game_mpi_collective.c:287-326). On TPU the whole
exchange is two ``ppermute`` phases per axis inside the compiled step:

  phase 1  rows:    each shard sends its last interior row to its south
                    neighbor and its first to its north neighbor
  phase 2  columns: the same east/west, but over the *row-extended* (h+2, w)
                    block — so the received columns already contain the
                    diagonal neighbors' corner cells and no separate corner
                    messages exist.

Phase 2 covering the corners for free is the reference's own CUDA trick
(halo_cols runs over the extended index range 0..width+1, src/game_cuda.cu:
64-74); here it also replaces the reference's 8 corner requests.

On a mesh axis of size 1 the torus wrap degenerates to a local edge copy (what
the CUDA halo kernels do on a single device, src/game_cuda.cu:52-74), so the
same engine serves 1x1 .. RxC meshes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gol_tpu.parallel.mesh import Topology, ROW_AXIS, COL_AXIS


def _ring_perms(size: int) -> tuple[list, list]:
    forward = [(i, (i + 1) % size) for i in range(size)]
    backward = [(i, (i - 1) % size) for i in range(size)]
    return forward, backward


def _extend(x: jnp.ndarray, axis: int, axis_name: str | None, size: int) -> jnp.ndarray:
    """Add the two ghost slices along ``axis`` (torus wrap across shards)."""
    first = jax.lax.slice_in_dim(x, 0, 1, axis=axis)
    last = jax.lax.slice_in_dim(x, x.shape[axis] - 1, x.shape[axis], axis=axis)
    if axis_name is None or size == 1:
        # Wrap is local: my own far edge is my ghost (src/game_cuda.cu:52-74).
        ghost_before, ghost_after = last, first
    else:
        forward, backward = _ring_perms(size)
        # Sending my last slice "forward" delivers my predecessor's last slice
        # to me: the ghost before my first row/col.
        ghost_before = jax.lax.ppermute(last, axis_name, forward)
        ghost_after = jax.lax.ppermute(first, axis_name, backward)
    return jnp.concatenate([ghost_before, x, ghost_after], axis=axis)


def exchange(local: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """Return the (h+2, w+2) halo-extended block for a (h, w) shard."""
    rows, cols = topology.shape
    row_axis = ROW_AXIS if topology.distributed else None
    col_axis = COL_AXIS if topology.distributed else None
    extended = _extend(local, 0, row_axis, rows)
    # Column phase runs over the row-extended block: corners ride along.
    return _extend(extended, 1, col_axis, cols)
