"""Multi-host process bootstrap — the ``MPI_Init`` / ``mpiexec -n`` analog.

The reference bootstraps its process group with ``MPI_Init`` + a Cartesian
communicator (src/game_mpi_collective.c:116-133) launched by ``mpiexec -n <x>``
(README.md:54-57). On TPU pods the analog is one Python process per host,
``jax.distributed.initialize`` to form the cluster, and a ``Mesh`` spanning
every chip; ICI carries the halo/psum traffic and DCN only carries the
runtime's control plane.

On Cloud TPU the coordinator/process-count/process-id triple is discovered
from the environment, so ``initialize()`` with no arguments is the whole
bootstrap. Elsewhere (e.g. a CPU test cluster) pass them explicitly, mirroring
``mpiexec``'s rank/size.

After initialization, ``gol_tpu.parallel.mesh.make_mesh`` over
``jax.devices()`` (ALL processes' devices) plus the engine's ``shard_map`` is
the complete distributed program; per-host I/O in ``io/sharded.py`` and
``io/packed_io.py`` only touches addressable shards, so no host ever
materializes the full grid — the property the reference gets from MPI-IO file
views (src/game_mpi_collective.c:186-196).
"""

from __future__ import annotations


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Join (or form) the multi-host cluster; no-op unless opted in.

    Safe to call unconditionally at CLI start: with no arguments it only
    initializes when ``GOL_MULTIHOST=1`` is set (the ``mpiexec`` analog is
    the launcher exporting that), letting JAX auto-discover the coordinator;
    pass the triple explicitly for manual clusters.
    """
    import jax

    if coordinator_address is None and num_processes is None and process_id is None:
        import os

        # Auto-initialization is explicit opt-in (GOL_MULTIHOST=1): cluster
        # env vars like TPU_WORKER_HOSTNAMES exist on single-chip setups too
        # (sometimes holding placeholder text), so their presence alone must
        # not make a plain run try to form a cluster.
        if os.environ.get("GOL_MULTIHOST", "") not in ("1", "true"):
            return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_multihost() -> bool:
    import jax

    return jax.process_count() > 1
