"""Device mesh construction and domain-decomposition bookkeeping.

The reference decomposes the grid over a fully periodic sqrtP x sqrtP
Cartesian process grid built with ``MPI_Cart_create(..., periods={1,1},
reorder=1)`` (src/game_mpi_collective.c:120-133), each rank owning a
``(width/sqrtP) x (height/sqrtP)`` block plus a one-cell ghost ring. Here the
process grid is a ``jax.sharding.Mesh`` with axes ``('row', 'col')`` laid out
over ICI; the periodic boundary is realized by ``ppermute`` rings (the physical
ICI torus makes the wrap literal on real pods). Unlike MPI ranks, shards never
materialize ghost cells in their owned array — halos live only inside the
compiled step (see gol_tpu/parallel/halo.py).

The reference implicitly requires a perfect-square process count and square
grids divisible by sqrtP (forced ``height = width``, src/game_mpi.c:504;
truncating ``width / rows_columns``, src/game_mpi.c:172). This build supports
any R x C mesh and rectangular grids but validates divisibility loudly instead
of silently truncating.
"""

from __future__ import annotations

import dataclasses
import math

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "row"
COL_AXIS = "col"


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static description of how the grid is laid out over devices.

    ``shape == (1, 1)`` with ``axes == ()`` is the single-device engine: halo
    wrap is local and consensus reductions are identities. Otherwise ``axes``
    names both mesh axes and collectives ride them.
    """

    shape: tuple[int, int] = (1, 1)
    axes: tuple[str, ...] = ()

    @property
    def num_devices(self) -> int:
        return self.shape[0] * self.shape[1]

    @property
    def distributed(self) -> bool:
        return bool(self.axes)


SINGLE_DEVICE = Topology()
MESH_TOPOLOGY_AXES = (ROW_AXIS, COL_AXIS)


def choose_mesh_shape(n_devices: int) -> tuple[int, int]:
    """Pick the most-square R x C factorization of ``n_devices``.

    The reference only accepts perfect squares (``sqrt(comm_sz)`` truncation,
    src/game_mpi_collective.c:125); a near-square factorization keeps the
    O(perimeter) halo volume minimal while accepting any device count.
    """
    r = int(math.isqrt(n_devices))
    while n_devices % r != 0:
        r -= 1
    return r, n_devices // r


def make_mesh(
    rows: int | None = None,
    cols: int | None = None,
    devices=None,
) -> Mesh:
    """Build the 2D ('row', 'col') device mesh."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if rows is None and cols is None:
        rows, cols = choose_mesh_shape(n)
    elif rows is None:
        if cols <= 0 or n % cols:
            raise ValueError(f"cannot infer mesh rows: {n} devices not divisible by cols={cols}")
        rows = n // cols
    elif cols is None:
        if rows <= 0 or n % rows:
            raise ValueError(f"cannot infer mesh cols: {n} devices not divisible by rows={rows}")
        cols = n // rows
    if rows < 1 or cols < 1:
        raise ValueError(f"mesh axes must be >= 1, got {rows}x{cols}")
    if rows * cols > n:
        raise ValueError(f"mesh {rows}x{cols} needs {rows * cols} devices, have {n}")
    return jax.make_mesh((rows, cols), MESH_TOPOLOGY_AXES, devices=devices[: rows * cols])


def topology_for(mesh: Mesh | None) -> Topology:
    if mesh is None:
        return SINGLE_DEVICE
    shape = (mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS])
    if shape == (1, 1):
        return SINGLE_DEVICE
    return Topology(shape=shape, axes=MESH_TOPOLOGY_AXES)


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Block sharding of the (height, width) grid over the mesh."""
    return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))


def validate_grid(height: int, width: int, topology: Topology) -> tuple[int, int]:
    """Check divisibility and return the local shard shape.

    The reference silently truncates (src/game_mpi.c:172) and corrupts the run
    when the grid doesn't divide; here it is a loud error (SURVEY.md §7).
    """
    rows, cols = topology.shape
    if height % rows != 0 or width % cols != 0:
        raise ValueError(
            f"grid {height}x{width} does not divide over a {rows}x{cols} mesh; "
            f"height must be a multiple of {rows} and width of {cols}"
        )
    return height // rows, width // cols
