"""Device mesh construction and domain-decomposition bookkeeping.

The reference decomposes the grid over a fully periodic sqrtP x sqrtP
Cartesian process grid built with ``MPI_Cart_create(..., periods={1,1},
reorder=1)`` (src/game_mpi_collective.c:120-133), each rank owning a
``(width/sqrtP) x (height/sqrtP)`` block plus a one-cell ghost ring. Here the
process grid is a ``jax.sharding.Mesh`` with axes ``('row', 'col')`` laid out
over ICI; the periodic boundary is realized by ``ppermute`` rings (the physical
ICI torus makes the wrap literal on real pods). Unlike MPI ranks, shards never
materialize ghost cells in their owned array — halos live only inside the
compiled step (see gol_tpu/parallel/halo.py).

The reference implicitly requires a perfect-square process count and square
grids divisible by sqrtP (forced ``height = width``, src/game_mpi.c:504;
truncating ``width / rows_columns``, src/game_mpi.c:172). This build supports
any R x C mesh and rectangular grids but validates divisibility loudly instead
of silently truncating.
"""

from __future__ import annotations

import dataclasses

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "row"
COL_AXIS = "col"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it top-level with ``check_vma``; older releases only
    have ``jax.experimental.shard_map.shard_map`` with the same semantics
    under ``check_rep``. Every shard_map in the tree goes through here so a
    jax downgrade degrades to the experimental entry point instead of an
    AttributeError at trace time.
    """
    import inspect

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    kwargs = {"mesh": mesh, "in_specs": in_specs, "out_specs": out_specs}
    params = inspect.signature(sm).parameters
    if "check_vma" in params:
        kwargs["check_vma"] = check_vma
    elif "check_rep" in params:
        # The legacy replication checker predates rules for while_loop (the
        # engine's whole loop) — it must stay off; correctness is pinned by
        # the differential suite, not the static check.
        kwargs["check_rep"] = False
    return sm(f, **kwargs)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static description of how the grid is laid out over devices.

    ``shape == (1, 1)`` with ``axes == ()`` is the single-device engine: halo
    wrap is local and consensus reductions are identities. Otherwise ``axes``
    names both mesh axes and collectives ride them.
    """

    shape: tuple[int, int] = (1, 1)
    axes: tuple[str, ...] = ()

    @property
    def num_devices(self) -> int:
        return self.shape[0] * self.shape[1]

    @property
    def distributed(self) -> bool:
        return bool(self.axes)


SINGLE_DEVICE = Topology()
MESH_TOPOLOGY_AXES = (ROW_AXIS, COL_AXIS)
# A cols>1 topology with NO mesh axes: local torus wraps, but the kernels
# route as for an R x C pod shard. Benchmarks/soaks/tests use it to exercise
# the 2D ghost-plane form on one chip (SINGLE_DEVICE routes rows-only).
PROXY_2D = Topology(shape=(1, 2), axes=())


def choose_mesh_shape(
    n_devices: int, width: int | None = None, height: int | None = None
) -> tuple[int, int]:
    """Pick the default R x C factorization of ``n_devices``: row-heaviest.

    The reference only accepts perfect squares (``sqrt(comm_sz)`` truncation,
    src/game_mpi_collective.c:125) because a near-square factorization
    minimizes the O(perimeter) halo bytes. On TPU that objective is the
    wrong one: halo bytes cost microseconds on ICI either way, while the
    COLUMN-direction ghost machinery costs real per-generation compute in
    the packed kernel. A row-only R x 1 decomposition needs none of it —
    full-width shards wrap E/W through their own lane roll. In DEVICE time
    (the r4 protocol's published series — wall clock over the attach
    tunnel spans +/-40%, benchmarks/README.md) the rows-only pod shard
    runs at 0.9997 of the single-chip kernel and the r4 split-edge 2D
    form at 0.85-0.86 (benchmarks/configs_r4.json,
    compare_{16384,32768}_r4.json; the r3 ghost-plane form it replaced
    measured 0.64-0.96 wall), so row-heavy is the default.

    ``width``/``height`` (the grid shape, when the caller knows it) refine
    the choice:

    - a factorization whose rows divide ``height`` (and cols divide
      ``width``) is preferred over one validate_grid would reject — e.g.
      100 rows on 8 devices picks (4, 2), since (8, 1) cannot shard it;
    - the temporal kernel's VMEM width cap: past it an R x 1 shard would
      silently fall to the ~2x slower per-generation kernel, so just
      enough mesh columns are added to bring the shard width back under
      the cap. When NO factorization can (or none that divides the grid),
      the choice falls back row-heavy and warns on stderr that the
      temporal kernel is disengaged — pick an explicit ``--mesh`` to
      trade the layout by hand.
    """
    # Late import: ops imports this module at load time.
    from gol_tpu.ops.stencil_packed import _BITS, _MAX_WORDS_T

    def divides_grid(r: int, c: int) -> bool:
        if height is not None and height % r:
            return False
        return not (width is not None and width % c)

    def under_cap(c: int) -> bool:
        return width is None or width // (_BITS * c) <= _MAX_WORDS_T

    # Row-heaviest first: cols ascending.
    candidates = [
        (n_devices // c, c) for c in range(1, n_devices + 1) if n_devices % c == 0
    ]
    # Nothing divides the grid -> keep (n, 1) and let validate_grid raise
    # its loud divisibility error for the default mesh too.
    pool = [rc for rc in candidates if divides_grid(*rc)] or candidates
    for r, c in pool:
        if under_cap(c):
            return r, c
    r, c = pool[0]
    import warnings

    # warnings.warn, not raw stderr (advisor r4): embedders/tests can
    # filter it, and repeated make_mesh calls dedupe per call site.
    warnings.warn(
        f"no {n_devices}-device mesh factorization keeps shards within "
        f"the temporal kernel's width cap ({_MAX_WORDS_T * _BITS} cells) "
        f"for a width-{width} grid; defaulting to {r}x{c} on the ~2x "
        "slower per-generation kernel — pass an explicit --mesh to choose "
        "the trade yourself",
        RuntimeWarning,
        stacklevel=2,
    )
    return r, c


def make_mesh(
    rows: int | None = None,
    cols: int | None = None,
    devices=None,
    width: int | None = None,
    height: int | None = None,
) -> Mesh:
    """Build the 2D ('row', 'col') device mesh. ``width``/``height`` only
    inform the default factorization (see ``choose_mesh_shape``)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if rows is None and cols is None:
        rows, cols = choose_mesh_shape(n, width, height)
    elif rows is None:
        if cols <= 0 or n % cols:
            raise ValueError(f"cannot infer mesh rows: {n} devices not divisible by cols={cols}")
        rows = n // cols
    elif cols is None:
        if rows <= 0 or n % rows:
            raise ValueError(f"cannot infer mesh cols: {n} devices not divisible by rows={rows}")
        cols = n // rows
    if rows < 1 or cols < 1:
        raise ValueError(f"mesh axes must be >= 1, got {rows}x{cols}")
    if rows * cols > n:
        raise ValueError(f"mesh {rows}x{cols} needs {rows * cols} devices, have {n}")
    return jax.make_mesh((rows, cols), MESH_TOPOLOGY_AXES, devices=devices[: rows * cols])


def topology_for(mesh: Mesh | None) -> Topology:
    if mesh is None:
        return SINGLE_DEVICE
    shape = (mesh.shape[ROW_AXIS], mesh.shape[COL_AXIS])
    if shape == (1, 1):
        return SINGLE_DEVICE
    return Topology(shape=shape, axes=MESH_TOPOLOGY_AXES)


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """Block sharding of the (height, width) grid over the mesh."""
    return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))


def validate_grid(height: int, width: int, topology: Topology) -> tuple[int, int]:
    """Check divisibility and return the local shard shape.

    The reference silently truncates (src/game_mpi.c:172) and corrupts the run
    when the grid doesn't divide; here it is a loud error (SURVEY.md §7).
    """
    rows, cols = topology.shape
    if height % rows != 0 or width % cols != 0:
        raise ValueError(
            f"grid {height}x{width} does not divide over a {rows}x{cols} mesh; "
            f"height must be a multiple of {rows} and width of {cols}"
        )
    return height // rows, width // cols
