"""Distribution layer: mesh construction, halo exchange, consensus collectives.

The TPU-native counterpart of the reference's MPI machinery: a 2D
``jax.sharding.Mesh`` replaces ``MPI_Cart_create`` (src/game_mpi_collective.c:
120-133), two-phase ``ppermute`` shifts replace the 16 persistent halo requests
(src/game_mpi.c:340-383), and ``psum`` consensus replaces ``MPI_Allreduce``
(src/game_mpi_collective.c:70-109).
"""

from gol_tpu.parallel.mesh import Topology, choose_mesh_shape, make_mesh, validate_grid
from gol_tpu.parallel.halo import exchange

__all__ = ["Topology", "choose_mesh_shape", "make_mesh", "validate_grid", "exchange"]
