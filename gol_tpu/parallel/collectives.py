"""Consensus collectives for termination votes.

The reference's termination consensus is one MPI_Allreduce(SUM) of a 0/1 flag
per check, compared against comm_sz (empty_all / similarity_all,
src/game_mpi_collective.c:70-81,98-109). Here the same vote is a ``psum`` over
both mesh axes inside the compiled step — it rides ICI and never touches the
host, which is what removes the reference CUDA program's
device-to-host-flag-per-generation bottleneck (src/game_cuda.cu:259-268).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gol_tpu.parallel.mesh import Topology


def all_agree(local_flag: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """True iff every shard's flag is true (the `global_sum == comm_sz` vote,
    src/game_mpi_collective.c:80)."""
    if not topology.distributed:
        return local_flag
    votes = jax.lax.psum(local_flag.astype(jnp.int32), topology.axes)
    return votes == topology.num_devices


def any_flag(local_flag: jnp.ndarray, topology: Topology) -> jnp.ndarray:
    """True iff any shard's flag is true (alive-anywhere vote)."""
    if not topology.distributed:
        return local_flag
    return jax.lax.psum(local_flag.astype(jnp.int32), topology.axes) > 0


def host_all_agree(flag: bool) -> bool:
    """Host-side (Python-level) counterpart of ``all_agree``: True iff every
    *process* votes True.

    ``all_agree`` votes per-shard inside a compiled step; this votes
    per-process between steps — the checkpoint/auto-resume protocol runs it
    on "can I read and verify this manifest?" so a cluster never resumes from
    a checkpoint only some hosts can see (resilience/checkpoint.py). On a
    single process it is the same identity short-circuit as the in-step vote.
    """
    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    votes = np.asarray(
        multihost_utils.process_allgather(np.asarray(bool(flag), np.int32))
    )
    return int(votes.sum()) == jax.process_count()
