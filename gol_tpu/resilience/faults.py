"""Fault-injection plan: deterministic failures for the recovery harness.

The crash-safety claims in ``resilience/checkpoint.py`` (a crash never leaves
the checkpoint dir without a readable prior state; auto-resume reproduces the
uninterrupted run byte-for-byte) are only claims until a harness kills real
runs at every boundary and fails writes mid-checkpoint. This module is that
harness's lever: a ``FaultPlan`` installed process-wide (by flag or env var)
that the IO and checkpoint layers probe at their injection points.

Production runs never install a plan, and every probe is a no-op ``None``
check — the hooks cost nothing when disarmed.

Knobs (``--fault-plan`` spec / ``GOL_FAULTS`` env var, ``k=v`` comma list):

- ``ts_write_fail=N``      fail the Nth tensorstore shard write (1-based,
                           counted process-wide)
- ``ts_write_error=hard|transient``  how that write fails (default hard)
- ``ts_open_transient=N``  first N tensorstore opens raise a transient error
- ``payload_write_fail=N`` fail the Nth checkpoint payload write mid-file
- ``kill_at_gen=K``        crash at the first checkpoint boundary whose
                           generation count is >= K
- ``kill_during_ckpt_write=N``  crash DURING the Nth checkpoint payload
                           write (the payload is torn mid-file first) —
                           with the async writer (gol_tpu/pipeline) this
                           fires on the background writer thread, modeling
                           a process dying with a write in flight; the
                           last *committed* checkpoint must survive
- ``kill_mode=exception|sigkill``  simulated crash (``InjectedCrash``, a
                           BaseException no library layer catches) or a real
                           ``SIGKILL`` (subprocess harness only)

Filesystem exhaustion knobs (the storage-lifecycle harness: every durable
writer — journal, CAS, checkpoint, compaction snapshot — routes its bytes
through the ``resilience/fsio`` shim, whose probes these drive):

- ``enospc_after_bytes=N`` shim writes succeed until N cumulative bytes
                           have passed, then every write raises
                           ``OSError(ENOSPC)`` — a partition filling up
                           mid-run, deterministically
- ``eio_every=N``          every Nth shim write raises ``OSError(EIO)``
                           (flaky media, not exhaustion — retries may heal)
- ``full_disk=1``          every shim write raises ``ENOSPC`` immediately
                           and ``fsio.free_bytes`` reports 0 — the disk is
                           full from the first byte (drives the watchdog)
- ``disk_free_bytes=N``    pin ``fsio.free_bytes`` to N without failing
                           writes: the watchdog sees pressure before the
                           filesystem actually refuses anything
- ``kill_during_compaction=snapshot|retire``  crash a journal compaction at
                           its two durability boundaries — ``snapshot``
                           fires with the new snapshot fully staged but not
                           yet committed; ``retire`` fires after the commit
                           with the folded segments not yet deleted
- ``kill_during_cas_gc=N`` crash the CAS garbage collector mid-evict on its
                           Nth entry, between the meta unlink (the entry is
                           now invisible) and the payload unlink (an orphan
                           sidecar the next sweep must collect)
- ``kill_during_prune=N``  crash checkpoint pruning on its Nth doomed
                           checkpoint, after the manifest delete and before
                           the payload delete (the orphaned payload must be
                           invisible garbage to the next restore/GC)
"""

from __future__ import annotations

import dataclasses
import errno
import os


class InjectedCrash(BaseException):
    """A simulated hard kill. Derives from BaseException so no library-level
    ``except Exception`` can absorb it — like SIGKILL, nothing between the
    injection point and the process boundary gets to clean up."""


class TransientInjectedError(OSError):
    """An injected transient IO error; the message carries the marker
    ``retry.is_transient_io`` classifies on."""

    def __init__(self, site: str):
        super().__init__(f"injected transient fault at {site}")


class InjectedWriteError(OSError):
    """An injected hard IO failure (non-transient: retries must NOT heal it)."""

    def __init__(self, site: str):
        super().__init__(f"injected hard write fault at {site}")


@dataclasses.dataclass
class FaultPlan:
    """Declarative failure schedule; counters live in the instance so one
    plan drives exactly one run's worth of faults."""

    ts_write_fail: int | None = None
    ts_write_error: str = "hard"  # "hard" | "transient"
    ts_open_transient: int = 0
    payload_write_fail: int | None = None
    kill_at_gen: int | None = None
    kill_during_ckpt_write: int | None = None
    kill_mode: str = "exception"  # "exception" | "sigkill"
    # Filesystem exhaustion (probed by the resilience/fsio shim).
    enospc_after_bytes: int | None = None
    eio_every: int | None = None
    full_disk: int = 0
    disk_free_bytes: int | None = None
    kill_during_compaction: str | None = None  # "snapshot" | "retire"
    kill_during_cas_gc: int | None = None
    kill_during_prune: int | None = None

    _ts_writes: int = dataclasses.field(default=0, repr=False)
    _ts_opens: int = dataclasses.field(default=0, repr=False)
    _payload_writes: int = dataclasses.field(default=0, repr=False)
    _killed: bool = dataclasses.field(default=False, repr=False)
    _fs_bytes: int = dataclasses.field(default=0, repr=False)
    _fs_writes: int = dataclasses.field(default=0, repr=False)
    _cas_evicts: int = dataclasses.field(default=0, repr=False)
    _prunes: int = dataclasses.field(default=0, repr=False)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``k=v,k=v`` spec -> plan; unknown keys are loud errors so a typo'd
        injection never silently tests nothing."""
        plan = cls()
        ints = {"ts_write_fail", "ts_open_transient", "payload_write_fail",
                "kill_at_gen", "kill_during_ckpt_write",
                "enospc_after_bytes", "eio_every", "full_disk",
                "disk_free_bytes", "kill_during_cas_gc",
                "kill_during_prune"}
        strs = {"ts_write_error": ("hard", "transient"),
                "kill_mode": ("exception", "sigkill"),
                "kill_during_compaction": ("snapshot", "retire")}
        for part in filter(None, (p.strip() for p in spec.split(","))):
            key, sep, value = part.partition("=")
            if not sep:
                raise ValueError(f"fault plan entry {part!r} is not k=v")
            if key in ints:
                setattr(plan, key, int(value))
            elif key in strs:
                if value not in strs[key]:
                    raise ValueError(
                        f"fault plan {key} must be one of {strs[key]}, "
                        f"got {value!r}")
                setattr(plan, key, value)
            else:
                raise ValueError(f"unknown fault plan key {key!r}")
        return plan

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        spec = os.environ.get("GOL_FAULTS")
        return cls.parse(spec) if spec else None


_active: FaultPlan | None = None


def install(plan: FaultPlan | None) -> None:
    """Arm ``plan`` process-wide (None disarms)."""
    global _active
    _active = plan


def clear() -> None:
    install(None)


def active() -> FaultPlan | None:
    return _active


def install_from_env() -> FaultPlan | None:
    """Arm a plan from ``GOL_FAULTS`` if set (the subprocess harness's path:
    the env var crosses the exec boundary, flags don't). Returns it."""
    plan = FaultPlan.from_env()
    if plan is not None:
        install(plan)
    return plan


# --- injection points -------------------------------------------------------
# Each probe is called by exactly one library site; the site string rides the
# raised error so a harness assertion can name where the fault landed.


def on_ts_open() -> None:
    plan = _active
    if plan is None:
        return
    if plan._ts_opens < plan.ts_open_transient:
        plan._ts_opens += 1
        raise TransientInjectedError("tensorstore open")
    plan._ts_opens += 1


def on_ts_shard_write(shard_index: int) -> None:
    plan = _active
    if plan is None:
        return
    plan._ts_writes += 1
    if plan.ts_write_fail is not None and plan._ts_writes == plan.ts_write_fail:
        site = f"tensorstore shard write #{plan._ts_writes} (shard {shard_index})"
        if plan.ts_write_error == "transient":
            raise TransientInjectedError(site)
        raise InjectedWriteError(site)


def _tear(path: str) -> None:
    """Corrupt ``path`` the way a crash mid-write would: truncate the file
    to half its bytes (directory payloads: tear the largest file inside)."""
    target = path
    if os.path.isdir(path):
        candidates = []
        for root, _, names in os.walk(path):
            for name in names:
                p = os.path.join(root, name)
                try:
                    candidates.append((os.path.getsize(p), p))
                except OSError:
                    pass
        if not candidates:
            return
        target = max(candidates)[1]
    try:
        with open(target, "r+b") as f:
            f.truncate(os.path.getsize(target) // 2)
    except OSError:
        pass


def on_payload_write(path: str) -> None:
    """Probed right after a checkpoint payload write completes; a firing
    fault TEARS the written payload (mid-file truncation) before raising, so
    the harness proves restore() treats torn payloads as invisible garbage —
    not merely that an error aborts the manifest commit.

    ``kill_during_ckpt_write`` fires here too, but as a process CRASH
    rather than an I/O error: with the async checkpoint writer this probe
    runs on the background ``gol-ckpt-writer`` thread, so the kill models
    exactly the window the deferred-commit discipline protects — a death
    with a payload write in flight, its manifest never committed. The
    payload is torn first (the write was "mid-file"), the flight recorder
    dumps (sigkill gets no unwinding), then ``kill_mode`` decides SIGKILL
    vs ``InjectedCrash`` (which the writer thread parks and the main thread
    re-raises at its next drain — the deferred MPI_Wait status)."""
    plan = _active
    if plan is None:
        return
    plan._payload_writes += 1
    if (
        plan.kill_during_ckpt_write is not None
        and plan._payload_writes == plan.kill_during_ckpt_write
        and not plan._killed
    ):
        plan._killed = True
        _tear(path)
        from gol_tpu.obs import recorder

        recorder.trigger(
            f"fault-injection: kill during checkpoint payload write "
            f"{path} ({plan.kill_mode})"
        )
        if plan.kill_mode == "sigkill":
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedCrash(
            f"injected crash during checkpoint payload write {path}"
        )
    if (
        plan.payload_write_fail is not None
        and plan._payload_writes == plan.payload_write_fail
    ):
        _tear(path)
        raise InjectedWriteError(f"checkpoint payload write {path}")


def _crash(site: str) -> None:
    """The shared kill tail: dump the flight recorder, then SIGKILL or raise
    ``InjectedCrash`` per the plan's ``kill_mode`` (exactly the
    ``on_checkpoint_boundary`` discipline — sigkill gets no unwinding, so
    the dump must happen here)."""
    plan = _active
    from gol_tpu.obs import recorder

    recorder.trigger(f"fault-injection: kill at {site} ({plan.kill_mode})")
    if plan.kill_mode == "sigkill":
        import signal

        os.kill(os.getpid(), signal.SIGKILL)
    raise InjectedCrash(f"injected crash at {site}")


def on_fs_write(nbytes: int, site: str) -> None:
    """Probed by ``resilience/fsio`` before every shim write: the
    exhaustion knobs fire here, with real errno values so the callers'
    ENOSPC/EIO handling is exercised verbatim."""
    plan = _active
    if plan is None:
        return
    plan._fs_writes += 1
    if plan.full_disk:
        raise OSError(errno.ENOSPC,
                      f"injected full disk at {site}")
    if plan.eio_every and plan._fs_writes % plan.eio_every == 0:
        raise OSError(errno.EIO,
                      f"injected EIO at {site} (write #{plan._fs_writes})")
    plan._fs_bytes += nbytes
    if (plan.enospc_after_bytes is not None
            and plan._fs_bytes > plan.enospc_after_bytes):
        raise OSError(
            errno.ENOSPC,
            f"injected ENOSPC at {site} ({plan._fs_bytes} bytes past the "
            f"{plan.enospc_after_bytes}-byte budget)")


def fs_free_bytes() -> int | None:
    """The watchdog's injected free-byte reading, or None (read the real
    filesystem). ``full_disk`` reports 0 so the pressure plane and the
    write failures agree on the world."""
    plan = _active
    if plan is None:
        return None
    if plan.full_disk:
        return 0
    return plan.disk_free_bytes


def on_compaction(stage: str) -> None:
    """Probed at a journal compaction's two durability boundaries:
    ``snapshot`` right before the atomic commit (staged, uncommitted) and
    ``retire`` right after it (committed, folded segments still on disk)."""
    plan = _active
    if plan is None or plan._killed:
        return
    if plan.kill_during_compaction == stage:
        plan._killed = True
        _crash(f"journal compaction ({stage} boundary)")


def on_cas_evict(fp: str) -> None:
    """Probed by the CAS garbage collector between an evicted entry's meta
    unlink and its payload unlink — the orphan-sidecar window."""
    plan = _active
    if plan is None or plan._killed or plan.kill_during_cas_gc is None:
        return
    plan._cas_evicts += 1
    if plan._cas_evicts == plan.kill_during_cas_gc:
        plan._killed = True
        _crash(f"CAS GC evict #{plan._cas_evicts} ({fp})")


def on_checkpoint_prune(path: str) -> None:
    """Probed by checkpoint pruning between a doomed checkpoint's manifest
    delete and its payload delete: a kill here leaves an orphaned payload
    that must be invisible garbage to the next restore (and swept by the
    next prune)."""
    plan = _active
    if plan is None or plan._killed or plan.kill_during_prune is None:
        return
    plan._prunes += 1
    if plan._prunes == plan.kill_during_prune:
        plan._killed = True
        _crash(f"checkpoint prune ({path})")


def on_checkpoint_boundary(generation: int) -> None:
    """Probed at every checkpoint boundary BEFORE the checkpoint is written:
    a kill here models dying between checkpoints, so the newest durable state
    is the previous boundary's."""
    plan = _active
    if plan is None or plan._killed or plan.kill_at_gen is None:
        return
    if generation >= plan.kill_at_gen:
        plan._killed = True
        # Flight-recorder composition: a kill about to happen is exactly the
        # moment the recorder exists for. The sigkill mode gets no Python
        # unwinding (no excepthook), so the dump MUST happen here; the
        # exception mode dumps here too so a harness that catches
        # InjectedCrash still leaves post-mortem evidence. Unarmed, this is
        # one None check (obs.recorder keeps no other state).
        from gol_tpu.obs import recorder

        recorder.trigger(
            f"fault-injection: kill at checkpoint boundary, "
            f"generation {generation} ({plan.kill_mode})"
        )
        if plan.kill_mode == "sigkill":
            import signal

            os.kill(os.getpid(), signal.SIGKILL)
        raise InjectedCrash(f"injected crash at checkpoint boundary, "
                            f"generation {generation}")
