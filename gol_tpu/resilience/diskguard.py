"""Disk-pressure watchdog: tiered degradation instead of shared-fate death.

A full partition disk used to be the one failure every durability contract
shared: the journal, the CAS tier, the checkpoints, and the metrics ring
all sit on it, and ENOSPC took the worker — and with it replay, the cache,
and admission — down together. ``DiskGuard`` watches free bytes (one
``statvfs`` per sampler tick, injected-fault-aware via ``fsio.free_bytes``)
and degrades in the order that sheds the most re-creatable state first:

- **level 1** (free < ``cas_bytes``): shed CAS *writes* — the cache is a
  pure accelerator; every entry is reconstructible by re-running the
  simulation. Reads, the memory tier, and everything else continue.
- **level 2** (free < ``checkpoint_bytes``): also shed checkpoint saves —
  checkpoints only buy restart time; the run still completes, and
  auto-resume falls back to the previous committed checkpoint.
- **level 3** (free < ``admission_bytes``): also refuse NEW job admission
  — ``POST /jobs`` answers **507** naming the partition and the free
  bytes. In-flight jobs still run and their done records still land (the
  reserve exists exactly so terminal appends have room; and a terminal
  append that loses the race anyway already survives ENOSPC — PR 2's
  ``journal_errors_total`` lane).

Recovery is automatic and hysteretic: a level is left only once free
bytes clear its watermark by ``hysteresis`` (default 25%), so a partition
oscillating at a watermark doesn't flap admission on and off.

Observability: ``disk_free_bytes`` / ``disk_pressure_level`` gauges and a
``disk_guard_transitions_total`` counter on the serving registry (they
fleet-merge like every serving series; the router merges free bytes by
MIN — the binding constraint — and the level by MAX), plus one record per
transition in the durable decision ring (the PR-10 history machinery,
exactly how autoscaler decisions and breaker transitions are journaled)
and an edge-triggered log line.

Clock discipline: ``time.perf_counter`` only (the injectable default),
used solely to timestamp transition records — never in any threshold
decision, which are pure byte comparisons.
"""

from __future__ import annotations

import logging
import time

from gol_tpu.resilience import fsio

logger = logging.getLogger(__name__)

# Degradation levels, in order. The NAME is what logs/rings/`gol top` show.
LEVEL_NAMES = ("ok", "shed-cas", "shed-checkpoints", "refuse-admission")
OK, SHED_CAS, SHED_CHECKPOINTS, REFUSE_ADMISSION = range(4)

STATE_PROVIDER = "disk_guard"


class DiskGuard:
    """Watermark state machine over one partition's free bytes.

    ``admission_bytes`` is the floor (refuse new work below it);
    ``checkpoint_bytes`` and ``cas_bytes`` default to 2x and 4x it, the
    shed-earlier tiers. ``free_fn`` injects the reading (tests pin it;
    the default consults the fault plan, then ``statvfs``)."""

    def __init__(
        self,
        path: str,
        admission_bytes: int,
        checkpoint_bytes: int | None = None,
        cas_bytes: int | None = None,
        *,
        hysteresis: float = 0.25,
        registry=None,
        history=None,
        free_fn=None,
        clock=time.perf_counter,
        partition: str | None = None,
    ):
        if admission_bytes < 1:
            raise ValueError(
                f"admission watermark must be >= 1 byte, got {admission_bytes}"
            )
        if hysteresis < 0:
            raise ValueError(f"hysteresis must be >= 0, got {hysteresis}")
        self.path = path
        self.partition = partition or path
        self.admission_bytes = int(admission_bytes)
        self.checkpoint_bytes = int(
            checkpoint_bytes if checkpoint_bytes is not None
            else 2 * admission_bytes
        )
        self.cas_bytes = int(
            cas_bytes if cas_bytes is not None else 4 * admission_bytes
        )
        if not (self.cas_bytes >= self.checkpoint_bytes
                >= self.admission_bytes):
            raise ValueError(
                "watermarks must degrade in order: cas_bytes "
                f"({self.cas_bytes}) >= checkpoint_bytes "
                f"({self.checkpoint_bytes}) >= admission_bytes "
                f"({self.admission_bytes})"
            )
        self.hysteresis = hysteresis
        self.registry = registry
        self.history = history
        self._free_fn = free_fn or (lambda: fsio.free_bytes(self.path))
        self._clock = clock
        self._level = OK
        self._free: int | None = None
        self.transitions = 0

    # -- the tick (gol-serve-sampler, or any caller's loop) -----------------

    def _watermark(self, level: int) -> int:
        return (self.cas_bytes, self.checkpoint_bytes,
                self.admission_bytes)[level - 1]

    def _deepest(self, free: int, scale: float) -> int:
        """The deepest level whose (scaled) watermark ``free`` is below."""
        for level in (REFUSE_ADMISSION, SHED_CHECKPOINTS, SHED_CAS):
            if free < self._watermark(level) * scale:
                return level
        return OK

    def tick(self) -> int:
        """Read free bytes, move the level, export, record transitions.
        Returns the (possibly new) level. A failing read holds the current
        level — a broken statvfs must not flap admission."""
        try:
            free = int(self._free_fn())
        except OSError as err:
            logger.warning("disk guard: free-bytes read failed on %s: %s",
                           self.path, err)
            return self._level
        self._free = free
        enter = self._deepest(free, 1.0)
        leave = self._deepest(free, 1.0 + self.hysteresis)
        if enter > self._level:
            new = enter  # degrade immediately: pressure is now
        elif leave < self._level:
            new = leave  # recover only past the hysteresis band
        else:
            new = self._level
        if new != self._level:
            self._transition(new, free)
        if self.registry is not None:
            self.registry.set_gauge("disk_free_bytes", free)
            self.registry.set_gauge("disk_pressure_level", self._level)
        return self._level

    def _transition(self, new: int, free: int) -> None:
        old, self._level = self._level, new
        self.transitions += 1
        log = logger.warning if new > old else logger.info
        log(
            "disk guard on %s: %s -> %s (%d bytes free; watermarks "
            "cas=%d ckpt=%d admission=%d)",
            self.partition, LEVEL_NAMES[old], LEVEL_NAMES[new], free,
            self.cas_bytes, self.checkpoint_bytes, self.admission_bytes,
        )
        if self.registry is not None:
            self.registry.inc("disk_guard_transitions_total")
        if self.history is not None:
            # The durable decision ring (obs/history.py) — the same record
            # shape the autoscaler journals its decisions with, so
            # `gol history-report` renders both.
            self.history.append({"diskguard": {
                "partition": self.partition,
                "from": LEVEL_NAMES[old],
                "to": LEVEL_NAMES[new],
                "free_bytes": free,
                "t": self._clock(),
            }})

    # -- the consumers' predicates -----------------------------------------

    @property
    def level(self) -> int:
        return self._level

    @property
    def level_name(self) -> str:
        return LEVEL_NAMES[self._level]

    @property
    def free_bytes(self) -> int | None:
        """The last tick's reading (None before the first tick)."""
        return self._free

    def allow_cas_writes(self) -> bool:
        return self._level < SHED_CAS

    def allow_checkpoints(self) -> bool:
        return self._level < SHED_CHECKPOINTS

    def refuse_admission(self) -> bool:
        return self._level >= REFUSE_ADMISSION

    def state(self) -> dict:
        """Flight-recorder state provider payload."""
        return {
            "partition": self.partition,
            "level": self._level,
            "level_name": self.level_name,
            "free_bytes": self._free,
            "transitions": self.transitions,
        }


__all__ = [
    "LEVEL_NAMES", "OK", "REFUSE_ADMISSION", "SHED_CAS", "SHED_CHECKPOINTS",
    "STATE_PROVIDER", "DiskGuard",
]
