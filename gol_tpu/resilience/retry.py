"""Unified bounded-retry policy for transient infrastructure failures.

One policy object replaces the ad-hoc retry shapes that had started to
accumulate per call site (the engine ladder's one-shot tunnel retry was the
first; advisor r4): bounded attempts, exponential backoff with a cap, and an
optional overall deadline. Callers keep their own *classification* of what is
retryable — a retry policy that guesses at semantics turns hard failures into
silent slow loops — and pass it as the ``retryable`` predicate.

The module is stdlib-only on purpose: ``gol_tpu.engine`` imports it at module
load, before jax-heavy modules, and the fault-injection harness imports it in
subprocesses that must start fast. (``gol_tpu.obs.registry`` — where every
taken retry is counted, so operators see transient-failure pressure building
before it turns hard — is stdlib-only by the same rule.)
"""

from __future__ import annotations

import dataclasses
import errno
import random
import socket
import threading
import time
from typing import Callable

from gol_tpu.obs import registry as _obs_registry

# Substrings that mark an IO failure as plausibly transient: tensorstore /
# kvstore surfaces absl status prose ("UNAVAILABLE", "DEADLINE_EXCEEDED",
# "ABORTED"), POSIX gives EAGAIN/EINTR shapes, and the fault harness tags its
# injected transients explicitly (resilience/faults.py). Matched lowercase
# against ``TypeName: message``.
_TRANSIENT_IO_MARKS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "connection reset",
    "broken pipe",
    "temporarily",
    "timed out",
    "try again",
    "injected transient",
)


def is_transient_io(err: BaseException) -> bool:
    """True when an IO error is worth retrying: infrastructure hiccups, not
    corrupt data or caller bugs. ``ValueError`` never classifies — a shape or
    format mismatch will not heal on retry no matter what its text says."""
    if isinstance(err, ValueError):
        return False
    text = f"{type(err).__name__}: {err}".lower()
    return any(mark in text for mark in _TRANSIENT_IO_MARKS)


def delivery_impossible(err: BaseException) -> bool:
    """Whether an HTTP-exchange failure GUARANTEES the request never
    reached the peer — the only failures safe to auto-retry (or re-route)
    for a NON-idempotent request like a job-creating POST. Anything
    ambiguous — a reset or timeout mid-exchange — may have been accepted
    and journaled on the far side; re-sending would run the board twice.
    Connection refused, DNS failure, and host/network-unreachable all fail
    before a byte is delivered. ``urllib.error.URLError`` wraps its cause
    in ``reason``; unwrap it so both raw-socket and urllib callers
    classify identically."""
    reason = getattr(err, "reason", err)
    if not isinstance(reason, BaseException):
        reason = err
    if isinstance(reason, (ConnectionRefusedError, socket.gaierror)):
        return True
    return isinstance(reason, OSError) and reason.errno in (
        errno.EHOSTUNREACH, errno.ENETUNREACH,
        getattr(errno, "EHOSTDOWN", errno.EHOSTUNREACH),
    )


class RetryBudget:
    """A token-bucket cap on RETRIES (not first attempts) across every
    site that shares the bucket.

    Unbudgeted exponential backoff is individually polite and collectively
    catastrophic: under a brownout every caller retries, and the retry
    traffic — each request amplified ``attempts``-fold — is exactly what
    keeps the browned-out service pinned down (a retry storm is a liveness
    bug wearing resilience's clothes). A budget bounds the amplification:
    each taken retry spends one token; tokens refill at ``refill_per_s``
    up to ``capacity``. When the bucket is empty, ``RetryPolicy.call``
    surfaces the ORIGINAL error immediately instead of retrying — under
    sustained failure the caller degrades to at-most-one-attempt, which is
    the behavior that lets the service come back.

    Thread-safe; clocked on ``time.monotonic`` like the policy deadline.
    """

    def __init__(self, capacity: float = 10.0, refill_per_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        if refill_per_s < 0:
            raise ValueError(
                f"refill_per_s must be >= 0, got {refill_per_s}"
            )
        self.capacity = float(capacity)
        self.refill_per_s = float(refill_per_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._last = clock()

    def _refill_locked(self) -> None:
        now = self._clock()
        self._tokens = min(
            self.capacity,
            self._tokens + (now - self._last) * self.refill_per_s,
        )
        self._last = now

    def try_take(self, tokens: float = 1.0) -> bool:
        """Spend ``tokens`` if available; False means the budget is
        exhausted and the caller must NOT retry."""
        with self._lock:
            self._refill_locked()
            if self._tokens < tokens:
                _obs_registry.default().inc("retry_budget_exhausted_total")
                return False
            self._tokens -= tokens
            remaining = self._tokens
        reg = _obs_registry.default()
        reg.set_gauge("retry_budget_remaining", round(remaining, 3))
        return True

    def remaining(self) -> float:
        with self._lock:
            self._refill_locked()
            return self._tokens


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts + exponential backoff + optional deadline.

    ``attempts`` counts total tries (attempts=1 means no retry); ``deadline``
    bounds the whole call in seconds — a retry that would *start* past the
    deadline is not taken and the last error propagates. ``base_delay=0``
    disables sleeping entirely (the engine's compile-ladder retry wants
    immediate re-dispatch: the tunnel helper either restarted or it didn't).
    ``jitter`` spreads each backoff uniformly over ``[1-j, 1+j]`` times the
    nominal delay: synchronized clients whose retries land in lockstep
    re-create the very spike they are backing off from. 0 (the default)
    keeps every pre-existing policy's sleeps byte-identical.
    """

    attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    deadline: float | None = None
    jitter: float = 0.0

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")

    def next_delay(self, delay: float) -> float:
        """The backoff step: the single copy of the growth rule, shared by
        ``call`` and batch retry loops that manage their own attempt state
        (io/ts_store._write_shards retries per-shard subsets)."""
        return min(max(delay, self.base_delay) * self.multiplier,
                   self.max_delay)

    def call(
        self,
        fn: Callable,
        *,
        retryable: Callable[[BaseException], bool] = is_transient_io,
        on_retry: Callable[[int, BaseException, float], None] | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        budget: "RetryBudget | None" = None,
        rng: Callable[[], float] = random.random,
    ):
        """Run ``fn`` under the policy; returns its value or raises its last
        error. ``on_retry(attempt, err, delay)`` fires before each backoff
        (attempt is 1-based), so callers can log without wrapping ``fn``.

        ``budget``: every retry (never the first attempt) must win a token
        from the shared bucket; an exhausted budget raises the error the
        attempt ACTUALLY produced — the original failure, not a synthetic
        budget error that would bury the diagnosis a retry storm needs."""
        start = clock()
        delay = self.base_delay
        err: BaseException | None = None
        for attempt in range(1, self.attempts + 1):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classification is the caller's
                err = e
                if attempt >= self.attempts or not retryable(e):
                    raise
                pause = delay
                if pause > 0 and self.jitter:
                    pause *= 1.0 + self.jitter * (2.0 * rng() - 1.0)
                if (
                    self.deadline is not None
                    and clock() - start + pause > self.deadline
                ):
                    # Guarded on the ACTUAL jittered pause (drawn above),
                    # not the nominal delay — an up-jittered sleep must
                    # not overrun the deadline the docstring promises.
                    raise
                if budget is not None and not budget.try_take():
                    raise
                _obs_registry.default().inc("retry_attempts_total")
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if pause > 0:
                    sleep(pause)
                delay = self.next_delay(delay)
        raise err  # pragma: no cover - loop always returns or raises


# Shared default for durable-storage operations (tensorstore open/write, the
# multihost create barrier, checkpoint payload IO): three tries, sub-second
# total backoff — a real outage should surface in seconds, not minutes.
DEFAULT_IO_RETRY = RetryPolicy(attempts=3, base_delay=0.05, multiplier=4.0,
                               max_delay=1.0)
