"""Unified bounded-retry policy for transient infrastructure failures.

One policy object replaces the ad-hoc retry shapes that had started to
accumulate per call site (the engine ladder's one-shot tunnel retry was the
first; advisor r4): bounded attempts, exponential backoff with a cap, and an
optional overall deadline. Callers keep their own *classification* of what is
retryable — a retry policy that guesses at semantics turns hard failures into
silent slow loops — and pass it as the ``retryable`` predicate.

The module is stdlib-only on purpose: ``gol_tpu.engine`` imports it at module
load, before jax-heavy modules, and the fault-injection harness imports it in
subprocesses that must start fast. (``gol_tpu.obs.registry`` — where every
taken retry is counted, so operators see transient-failure pressure building
before it turns hard — is stdlib-only by the same rule.)
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable

from gol_tpu.obs import registry as _obs_registry

# Substrings that mark an IO failure as plausibly transient: tensorstore /
# kvstore surfaces absl status prose ("UNAVAILABLE", "DEADLINE_EXCEEDED",
# "ABORTED"), POSIX gives EAGAIN/EINTR shapes, and the fault harness tags its
# injected transients explicitly (resilience/faults.py). Matched lowercase
# against ``TypeName: message``.
_TRANSIENT_IO_MARKS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "aborted",
    "connection reset",
    "broken pipe",
    "temporarily",
    "timed out",
    "try again",
    "injected transient",
)


def is_transient_io(err: BaseException) -> bool:
    """True when an IO error is worth retrying: infrastructure hiccups, not
    corrupt data or caller bugs. ``ValueError`` never classifies — a shape or
    format mismatch will not heal on retry no matter what its text says."""
    if isinstance(err, ValueError):
        return False
    text = f"{type(err).__name__}: {err}".lower()
    return any(mark in text for mark in _TRANSIENT_IO_MARKS)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded attempts + exponential backoff + optional deadline.

    ``attempts`` counts total tries (attempts=1 means no retry); ``deadline``
    bounds the whole call in seconds — a retry that would *start* past the
    deadline is not taken and the last error propagates. ``base_delay=0``
    disables sleeping entirely (the engine's compile-ladder retry wants
    immediate re-dispatch: the tunnel helper either restarted or it didn't).
    """

    attempts: int = 3
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    deadline: float | None = None

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be >= 0")

    def next_delay(self, delay: float) -> float:
        """The backoff step: the single copy of the growth rule, shared by
        ``call`` and batch retry loops that manage their own attempt state
        (io/ts_store._write_shards retries per-shard subsets)."""
        return min(max(delay, self.base_delay) * self.multiplier,
                   self.max_delay)

    def call(
        self,
        fn: Callable,
        *,
        retryable: Callable[[BaseException], bool] = is_transient_io,
        on_retry: Callable[[int, BaseException, float], None] | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
    ):
        """Run ``fn`` under the policy; returns its value or raises its last
        error. ``on_retry(attempt, err, delay)`` fires before each backoff
        (attempt is 1-based), so callers can log without wrapping ``fn``."""
        start = clock()
        delay = self.base_delay
        err: BaseException | None = None
        for attempt in range(1, self.attempts + 1):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 - classification is the caller's
                err = e
                if attempt >= self.attempts or not retryable(e):
                    raise
                if (
                    self.deadline is not None
                    and clock() - start + delay > self.deadline
                ):
                    raise
                _obs_registry.default().inc("retry_attempts_total")
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                if delay > 0:
                    sleep(delay)
                delay = self.next_delay(delay)
        raise err  # pragma: no cover - loop always returns or raises


# Shared default for durable-storage operations (tensorstore open/write, the
# multihost create barrier, checkpoint payload IO): three tries, sub-second
# total backoff — a real outage should surface in seconds, not minutes.
DEFAULT_IO_RETRY = RetryPolicy(attempts=3, base_delay=0.05, multiplier=4.0,
                               max_delay=1.0)
