"""The injectable filesystem shim under the tree's durable-state writers.

Every byte the journal (serve/jobs.py), the compaction snapshot
(serve/compaction.py), the CAS (cache/store.py), and the checkpoint
manifests (resilience/checkpoint.py) put on disk routes through one of the
helpers here, and every helper probes ``faults.on_fs_write`` first — so the
fault plan's exhaustion knobs (``enospc_after_bytes``, ``eio_every``,
``full_disk``) can drive each writer into ENOSPC/EIO deterministically,
from outside the process, without monkeypatching anything. Disarmed (no
plan installed — every production run), each probe is one ``None`` check.

``free_bytes`` is the disk-pressure watchdog's (resilience/diskguard.py)
one reading of the world: the real ``os.statvfs`` free bytes, unless the
plan pins a value (``disk_free_bytes=N`` / ``full_disk=1`` -> 0).

No clocks in this module, by design: exhaustion is about bytes, not time
(tests/test_lint.py pins the wall-clock ban on it anyway).
"""

from __future__ import annotations

import os

from gol_tpu.resilience import faults


def write_all(fd: int, data, site: str) -> None:
    """Write ``data`` to ``fd`` completely (``os.write`` may return short —
    large records, ENOSPC mid-way). The fault probe fires ONCE per logical
    record, before the first byte: a journal record either wholly precedes
    the injected exhaustion or wholly fails, matching how a real ENOSPC
    surfaces to an fsynced appender."""
    faults.on_fs_write(len(data), site)
    view = memoryview(data)
    while view:
        view = view[os.write(fd, view):]


def write_stream(f, data, site: str) -> None:
    """``f.write(data)`` behind the probe — the buffered-file counterpart
    of ``write_all`` for the staged-commit writers (CAS meta/sidecar,
    compaction snapshot, checkpoint manifest)."""
    faults.on_fs_write(len(data), site)
    f.write(data)


def free_bytes(path: str) -> int:
    """Free bytes available on ``path``'s filesystem (or the fault plan's
    pinned value). ``f_bavail`` — the unprivileged view — because the
    reserved-root blocks are exactly the ones this process cannot use."""
    pinned = faults.fs_free_bytes()
    if pinned is not None:
        return pinned
    st = os.statvfs(path)
    return st.f_bavail * st.f_frsize


__all__ = ["free_bytes", "write_all", "write_stream"]
