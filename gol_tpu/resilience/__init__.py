"""Crash-safety subsystem: atomic checkpoints, auto-resume, fault injection,
and the unified retry policy.

Import layering: ``retry`` and ``faults`` are stdlib-only (the engine imports
``retry`` at module load; fault-harness subprocesses import ``faults`` before
jax warms up). ``checkpoint`` pulls jax/numpy and is imported lazily by its
callers — do not re-export it here.
"""

from gol_tpu.resilience.faults import FaultPlan, InjectedCrash
from gol_tpu.resilience.retry import DEFAULT_IO_RETRY, RetryPolicy, is_transient_io

# Two-phase-commit staging suffixes, shared by every writer that stages an
# overwrite (io/packed_io, io/ts_store) and by the checkpoint GC that sweeps
# stale leftovers (resilience/checkpoint._gc) — one definition, or the sweep
# silently stops matching the writers.
STAGING_SUFFIX = ".inprogress"
REPLACED_SUFFIX = ".replaced"

__all__ = [
    "DEFAULT_IO_RETRY",
    "FaultPlan",
    "InjectedCrash",
    "REPLACED_SUFFIX",
    "RetryPolicy",
    "STAGING_SUFFIX",
    "is_transient_io",
]
