"""Crash-consistent checkpoints: fresh payload + atomically-committed manifest.

The reference programs' only durability story is output-is-a-valid-input-file;
a crash mid-run loses everything (SURVEY.md §5). The snapshot lanes improved
on that but not on crash *consistency*: a die mid-write could leave a torn
file as the newest state. This module closes that hole with the classic
write-ahead discipline:

1. the state is written to a **fresh payload path** (``ckpt-<gen>.<ext>``) —
   never over the previous checkpoint, so no write ever endangers the only
   durable copy;
2. a small JSON **manifest** (generation, similarity counter, grid geometry,
   per-shard CRC32 checksums, payload name) is written to a temp file,
   fsynced, and committed with ``os.replace`` — the one atomic step. A
   checkpoint exists iff its manifest does; torn payloads without a manifest
   are invisible garbage;
3. older checkpoints are garbage-collected only **after** the new manifest is
   durable (manifest deleted before its payload, so GC can never produce a
   manifest pointing at nothing).

Recovery (``restore``) walks manifests newest-first and returns the first
whose payload reads back and checksums clean. On multihost runs the
processes vote — one collective per candidate (``_collective_is_valid``),
pooling both readability and CRC coverage — so the run resumes from the
newest manifest *every* process can read, never a mix.

The payload encoding is pluggable (``PayloadCodec``): the packed lane stores
the bitpacked words as a sharded TensorStore zarr (io/ts_store.py), the byte
lane a text grid — both topology-independent, so a checkpoint taken on one
mesh restores on another (the elastic-reconfiguration property pinned by
tests/test_segments.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from typing import Any, Callable

import numpy as np

from gol_tpu.obs import registry as obs_registry, trace as obs_trace
from gol_tpu.resilience import REPLACED_SUFFIX, STAGING_SUFFIX, faults
from gol_tpu.resilience.retry import DEFAULT_IO_RETRY, RetryPolicy

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1
_MANIFEST_SUFFIX = ".manifest.json"
_PREFIX = "ckpt-"


@dataclasses.dataclass(frozen=True)
class PayloadCodec:
    """How checkpoint state bytes get to/from disk; the manager owns naming,
    manifests, and GC, the codec owns only the array encoding."""

    format: str  # recorded in the manifest; must match on restore
    suffix: str  # payload file/dir extension, e.g. ".zarr"
    write: Callable[[str, Any], None]  # (path, state) -> None
    read: Callable[[str], Any]  # path -> state (device array)
    # True when write/read run their own RetryPolicy internally (the zarr
    # codec): the manager then must not stack its outer retry on top.
    self_retrying: bool = False


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    generation: int  # completed generations (the reported count convention)
    counter: int  # similarity counter at that point
    path: str  # manifest path


@dataclasses.dataclass(frozen=True)
class _LoadedCheckpoint:
    """One process's collective-free view of a candidate checkpoint; the
    cluster-wide verdict comes from ``_collective_is_valid``."""

    state: Any
    info: CheckpointInfo
    local_ok: bool  # every locally-checked block CRC-matched
    verified: frozenset  # manifest keys this process actually checked
    recorded: frozenset  # every key the manifest records


def _block_key(r0: int, r1: int, c0: int, c1: int) -> str:
    return f"{r0}:{r1},{c0}:{c1}"


def _parse_key(key: str) -> tuple[int, int, int, int]:
    rows, cols = key.split(",")
    r0, r1 = (int(x) for x in rows.split(":"))
    c0, c1 = (int(x) for x in cols.split(":"))
    return r0, r1, c0, c1


_LIMB_BITS = 16
_LIMB_COUNT = 4
_MASK64 = (1 << 64) - 1


def _fingerprint_limbs(partial: int) -> np.ndarray:
    """Split a 64-bit fingerprint partial into four 16-bit limbs in int32:
    jax may be running without x64, and an allgather payload silently
    downcast to int32 must stay lossless. (Two 31-bit halves would drop bits
    62-63 and make the merged fingerprint decomposition-dependent.)"""
    return np.asarray(
        [(partial >> (_LIMB_BITS * i)) & 0xFFFF for i in range(_LIMB_COUNT)],
        np.int32)


def _merge_fingerprint_limbs(everyone) -> int:
    """Sum per-limb, then fold the carries in Python ints so the result is
    EXACTLY ``sum(partials) mod 2**64`` — the property that makes the same
    state fingerprint identically under ANY process decomposition."""
    sums = np.asarray(everyone, np.int64).reshape(-1, _LIMB_COUNT).sum(axis=0)
    return sum(int(s) << (_LIMB_BITS * i) for i, s in enumerate(sums)) & _MASK64


def positional_digest(blocks) -> int:
    """The positional-hash core of ``run_fingerprint``, numpy-only: each
    cell of each ``((r0, r1, c0, c1), block)`` piece contributes
    ``value * mix(global_row, global_col)``, summed mod 2^64. Commutative
    and per-cell, so the SAME state digests identically under ANY block
    decomposition. Split out (jax-free) so the result cache
    (gol_tpu/cache/fingerprint.py) keys boards with the exact same limb
    math the checkpoint identity uses — including in the jax-free fleet
    router."""
    local = np.uint64(0)
    for (r0, r1, c0, c1), block in blocks:
        rr = np.arange(r0, r1, dtype=np.uint64)[:, None]
        cc = np.arange(c0, c1, dtype=np.uint64)[None, :]
        mix = (rr + np.uint64(1)) * np.uint64(0x9E3779B97F4A7C15) \
            ^ (cc + np.uint64(1)) * np.uint64(0xC2B2AE3D27D4EB4F)
        with np.errstate(over="ignore"):
            local += (block.astype(np.uint64) * mix).sum(dtype=np.uint64)
    return int(local)


def state_blocks(state):
    """``(index ranges, ndarray)`` pieces of a (possibly sharded) 2-D state
    — the decomposition ``positional_digest`` and the CRC pass consume."""
    h, w = state.shape
    shards = getattr(state, "addressable_shards", None)
    if shards is None:
        return [((0, h, 0, w), np.ascontiguousarray(np.asarray(state)))]
    blocks = []
    for shard in shards:
        rows, cols = shard.index[0], shard.index[1]
        r0, r1, _ = rows.indices(h)
        c0, c1, _ = cols.indices(w)
        blocks.append(((r0, r1, c0, c1), np.asarray(shard.data)))
    return blocks


def run_fingerprint(state, tag: str = "") -> str:
    """Cluster-stable fingerprint of a run's identity, computed from its
    INITIAL state as a positional hash: each cell contributes
    ``value * mix(global_row, global_col)`` and the contributions are summed
    (mod 2^64) over every process's shards. The sum is commutative and
    per-cell, so the SAME state yields the same fingerprint under ANY shard
    decomposition — a rerun on a different mesh still recognizes its own
    checkpoints (the topology-independent-restore property), while a
    different input cannot collide by rearrangement. Recorded in each
    manifest and checked on restore, so a checkpoint directory reused with a
    different input never silently hands an old run's state to a new run.
    ``tag`` folds in non-derivable config identity (convention)."""
    import jax

    total = positional_digest(state_blocks(state))
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        everyone = multihost_utils.process_allgather(_fingerprint_limbs(total))
        total = _merge_fingerprint_limbs(everyone)
    return f"{total:016x}" + (f":{tag}" if tag else "")


def _shard_checksums(state) -> dict[str, int]:
    """CRC32 per addressable shard, keyed by the block's index ranges in the
    stored array — geometry-keyed so restore can re-verify under ANY
    topology (regions are recomputed by slicing, not by shard identity)."""
    h, w = state.shape
    shards = getattr(state, "addressable_shards", None)
    if shards is None:  # plain ndarray
        block = np.ascontiguousarray(np.asarray(state))
        return {_block_key(0, h, 0, w): zlib.crc32(block)}
    sums = {}
    for shard in shards:
        rows, cols = shard.index[0], shard.index[1]
        r0, r1, _ = rows.indices(h)
        c0, c1, _ = cols.indices(w)
        block = np.ascontiguousarray(np.asarray(shard.data))
        sums[_block_key(r0, r1, c0, c1)] = zlib.crc32(block)
    return sums


def _allgather_json(obj) -> list:
    """Allgather one JSON-serializable value per process, returned in process
    order. The payload rides as a length-prefixed uint8 blob: jax may be
    running without x64, and int32 lengths + uint8 bytes survive any
    downcast policy losslessly."""
    from jax.experimental import multihost_utils

    blob = np.frombuffer(json.dumps(obj, sort_keys=True).encode(), np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray(len(blob), np.int32))).ravel()
    padded = np.zeros((max(int(lens.max()), 1),), np.uint8)
    padded[: len(blob)] = blob
    everyone = np.asarray(multihost_utils.process_allgather(padded))
    return [json.loads(bytes(everyone[i, : int(n)]).decode())
            for i, n in enumerate(lens)]


def _allgather_checksums(sums: dict[str, int]) -> dict[str, int]:
    """Union of every process's shard checksums. The manifest is committed
    by the lead alone; without this merge it would record only the lead's
    addressable blocks and peer-owned shards would restore UNVERIFIED."""
    import jax

    if jax.process_count() == 1:
        return sums
    merged: dict[str, int] = {}
    for peer in _allgather_json(sums):
        merged.update(peer)
    return merged


def _verify_checksums(state, checksums: dict[str, int]) -> tuple[bool, set[str]]:
    """LOCAL re-verification: ``(every checked block matched, keys checked)``.

    Single-process: every block is re-sliced from the host copy, so any
    writer decomposition verifies and the returned key set covers the whole
    manifest. Multihost: a recorded block is checked when this process's
    shards tile its region — assembled across shards if it straddles them
    (elastic restores onto a finer local mesh still verify) — and skipped
    when part of it lives on a peer. Pooling which keys ANY process
    verified happens in ``CheckpointManager._collective_is_valid``, NOT
    here: this function must stay collective-free so a process that fails
    anywhere in ``_load`` can skip it without desynchronizing its peers'
    collectives.
    """
    import jax

    h, w = state.shape
    ok = True
    verified: set[str] = set()
    if jax.process_count() == 1:
        host = np.asarray(state)
        for key, want in checksums.items():
            r0, r1, c0, c1 = _parse_key(key)
            got = zlib.crc32(np.ascontiguousarray(host[r0:r1, c0:c1]))
            if got != int(want):
                ok = False
            else:
                verified.add(key)
        return ok, verified
    blocks = []
    seen_bounds = set()  # replicated shardings repeat bounds; count each once
    for shard in state.addressable_shards:
        rows, cols = shard.index[0], shard.index[1]
        sr0, sr1, _ = rows.indices(h)
        sc0, sc1, _ = cols.indices(w)
        if (sr0, sr1, sc0, sc1) not in seen_bounds:
            seen_bounds.add((sr0, sr1, sc0, sc1))
            blocks.append(((sr0, sr1, sc0, sc1), shard))
    hosted: dict[int, np.ndarray] = {}  # lazy per-shard device->host copies
    for key, want in checksums.items():
        r0, r1, c0, c1 = _parse_key(key)
        pieces, covered = [], 0
        for i, ((sr0, sr1, sc0, sc1), _) in enumerate(blocks):
            ir0, ir1 = max(r0, sr0), min(r1, sr1)
            ic0, ic1 = max(c0, sc0), min(c1, sc1)
            if ir0 < ir1 and ic0 < ic1:
                pieces.append((i, (ir0, ir1, ic0, ic1), (sr0, sc0)))
                covered += (ir1 - ir0) * (ic1 - ic0)
        if covered != (r1 - r0) * (c1 - c0):
            continue  # part of the block lives on a peer; the vote pools this
        for i, _, _ in pieces:
            if i not in hosted:
                hosted[i] = np.asarray(blocks[i][1].data)
        region = np.empty((r1 - r0, c1 - c0), hosted[pieces[0][0]].dtype)
        for i, (ir0, ir1, ic0, ic1), (sr0, sc0) in pieces:
            region[ir0 - r0 : ir1 - r0, ic0 - c0 : ic1 - c0] = \
                hosted[i][ir0 - sr0 : ir1 - sr0, ic0 - sc0 : ic1 - sc0]
        if zlib.crc32(np.ascontiguousarray(region)) != int(want):
            ok = False
        else:
            verified.add(key)
    return ok, verified


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _commit_file(path: str, data: bytes) -> None:
    """Write ``data`` durably at ``path`` via tmp + fsync + atomic rename."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _rmtree_or_file(path: str) -> None:
    if os.path.isdir(path):
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    elif os.path.exists(path):
        try:
            os.remove(path)
        except OSError:
            pass


class CheckpointManager:
    """Atomic checkpoints for one run's geometry in one directory.

    ``keep`` retains that many newest checkpoints (>=1): the window a slow
    shared filesystem gets to make a manifest readable on every host before
    the vote falls back to the previous one.
    """

    def __init__(
        self,
        directory: str,
        *,
        height: int,
        width: int,
        codec: PayloadCodec,
        keep: int = 2,
        retry: RetryPolicy = DEFAULT_IO_RETRY,
        run_fingerprint: str | None = None,
        guard=None,
    ):
        if keep < 1:
            raise ValueError(f"checkpoint keep must be >= 1, got {keep}")
        self.directory = directory
        self.height = height
        self.width = width
        self.codec = codec
        self.keep = keep
        self.retry = retry
        self.run_fingerprint = run_fingerprint
        # The disk-pressure watchdog (resilience/diskguard.DiskGuard) or
        # None: under its shed-checkpoints tier, saves are skipped loudly
        # — a checkpoint only buys restart time; the run still completes,
        # and auto-resume falls back to the previous committed one.
        self.guard = guard
        # Serializes ``--checkpoint-keep`` pruning against payload writes:
        # the async writer (gol_tpu/pipeline) runs ``_write_payload`` on a
        # background thread, and a prune sweeping the directory while a
        # codec stages payload files there could collect the in-flight
        # write's staging as "stale". The deferred-commit protocol already
        # orders the two on the happy path; this lock makes the ordering
        # STRUCTURAL — any caller overlap serializes instead of corrupting.
        self._io_lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)

    # -- naming --------------------------------------------------------------

    def _manifest_path(self, generation: int) -> str:
        return os.path.join(self.directory,
                            f"{_PREFIX}{generation:08d}{_MANIFEST_SUFFIX}")

    def _payload_name(self, generation: int) -> str:
        return f"{_PREFIX}{generation:08d}{self.codec.suffix}"

    def _list_generations(self) -> list[int]:
        """Generations with a committed manifest, newest first."""
        gens = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if name.startswith(_PREFIX) and name.endswith(_MANIFEST_SUFFIX):
                digits = name[len(_PREFIX) : -len(_MANIFEST_SUFFIX)]
                if digits.isdigit():
                    gens.append(int(digits))
        return sorted(gens, reverse=True)

    # -- save ----------------------------------------------------------------

    def save(self, state, generation: int, counter: int) -> str:
        """Checkpoint ``state`` after ``generation`` completed generations.

        Returns the manifest path. Ordering is the crash-safety argument:
        payload first (fresh path), manifest committed atomically second, GC
        of older checkpoints last — a crash at ANY point leaves the previous
        checkpoint intact and discoverable.

        Every outcome is counted in the global obs registry (saves /
        failures), and the whole save is one trace span — so a flight-
        recorder dump after a crash shows whether the process died inside a
        checkpoint and which generation it was committing.
        """
        reg = obs_registry.default()
        if self.sheds_save():
            return self._manifest_path(generation)
        with obs_trace.span("checkpoint.save", generation=int(generation)):
            try:
                path = self._save(state, generation, counter)
            except BaseException:
                # BaseException: InjectedCrash must be counted too — the
                # recorder dump that follows should show the failed save.
                reg.inc("checkpoint_save_failures_total")
                raise
        reg.inc("checkpoint_saves_total")
        return path

    def sheds_save(self) -> bool:
        """Disk-pressure shed decision for one boundary (consumed by BOTH
        the sync path above and the async writer's): tick the guard, and
        under its shed-checkpoints tier skip the save loudly — counted, so
        an operator sees checkpoints thinning before the disk is gone."""
        if self.guard is None:
            return False
        self.guard.tick()
        if self.guard.allow_checkpoints():
            return False
        obs_registry.default().inc("checkpoint_sheds_total")
        logger.warning(
            "checkpoint shed: %s is under disk pressure (%s, %s bytes "
            "free); the previous committed checkpoint remains the restore "
            "point", self.directory, self.guard.level_name,
            self.guard.free_bytes,
        )
        return True

    def _save(self, state, generation: int, counter: int) -> str:
        """The synchronous save: the four staged phases back to back.

        The async writer (gol_tpu/pipeline/writer.py) drives the SAME four
        phases but defers ``_commit_manifest`` to the next boundary, running
        ``_write_payload`` on a background thread against a HostSnapshot —
        which is why the phases are split out rather than inlined here."""
        faults.on_checkpoint_boundary(generation)
        if self._already_committed(generation):
            # A resumed run re-reached a boundary it had already committed;
            # the engine is bit-exact, so the existing checkpoint IS this
            # state — rewriting it would put a valid manifest over a payload
            # mid-rewrite, the one window the ordering otherwise closes.
            return self._manifest_path(generation)
        self._sweep_stale(generation)
        local_sums, write_err = self._write_payload(state, generation)
        path = self._commit_manifest(
            tuple(state.shape), generation, counter, local_sums, write_err
        )
        self.prune()
        return path

    def _already_committed(self, generation: int) -> bool:
        """Whether a valid checkpoint for ``generation`` already exists."""
        import jax

        manifest_path = self._manifest_path(generation)
        if jax.process_count() > 1:
            # The skip must be a COLLECTIVE decision: a lone process skipping
            # (or sweeping the shared manifest) while peers rewrite would
            # desynchronize the barrier sequence below and deadlock the
            # cluster. The exists check only decides whether to ATTEMPT the
            # local load (a first save's manifest is expected to be missing
            # — _load would log a spurious "invalid, trying older" warning);
            # every process reaches _collective_is_valid's one collective
            # regardless of what its local view of the shared FS says.
            # Unanimous yes -> all skip; otherwise all rewrite.
            return self._collective_is_valid(
                self._load(generation)
                if os.path.exists(manifest_path) else None)
        return (
            os.path.exists(manifest_path)
            and self._load(generation) is not None
        )

    def _sweep_stale(self, generation: int) -> None:
        """Clear invalid leftovers at this generation's paths before writing."""
        import jax

        multihost = jax.process_count() > 1
        if not multihost or jax.process_index() == 0:
            _rmtree_or_file(self._manifest_path(generation))  # invalid leftover
            _rmtree_or_file(os.path.join(
                self.directory, self._payload_name(generation)
            ))  # torn orphan from a crashed save
        if multihost:
            # The lead's sweep of shared-FS leftovers must finish before any
            # peer starts writing shards into the payload path.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.clean:{self.directory}:{generation}")

    def _write_payload(self, state, generation: int):
        """Write the payload and checksum it: ``(local_sums, write_err)``.

        Single-process failures raise; multihost failures are RETURNED so
        the caller's commit phase can vote on them before any collective.
        ``state`` may be a live device array or a ``pipeline.HostSnapshot``
        — both expose the shard walk the codecs and ``_shard_checksums``
        consume, producing byte-identical payloads and CRC blocks.
        """
        import jax

        multihost = jax.process_count() > 1
        payload_path = os.path.join(
            self.directory, self._payload_name(generation)
        )
        write_err: Exception | None = None
        local_sums: dict[str, int] = {}
        try:
            # Serialized against prune(): the async writer runs this on a
            # background thread, and the codecs stage files in the
            # checkpoint directory mid-write — a concurrent prune must
            # never sweep them as stale leftovers.
            with self._io_lock:
                if multihost or self.codec.self_retrying:
                    # No outer retry. Multihost: the zarr codec's write
                    # contains collective barriers, and ONE process
                    # re-entering them while peers have moved on joins the
                    # wrong barrier. Self-retrying codecs: stacking this
                    # policy on the codec's own would cube the
                    # time-to-failure of a persistent outage.
                    self.codec.write(payload_path, state)
                else:
                    self.retry.call(
                        lambda: self.codec.write(payload_path, state))
                faults.on_payload_write(payload_path)
            local_sums = _shard_checksums(state)
        except Exception as e:
            if not multihost:
                raise
            write_err = e
        return local_sums, write_err

    def _commit_manifest(self, state_shape, generation: int, counter: int,
                         local_sums: dict[str, int],
                         write_err: Exception | None) -> str:
        """Vote, merge checksums, commit the manifest atomically, GC.

        The only phase that makes a checkpoint EXIST (a checkpoint exists
        iff its manifest does) — the async writer defers exactly this call
        to the next boundary, so its vote ordering and barriers always run
        on the main thread, in program order."""
        import jax

        multihost = jax.process_count() > 1
        manifest_path = self._manifest_path(generation)
        payload_name = self._payload_name(generation)
        if multihost:
            # A process whose shard write (or checksum pass) failed must not
            # leave its peers parked in the allgather/commit barriers below
            # until the distributed-runtime timeout: vote on success first,
            # the failing process voting False before re-raising, so the
            # whole cluster abandons this checkpoint together (previous one
            # stays intact and discoverable).
            from gol_tpu.parallel.collectives import host_all_agree

            if not host_all_agree(write_err is None):
                if write_err is not None:
                    raise write_err
                raise RuntimeError(
                    "checkpoint abandoned: a peer process failed to write "
                    f"its payload shards for generation {generation}")
        # Merged across processes AFTER the write (a fixed point in the
        # collective order): the lead-committed manifest must carry EVERY
        # process's block CRCs or peer shards would restore unverified.
        checksums = _allgather_checksums(local_sums)
        manifest = {
            "format_version": FORMAT_VERSION,
            "generation": int(generation),
            "counter": int(counter),
            "height": int(self.height),
            "width": int(self.width),
            "state_shape": [int(d) for d in state_shape],
            "payload": payload_name,
            "payload_format": self.codec.format,
            "run_fingerprint": self.run_fingerprint,
            "checksums": checksums,
            "created_unix": time.time(),
        }
        data = json.dumps(manifest, indent=1).encode()
        if multihost:
            # Peers' payload shards must be durable before ANY process
            # commits a manifest claiming them; only the lead commits. The
            # barriers are never retried: a process unilaterally re-entering
            # a barrier its peers already passed can only join the WRONG
            # barrier — a transient collective failure is fatal by design.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.commit:{self.directory}:{generation}")
            if jax.process_index() == 0:
                _commit_file(manifest_path, data)
            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.committed:{self.directory}:{generation}")
        else:
            _commit_file(manifest_path, data)
        return manifest_path

    def prune(self) -> None:
        """``--checkpoint-keep`` pruning, as its own phase BEHIND the
        commit: the sync save runs it right after ``_commit_manifest``;
        the async writer runs it after the DEFERRED commit lands (its
        drain), never concurrently with the background payload write —
        and the ``_io_lock`` shared with ``_write_payload`` makes that
        ordering structural rather than conventional. The ``prune`` fault
        boundary (kill_during_prune) fires inside, between a doomed
        checkpoint's manifest delete and its payload delete."""
        with self._io_lock:
            self._gc()

    def _manifest_is_foreign(self, generation: int) -> bool:
        """True when the manifest readably belongs to a DIFFERENT run (its
        fingerprint exists and mismatches ours): garbage to this run, and it
        must not shadow (or out-sort) this run's own checkpoints."""
        if self.run_fingerprint is None:
            return False
        try:
            with open(self._manifest_path(generation)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False  # unreadable != foreign; restore() handles invalid
        return manifest.get("run_fingerprint") != self.run_fingerprint

    def _gc(self) -> None:
        """Drop all but the ``keep`` newest of THIS run's checkpoints,
        manifest first (so a crash mid-GC can only orphan a payload, never
        dangle a manifest); foreign-run leftovers in a reused directory are
        garbage outright. Then sweep tmp/staging files and manifest-less
        payloads older than the newest."""
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        gens, doomed = [], []
        for gen in self._list_generations():
            (doomed if self._manifest_is_foreign(gen) else gens).append(gen)
        doomed.extend(gens[self.keep :])
        for gen in doomed:
            manifest_path = self._manifest_path(gen)
            # A foreign manifest may name a payload from a DIFFERENT lane
            # (other codec suffix); deleting by this run's naming would leak
            # it as an invisible orphan once its manifest is gone. Trust the
            # manifest's own record first, basename-d so a crafted payload
            # field can never reach outside the checkpoint dir.
            payload_name = self._payload_name(gen)
            try:
                with open(manifest_path) as f:
                    payload_name = os.path.basename(
                        json.load(f).get("payload", payload_name))
            except (OSError, ValueError):
                pass  # unreadable manifest: fall back to this lane's name
            _rmtree_or_file(manifest_path)
            # Manifest-first ordering: a crash HERE (the kill_during_prune
            # fault boundary) orphans a payload, never dangles a manifest —
            # the orphan is invisible to restore and swept by the next
            # prune's manifest-less-payload pass.
            faults.on_checkpoint_prune(manifest_path)
            _rmtree_or_file(os.path.join(self.directory, payload_name))
        newest = gens[0] if gens else None
        live = {self._payload_name(g) for g in gens[: self.keep]}
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.endswith(".tmp") or (
                name.startswith(_PREFIX)
                and name.endswith((STAGING_SUFFIX, REPLACED_SUFFIX))
            ):
                # .tmp: torn manifest commits; .inprogress/.replaced: staging
                # leftovers from a codec writer (packed_io/ts_store) crashed
                # mid-payload. Saves are serialized within a run and GC runs
                # after the commit barrier, so anything still staged is stale.
                _rmtree_or_file(path)
            elif (
                name.startswith(_PREFIX)
                and name.endswith(self.codec.suffix)
                and name not in live
            ):
                digits = name[len(_PREFIX) : -len(self.codec.suffix)]
                if digits.isdigit() and newest is not None and int(digits) <= newest:
                    _rmtree_or_file(path)

    # -- restore -------------------------------------------------------------

    def _load(self, generation: int) -> _LoadedCheckpoint | None:
        """One checkpoint's LOCAL view, or None if anything about it —
        manifest JSON, geometry, payload read, (single-process) checksums —
        fails to verify.

        Collective-free by contract: processes fail here at different points
        (or skip the call entirely), so any collective inside would pair
        with a DIFFERENT collective on a peer and hang or corrupt the
        exchange. The cluster-wide verdict is one unconditional collective
        in ``_collective_is_valid``; on multihost a local CRC mismatch is
        therefore carried in ``local_ok`` rather than raised.
        """
        try:
            with open(self._manifest_path(generation)) as f:
                manifest = json.load(f)
            if manifest.get("format_version") != FORMAT_VERSION:
                raise ValueError(
                    f"unknown format_version {manifest.get('format_version')}")
            if (manifest["height"], manifest["width"]) != (self.height, self.width):
                raise ValueError(
                    f"geometry {manifest['height']}x{manifest['width']} != "
                    f"run geometry {self.height}x{self.width}")
            if manifest["payload_format"] != self.codec.format:
                raise ValueError(
                    f"payload format {manifest['payload_format']!r} != "
                    f"this lane's {self.codec.format!r}")
            if (
                self.run_fingerprint is not None
                and manifest.get("run_fingerprint") != self.run_fingerprint
            ):
                raise ValueError(
                    f"checkpoint belongs to a different run (fingerprint "
                    f"{manifest.get('run_fingerprint')!r} != this run's "
                    f"{self.run_fingerprint!r}) — stale checkpoint dir?")
            payload = os.path.join(self.directory, manifest["payload"])
            if self.codec.self_retrying:
                state = self.codec.read(payload)
            else:
                state = self.retry.call(lambda: self.codec.read(payload))
            if tuple(state.shape) != tuple(manifest["state_shape"]):
                raise ValueError(
                    f"payload shape {tuple(state.shape)} != manifest "
                    f"{tuple(manifest['state_shape'])}")
            import jax

            ok, verified = _verify_checksums(state, manifest["checksums"])
            if jax.process_count() == 1 and not ok:
                raise ValueError("shard checksum mismatch")
            info = CheckpointInfo(
                generation=int(manifest["generation"]),
                counter=int(manifest["counter"]),
                path=self._manifest_path(generation),
            )
            return _LoadedCheckpoint(
                state=state,
                info=info,
                local_ok=ok,
                verified=frozenset(verified),
                recorded=frozenset(manifest["checksums"]),
            )
        except Exception as e:  # noqa: BLE001 - any defect means "not valid"
            logger.warning(
                "checkpoint %s/%s%08d invalid, trying older: %s: %s",
                self.directory, _PREFIX, generation, type(e).__name__, e)
            return None

    def _collective_is_valid(self, loaded: _LoadedCheckpoint | None) -> bool:
        """Cluster-wide verdict on one candidate checkpoint, via ONE
        collective that every process reaches exactly once — including
        processes whose ``_load`` returned None, which vote False here
        instead of skipping the exchange (the skip would pair a peer's
        allgather with whatever collective this process runs next).

        The verdict requires every process to have loaded and locally
        CRC-matched the checkpoint. Coverage is then pooled: a recorded
        block NO process could tile from its shards (it straddles a process
        boundary on this topology — e.g. a single-host checkpoint restored
        on a multi-host mesh) is loudly logged rather than silently passing
        as verified; it does NOT fail the restore, because refusing valid
        on-disk state (and restarting from scratch while GC churns) is
        strictly worse than restoring payload bytes every process read
        successfully."""
        import jax

        if jax.process_count() == 1:
            return loaded is not None  # _load already enforced checksums
        ok = loaded is not None and loaded.local_ok
        verified = sorted(loaded.verified) if loaded is not None else []
        votes = _allgather_json([bool(ok), verified])
        if not all(bool(v[0]) for v in votes):
            return False
        covered: set[str] = set()
        for _, keys in votes:
            covered.update(keys)
        # All processes loaded OK, so every manifest copy (hence `recorded`)
        # is identical and this log fires identically everywhere.
        unverified = loaded.recorded - covered
        if unverified:
            logger.warning(
                "restoring with %d/%d recorded block(s) CRC-UNVERIFIED: "
                "they straddle process boundaries on this topology (written "
                "on a different mesh); every process read its payload "
                "shards successfully", len(unverified), len(loaded.recorded))
        return True

    def _global_candidates(self) -> list[int]:
        """Union of every process's manifest generations, newest first: a
        manifest only one host can list must still get voted on (and down)."""
        import jax

        local = self._list_generations()
        if jax.process_count() == 1:
            return local
        from jax.experimental import multihost_utils

        # Fixed-size exchange: newest 2*keep generations, -1 padded.
        width = max(2 * self.keep, 4)
        mine = np.full((width,), -1, np.int64)
        mine[: min(len(local), width)] = local[:width]
        everyone = np.asarray(multihost_utils.process_allgather(mine))
        gens = {int(g) for g in everyone.ravel() if int(g) >= 0}
        return sorted(gens, reverse=True)

    def restore(self, max_generation: int | None = None):
        """Newest checkpoint every process can read, or None.

        Walks candidates newest-first; each process validates locally
        (collective-free ``_load``) and the cluster votes with one
        collective per candidate (``_collective_is_valid``) — a manifest any
        process cannot read and fully verify is skipped by ALL of them, so
        no two processes ever resume from different generations. Returns
        ``(state, info)``.

        ``max_generation`` skips checkpoints past it (deterministically, so
        no vote is needed): a rerun with a REDUCED --gen-limit resumes from
        the newest checkpoint at or below the limit — any such checkpoint is
        an exact prefix of the shorter run — or starts fresh.
        """
        reg = obs_registry.default()
        with obs_trace.span("checkpoint.restore"):
            for gen in self._global_candidates():
                if max_generation is not None and gen > max_generation:
                    continue
                loaded = self._load(gen)
                if self._collective_is_valid(loaded):
                    logger.info("auto-resume: restored checkpoint at "
                                "generation %d from %s",
                                loaded.info.generation, loaded.info.path)
                    reg.inc("checkpoint_restores_total")
                    return loaded.state, loaded.info
                reg.inc("checkpoint_restore_rejected_total")
                if loaded is not None:
                    logger.warning(
                        "checkpoint generation %d readable here but not "
                        "verified on every process; falling back to an "
                        "older one", gen)
        return None
