"""Crash-consistent checkpoints: fresh payload + atomically-committed manifest.

The reference programs' only durability story is output-is-a-valid-input-file;
a crash mid-run loses everything (SURVEY.md §5). The snapshot lanes improved
on that but not on crash *consistency*: a die mid-write could leave a torn
file as the newest state. This module closes that hole with the classic
write-ahead discipline:

1. the state is written to a **fresh payload path** (``ckpt-<gen>.<ext>``) —
   never over the previous checkpoint, so no write ever endangers the only
   durable copy;
2. a small JSON **manifest** (generation, similarity counter, grid geometry,
   per-shard CRC32 checksums, payload name) is written to a temp file,
   fsynced, and committed with ``os.replace`` — the one atomic step. A
   checkpoint exists iff its manifest does; torn payloads without a manifest
   are invisible garbage;
3. older checkpoints are garbage-collected only **after** the new manifest is
   durable (manifest deleted before its payload, so GC can never produce a
   manifest pointing at nothing).

Recovery (``restore``) walks manifests newest-first and returns the first
whose payload reads back and checksums clean. On multihost runs the processes
vote — ``parallel/collectives.host_all_agree`` — so the run resumes from the
newest manifest *every* process can read, never a mix.

The payload encoding is pluggable (``PayloadCodec``): the packed lane stores
the bitpacked words as a sharded TensorStore zarr (io/ts_store.py), the byte
lane a text grid — both topology-independent, so a checkpoint taken on one
mesh restores on another (the elastic-reconfiguration property pinned by
tests/test_segments.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
import zlib
from typing import Any, Callable

import numpy as np

from gol_tpu.resilience import REPLACED_SUFFIX, STAGING_SUFFIX, faults
from gol_tpu.resilience.retry import DEFAULT_IO_RETRY, RetryPolicy

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1
_MANIFEST_SUFFIX = ".manifest.json"
_PREFIX = "ckpt-"


@dataclasses.dataclass(frozen=True)
class PayloadCodec:
    """How checkpoint state bytes get to/from disk; the manager owns naming,
    manifests, and GC, the codec owns only the array encoding."""

    format: str  # recorded in the manifest; must match on restore
    suffix: str  # payload file/dir extension, e.g. ".zarr"
    write: Callable[[str, Any], None]  # (path, state) -> None
    read: Callable[[str], Any]  # path -> state (device array)
    # True when write/read run their own RetryPolicy internally (the zarr
    # codec): the manager then must not stack its outer retry on top.
    self_retrying: bool = False


@dataclasses.dataclass(frozen=True)
class CheckpointInfo:
    generation: int  # completed generations (the reported count convention)
    counter: int  # similarity counter at that point
    path: str  # manifest path


def _block_key(r0: int, r1: int, c0: int, c1: int) -> str:
    return f"{r0}:{r1},{c0}:{c1}"


def _parse_key(key: str) -> tuple[int, int, int, int]:
    rows, cols = key.split(",")
    r0, r1 = (int(x) for x in rows.split(":"))
    c0, c1 = (int(x) for x in cols.split(":"))
    return r0, r1, c0, c1


def run_fingerprint(state, tag: str = "") -> str:
    """Cluster-stable fingerprint of a run's identity, computed from its
    INITIAL state as a positional hash: each cell contributes
    ``value * mix(global_row, global_col)`` and the contributions are summed
    (mod 2^64) over every process's shards. The sum is commutative and
    per-cell, so the SAME state yields the same fingerprint under ANY shard
    decomposition — a rerun on a different mesh still recognizes its own
    checkpoints (the topology-independent-restore property), while a
    different input cannot collide by rearrangement. Recorded in each
    manifest and checked on restore, so a checkpoint directory reused with a
    different input never silently hands an old run's state to a new run.
    ``tag`` folds in non-derivable config identity (convention)."""
    import jax

    h, w = state.shape
    shards = getattr(state, "addressable_shards", None)
    if shards is None:
        blocks = [((0, h, 0, w), np.ascontiguousarray(np.asarray(state)))]
    else:
        blocks = []
        for shard in shards:
            rows, cols = shard.index[0], shard.index[1]
            r0, r1, _ = rows.indices(h)
            c0, c1, _ = cols.indices(w)
            blocks.append(((r0, r1, c0, c1), np.asarray(shard.data)))
    local = np.uint64(0)
    for (r0, r1, c0, c1), block in blocks:
        rr = np.arange(r0, r1, dtype=np.uint64)[:, None]
        cc = np.arange(c0, c1, dtype=np.uint64)[None, :]
        mix = (rr + np.uint64(1)) * np.uint64(0x9E3779B97F4A7C15) \
            ^ (cc + np.uint64(1)) * np.uint64(0xC2B2AE3D27D4EB4F)
        with np.errstate(over="ignore"):
            local += (block.astype(np.uint64) * mix).sum(dtype=np.uint64)
    total = int(local)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        # Exchange as two 31-bit halves: jax may be running without x64, and
        # an allgather payload silently downcast to int32 would corrupt the
        # hash differently per process.
        halves = np.asarray([total & 0x7FFFFFFF, (total >> 31) & 0x7FFFFFFF],
                            np.int32)
        everyone = np.asarray(multihost_utils.process_allgather(halves),
                              np.int64).reshape(-1, 2)
        total = int((everyone[:, 0].sum() + (everyone[:, 1].sum() << 31))
                    & 0xFFFFFFFFFFFFFFFF)
    return f"{total:016x}" + (f":{tag}" if tag else "")


def _shard_checksums(state) -> dict[str, int]:
    """CRC32 per addressable shard, keyed by the block's index ranges in the
    stored array — geometry-keyed so restore can re-verify under ANY
    topology (regions are recomputed by slicing, not by shard identity)."""
    h, w = state.shape
    shards = getattr(state, "addressable_shards", None)
    if shards is None:  # plain ndarray
        block = np.ascontiguousarray(np.asarray(state))
        return {_block_key(0, h, 0, w): zlib.crc32(block.tobytes())}
    sums = {}
    for shard in shards:
        rows, cols = shard.index[0], shard.index[1]
        r0, r1, _ = rows.indices(h)
        c0, c1, _ = cols.indices(w)
        block = np.ascontiguousarray(np.asarray(shard.data))
        sums[_block_key(r0, r1, c0, c1)] = zlib.crc32(block.tobytes())
    return sums


def _allgather_checksums(sums: dict[str, int]) -> dict[str, int]:
    """Union of every process's shard checksums. The manifest is committed
    by the lead alone; without this merge it would record only the lead's
    addressable blocks and peer-owned shards would restore UNVERIFIED."""
    import jax

    if jax.process_count() == 1:
        return sums
    from jax.experimental import multihost_utils

    blob = np.frombuffer(
        json.dumps(sums, sort_keys=True).encode(), np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray(len(blob), np.int32))).ravel()
    padded = np.zeros((int(lens.max()),), np.uint8)
    padded[: len(blob)] = blob
    everyone = np.asarray(multihost_utils.process_allgather(padded))
    merged: dict[str, int] = {}
    for i, n in enumerate(lens):
        merged.update(json.loads(bytes(everyone[i, : int(n)]).decode()))
    return merged


def _verify_checksums(state, checksums: dict[str, int]) -> bool:
    """Re-verify every recorded block this process can address. Blocks owned
    entirely by peers are skipped (they verify their own); a block that
    straddles shards is re-sliced from the host copy on single-process runs.
    """
    import jax

    h, w = state.shape
    if jax.process_count() == 1:
        host = np.asarray(state)
        for key, want in checksums.items():
            r0, r1, c0, c1 = _parse_key(key)
            got = zlib.crc32(np.ascontiguousarray(host[r0:r1, c0:c1]).tobytes())
            if got != int(want):
                return False
        return True
    # Multihost: check keys contained in an addressable shard.
    for shard in state.addressable_shards:
        rows, cols = shard.index[0], shard.index[1]
        sr0, sr1, _ = rows.indices(h)
        sc0, sc1, _ = cols.indices(w)
        block = None
        for key, want in checksums.items():
            r0, r1, c0, c1 = _parse_key(key)
            if r0 >= sr0 and r1 <= sr1 and c0 >= sc0 and c1 <= sc1:
                if block is None:
                    block = np.asarray(shard.data)
                window = block[r0 - sr0 : r1 - sr0, c0 - sc0 : c1 - sc0]
                if zlib.crc32(np.ascontiguousarray(window).tobytes()) != int(want):
                    return False
    return True


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _commit_file(path: str, data: bytes) -> None:
    """Write ``data`` durably at ``path`` via tmp + fsync + atomic rename."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path) or ".")


def _rmtree_or_file(path: str) -> None:
    if os.path.isdir(path):
        import shutil

        shutil.rmtree(path, ignore_errors=True)
    elif os.path.exists(path):
        try:
            os.remove(path)
        except OSError:
            pass


class CheckpointManager:
    """Atomic checkpoints for one run's geometry in one directory.

    ``keep`` retains that many newest checkpoints (>=1): the window a slow
    shared filesystem gets to make a manifest readable on every host before
    the vote falls back to the previous one.
    """

    def __init__(
        self,
        directory: str,
        *,
        height: int,
        width: int,
        codec: PayloadCodec,
        keep: int = 2,
        retry: RetryPolicy = DEFAULT_IO_RETRY,
        run_fingerprint: str | None = None,
    ):
        if keep < 1:
            raise ValueError(f"checkpoint keep must be >= 1, got {keep}")
        self.directory = directory
        self.height = height
        self.width = width
        self.codec = codec
        self.keep = keep
        self.retry = retry
        self.run_fingerprint = run_fingerprint
        os.makedirs(directory, exist_ok=True)

    # -- naming --------------------------------------------------------------

    def _manifest_path(self, generation: int) -> str:
        return os.path.join(self.directory,
                            f"{_PREFIX}{generation:08d}{_MANIFEST_SUFFIX}")

    def _payload_name(self, generation: int) -> str:
        return f"{_PREFIX}{generation:08d}{self.codec.suffix}"

    def _list_generations(self) -> list[int]:
        """Generations with a committed manifest, newest first."""
        gens = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for name in names:
            if name.startswith(_PREFIX) and name.endswith(_MANIFEST_SUFFIX):
                digits = name[len(_PREFIX) : -len(_MANIFEST_SUFFIX)]
                if digits.isdigit():
                    gens.append(int(digits))
        return sorted(gens, reverse=True)

    # -- save ----------------------------------------------------------------

    def save(self, state, generation: int, counter: int) -> str:
        """Checkpoint ``state`` after ``generation`` completed generations.

        Returns the manifest path. Ordering is the crash-safety argument:
        payload first (fresh path), manifest committed atomically second, GC
        of older checkpoints last — a crash at ANY point leaves the previous
        checkpoint intact and discoverable.
        """
        faults.on_checkpoint_boundary(generation)
        import jax

        multihost = jax.process_count() > 1
        manifest_path = self._manifest_path(generation)
        already = (
            os.path.exists(manifest_path) and self._load(generation) is not None
        )
        if multihost:
            # The skip must be a COLLECTIVE decision: a lone process skipping
            # (or sweeping the shared manifest) while peers rewrite would
            # desynchronize the barrier sequence below and deadlock the
            # cluster. Unanimous yes -> all skip; otherwise all rewrite.
            from gol_tpu.parallel.collectives import host_all_agree

            already = host_all_agree(already)
        if already:
            # A resumed run re-reached a boundary it had already committed;
            # the engine is bit-exact, so the existing checkpoint IS this
            # state — rewriting it would put a valid manifest over a payload
            # mid-rewrite, the one window the ordering otherwise closes.
            return manifest_path
        payload_name = self._payload_name(generation)
        payload_path = os.path.join(self.directory, payload_name)
        if not multihost or jax.process_index() == 0:
            _rmtree_or_file(manifest_path)  # invalid leftover, if any
            _rmtree_or_file(payload_path)  # torn orphan from a crashed save
        if multihost:
            # The lead's sweep of shared-FS leftovers must finish before any
            # peer starts writing shards into the payload path.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.clean:{self.directory}:{generation}")
        if multihost or self.codec.self_retrying:
            # No outer retry. Multihost: the zarr codec's write contains
            # collective barriers, and ONE process re-entering them while
            # peers have moved on joins the wrong barrier. Self-retrying
            # codecs: stacking this policy on the codec's own would cube the
            # time-to-failure of a persistent outage.
            self.codec.write(payload_path, state)
        else:
            self.retry.call(lambda: self.codec.write(payload_path, state))
        faults.on_payload_write(payload_path)
        # Merged across processes AFTER the write (a fixed point in the
        # collective order): the lead-committed manifest must carry EVERY
        # process's block CRCs or peer shards would restore unverified.
        checksums = _allgather_checksums(_shard_checksums(state))
        manifest = {
            "format_version": FORMAT_VERSION,
            "generation": int(generation),
            "counter": int(counter),
            "height": int(self.height),
            "width": int(self.width),
            "state_shape": [int(d) for d in state.shape],
            "payload": payload_name,
            "payload_format": self.codec.format,
            "run_fingerprint": self.run_fingerprint,
            "checksums": checksums,
            "created_unix": time.time(),
        }
        data = json.dumps(manifest, indent=1).encode()
        if multihost:
            # Peers' payload shards must be durable before ANY process
            # commits a manifest claiming them; only the lead commits. The
            # barriers are never retried: a process unilaterally re-entering
            # a barrier its peers already passed can only join the WRONG
            # barrier — a transient collective failure is fatal by design.
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.commit:{self.directory}:{generation}")
            if jax.process_index() == 0:
                _commit_file(manifest_path, data)
            multihost_utils.sync_global_devices(
                f"gol_tpu.ckpt.committed:{self.directory}:{generation}")
        else:
            _commit_file(manifest_path, data)
        self._gc()
        return manifest_path

    def _manifest_is_foreign(self, generation: int) -> bool:
        """True when the manifest readably belongs to a DIFFERENT run (its
        fingerprint exists and mismatches ours): garbage to this run, and it
        must not shadow (or out-sort) this run's own checkpoints."""
        if self.run_fingerprint is None:
            return False
        try:
            with open(self._manifest_path(generation)) as f:
                manifest = json.load(f)
        except (OSError, ValueError):
            return False  # unreadable != foreign; restore() handles invalid
        return manifest.get("run_fingerprint") != self.run_fingerprint

    def _gc(self) -> None:
        """Drop all but the ``keep`` newest of THIS run's checkpoints,
        manifest first (so a crash mid-GC can only orphan a payload, never
        dangle a manifest); foreign-run leftovers in a reused directory are
        garbage outright. Then sweep tmp/staging files and manifest-less
        payloads older than the newest."""
        import jax

        if jax.process_count() > 1 and jax.process_index() != 0:
            return
        gens, doomed = [], []
        for gen in self._list_generations():
            (doomed if self._manifest_is_foreign(gen) else gens).append(gen)
        doomed.extend(gens[self.keep :])
        for gen in doomed:
            _rmtree_or_file(self._manifest_path(gen))
            _rmtree_or_file(os.path.join(self.directory, self._payload_name(gen)))
        newest = gens[0] if gens else None
        live = {self._payload_name(g) for g in gens[: self.keep]}
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.endswith(".tmp") or (
                name.startswith(_PREFIX)
                and name.endswith((STAGING_SUFFIX, REPLACED_SUFFIX))
            ):
                # .tmp: torn manifest commits; .inprogress/.replaced: staging
                # leftovers from a codec writer (packed_io/ts_store) crashed
                # mid-payload. Saves are serialized within a run and GC runs
                # after the commit barrier, so anything still staged is stale.
                _rmtree_or_file(path)
            elif (
                name.startswith(_PREFIX)
                and name.endswith(self.codec.suffix)
                and name not in live
            ):
                digits = name[len(_PREFIX) : -len(self.codec.suffix)]
                if digits.isdigit() and newest is not None and int(digits) <= newest:
                    _rmtree_or_file(path)

    # -- restore -------------------------------------------------------------

    def _load(self, generation: int):
        """(state, info) for one checkpoint, or None if anything about it —
        manifest JSON, geometry, payload read, checksums — fails to verify."""
        try:
            with open(self._manifest_path(generation)) as f:
                manifest = json.load(f)
            if manifest.get("format_version") != FORMAT_VERSION:
                raise ValueError(
                    f"unknown format_version {manifest.get('format_version')}")
            if (manifest["height"], manifest["width"]) != (self.height, self.width):
                raise ValueError(
                    f"geometry {manifest['height']}x{manifest['width']} != "
                    f"run geometry {self.height}x{self.width}")
            if manifest["payload_format"] != self.codec.format:
                raise ValueError(
                    f"payload format {manifest['payload_format']!r} != "
                    f"this lane's {self.codec.format!r}")
            if (
                self.run_fingerprint is not None
                and manifest.get("run_fingerprint") != self.run_fingerprint
            ):
                raise ValueError(
                    f"checkpoint belongs to a different run (fingerprint "
                    f"{manifest.get('run_fingerprint')!r} != this run's "
                    f"{self.run_fingerprint!r}) — stale checkpoint dir?")
            payload = os.path.join(self.directory, manifest["payload"])
            if self.codec.self_retrying:
                state = self.codec.read(payload)
            else:
                state = self.retry.call(lambda: self.codec.read(payload))
            if tuple(state.shape) != tuple(manifest["state_shape"]):
                raise ValueError(
                    f"payload shape {tuple(state.shape)} != manifest "
                    f"{tuple(manifest['state_shape'])}")
            if not _verify_checksums(state, manifest["checksums"]):
                raise ValueError("shard checksum mismatch")
            info = CheckpointInfo(
                generation=int(manifest["generation"]),
                counter=int(manifest["counter"]),
                path=self._manifest_path(generation),
            )
            return state, info
        except Exception as e:  # noqa: BLE001 - any defect means "not valid"
            logger.warning(
                "checkpoint %s/%s%08d invalid, trying older: %s: %s",
                self.directory, _PREFIX, generation, type(e).__name__, e)
            return None

    def _global_candidates(self) -> list[int]:
        """Union of every process's manifest generations, newest first: a
        manifest only one host can list must still get voted on (and down)."""
        import jax

        local = self._list_generations()
        if jax.process_count() == 1:
            return local
        from jax.experimental import multihost_utils

        # Fixed-size exchange: newest 2*keep generations, -1 padded.
        width = max(2 * self.keep, 4)
        mine = np.full((width,), -1, np.int64)
        mine[: min(len(local), width)] = local[:width]
        everyone = np.asarray(multihost_utils.process_allgather(mine))
        gens = {int(g) for g in everyone.ravel() if int(g) >= 0}
        return sorted(gens, reverse=True)

    def restore(self, max_generation: int | None = None):
        """Newest checkpoint every process can read, or None.

        Walks candidates newest-first; each process validates locally and the
        cluster votes (``host_all_agree``) — a manifest any process cannot
        read and verify is skipped by ALL of them, so no two processes ever
        resume from different generations. Returns ``(state, info)``.

        ``max_generation`` skips checkpoints past it (deterministically, so
        no vote is needed): a rerun with a REDUCED --gen-limit resumes from
        the newest checkpoint at or below the limit — any such checkpoint is
        an exact prefix of the shorter run — or starts fresh.
        """
        from gol_tpu.parallel.collectives import host_all_agree

        for gen in self._global_candidates():
            if max_generation is not None and gen > max_generation:
                continue
            loaded = self._load(gen)
            if host_all_agree(loaded is not None):
                state, info = loaded
                logger.info("auto-resume: restored checkpoint at generation "
                            "%d from %s", info.generation, info.path)
                return state, info
            if loaded is not None:
                logger.warning(
                    "checkpoint generation %d readable here but not on every "
                    "process; falling back to an older one", gen)
        return None
