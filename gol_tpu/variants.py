"""The six reference programs as policy bundles over the one engine.

The reference is six standalone mains differing only in I/O strategy, loop
accounting, and which lines they print (SURVEY.md §2 C1-C6). Here each is a
``Variant`` record; the engine, kernels, and mesh machinery are shared. Output
filenames match the reference byte-for-byte so existing comparison scripts
keep working.
"""

from __future__ import annotations

import dataclasses

from gol_tpu.config import Convention


@dataclasses.dataclass(frozen=True)
class Variant:
    """Per-program behavior switches (citations per field below)."""

    name: str
    output_file: str  # src/game.c:27 etc.
    convention: str = Convention.C
    io: str = "serial"  # serial | gathered | sharded | sharded_async
    distributed: bool = False  # runs over a device mesh
    force_square: bool = False  # `height = width`, src/game_mpi.c:504
    serial_header: bool = False  # the extra "Finished.\n\n", src/game.c:201
    io_timings: bool = False  # "Reading file"/"Writing file" lines
    final_finished: bool = True  # game_openmp.c:501 comments its one out


VARIANTS = {
    # C1 — serial ground truth (src/game.c). Single device, rectangles allowed.
    "game": Variant(
        name="game",
        output_file="game_output.out",
        serial_header=True,
    ),
    # C2 — master-scatter I/O (src/game_mpi.c): one host reads/writes, blocks
    # are scattered/gathered. The degenerate debug-mode I/O.
    "mpi": Variant(
        name="mpi",
        output_file="mpi_output.out",
        io="gathered",
        distributed=True,
        force_square=True,
        io_timings=True,
    ),
    # C3 — collective MPI-IO (src/game_mpi_collective.c): every shard reads
    # and writes its own file window.
    "collective": Variant(
        name="collective",
        output_file="collective_output.out",
        io="sharded",
        distributed=True,
        force_square=True,
        io_timings=True,
    ),
    # C4 — async MPI-IO (src/game_mpi_async.c): byte-identical to C3 except
    # iread/iwrite and the filename; here the per-shard windows genuinely
    # overlap via a thread pool (the reference waits immediately).
    "async": Variant(
        name="async",
        output_file="async_output.out",
        io="sharded_async",
        distributed=True,
        force_square=True,
        io_timings=True,
    ),
    # C5 — hybrid MPI+OpenMP (src/game_openmp.c): intra-rank threading is
    # inherent on TPU (the VPU vectorizes the whole shard), so this is C3
    # with the reference's quirks: openmp_output.out and no final "Finished"
    # (game_openmp.c:501 is commented out).
    "openmp": Variant(
        name="openmp",
        output_file="openmp_output.out",
        io="sharded",
        distributed=True,
        force_square=True,
        io_timings=True,
        final_finished=False,
    ),
    # C6 — CUDA single-accelerator (src/game_cuda.cu): single chip, numeric
    # cells, divergent loop accounting, no I/O timing lines.
    "cuda": Variant(
        name="cuda",
        output_file="cuda_output.out",
        convention=Convention.CUDA,
    ),
    # The TPU-native flagship: no legacy quirks — sharded I/O over the full
    # mesh, rectangles allowed, C accounting. Not in the reference; this is
    # what new users should run.
    "tpu": Variant(
        name="tpu",
        output_file="tpu_output.out",
        io="sharded",
        distributed=True,
        io_timings=True,
    ),
}


def get_variant(name: str) -> Variant:
    try:
        return VARIANTS[name]
    except KeyError:
        raise ValueError(
            f"unknown variant {name!r}; available: {', '.join(sorted(VARIANTS))}"
        ) from None
