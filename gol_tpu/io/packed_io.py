"""Sharded grid I/O straight to/from bitpacked device state.

The end-to-end fast lane: text file bytes -> uint32 cell words (native codec)
-> sharded device array, and back — the uint8 cell grid never materializes on
the host. Next to ``io/sharded.py`` (the byte-level MPI-IO counterpart,
src/game_mpi_collective.c:174-196,425-443) this cuts host memory and
host->device transfer 8x, which is what makes the 65536^2 configuration
(4 GB of text, 512 MB packed) practical.

Same file-layout contract: ``height x (width+1)`` bytes, '\\n' column owned
by east-edge shards on write.
"""

from __future__ import annotations

import collections
import concurrent.futures
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu import native
from gol_tpu.io.text_grid import create_sized, row_stride
from gol_tpu.ops.packed_math import BITS
from gol_tpu.parallel.mesh import COL_AXIS, ROW_AXIS
from gol_tpu.resilience import STAGING_SUFFIX


def words_sharding(mesh: Mesh) -> NamedSharding:
    """Block sharding of the (height, width/32) word array over the mesh."""
    return NamedSharding(mesh, P(ROW_AXIS, COL_AXIS))


# Host-side pack granularity (text bytes per codec call) and device->host
# transfer granularity (packed bytes per fetch). Module-level so tests can
# shrink them to exercise the chunked paths on small grids.
_READ_CHUNK_BYTES = 128 << 20
_WRITE_CHUNK_BYTES = 64 << 20
# In-flight device->host fetches per shard. Depth 1 is the strict
# fetch-ahead-one pipeline (transfers serial, the next one queued while the
# codec drains the current — the link barely idles); deeper keeps several
# transfers genuinely concurrent, which helps transports that aggregate
# multiple streams and hurts ones that serialize them. The attach tunnel is
# NON-STATIONARY on this axis: two d2h probe runs an hour apart measured
# depth 4 at 1.7x slower, then 2.3x faster, than depth 1 for the same
# 512MB (benchmarks/d2h_probe_r3.json holds the latest), and back-to-back
# config-5 writes flipped the same way. Default to 2 as the middle;
# GOL_D2H_DEPTH overrides for a known transport (a real local chip, where
# D2H is PCIe-fast, is insensitive to this knob). Malformed values fall to
# the default rather than poisoning every package import.
try:
    _D2H_PREFETCH_DEPTH = int(os.environ.get("GOL_D2H_DEPTH", "2"))
except ValueError:
    _D2H_PREFETCH_DEPTH = 2
# Test hook: engage the pipelined chunked upload on the CPU backend too
# (production gates it to accelerators, where there is a transfer to hide).
_FORCE_READ_PIPELINE = False


def _check_shape(width: int, mesh: Mesh | None) -> None:
    cols = 1 if mesh is None else mesh.shape[COL_AXIS]
    if width % (BITS * cols) != 0:
        raise ValueError(
            f"packed I/O needs width ({width}) divisible by 32 x mesh cols ({cols})"
        )


def read_packed(path: str, width: int, height: int, mesh: Mesh | None = None) -> jax.Array:
    """Text grid file -> bitpacked (height, width/32) device array."""
    _check_shape(width, mesh)
    size, expected = os.path.getsize(path), height * row_stride(width)
    if size != expected:
        raise ValueError(
            f"{path}: size {size} != {expected} for a {height}x{width} text grid"
        )
    mm = np.memmap(path, dtype=np.uint8, mode="r", shape=(height, row_stride(width)))
    nwords = width // BITS

    if mesh is None:
        chunk = max(1, _READ_CHUNK_BYTES // max(row_stride(width), 1))
        starts = list(range(0, height, chunk))
        total_bytes = height * nwords * 4
        # Pipelined upload: ship each block to the device as soon as it is
        # packed — device_put is async, so uploads overlap the remaining
        # packing instead of waiting for one whole-array transfer at the
        # end. The on-device concatenate costs one extra HBM pass and a 2x
        # transient (parts + result), so the pipeline engages only where it
        # hides a real transfer (an accelerator backend) and the transient
        # comfortably fits (<=2GB packed); otherwise pack into one host
        # buffer and transfer once.
        pipelined = (
            (jax.default_backend() != "cpu" or _FORCE_READ_PIPELINE)
            and len(starts) > 1
            and total_bytes <= (2 << 30)
        )
        if pipelined:

            def pack_rows_out(r0: int) -> np.ndarray:
                r1 = min(height, r0 + chunk)
                return native.pack_text(mm[r0:r1], width)

            with concurrent.futures.ThreadPoolExecutor() as pool:
                futures = [pool.submit(pack_rows_out, r0) for r0 in starts]
                parts = [jax.device_put(f.result()) for f in futures]
            return jax.numpy.concatenate(parts, axis=0)

        # Pack row blocks across a thread pool (the codec releases the GIL).
        out = np.empty((height, nwords), dtype=np.uint32)

        def pack_rows(r0: int) -> None:
            r1 = min(height, r0 + chunk)
            out[r0:r1] = native.pack_text(mm[r0:r1], width)

        with concurrent.futures.ThreadPoolExecutor() as pool:
            list(pool.map(pack_rows, starts))
        return jax.numpy.asarray(out)

    sharding = words_sharding(mesh)

    def load_window(index) -> np.ndarray:
        rows, wcols = index
        r0, r1, _ = rows.indices(height)
        w0, w1, _ = wcols.indices(nwords)
        window = mm[r0:r1, w0 * BITS : w1 * BITS]
        return native.pack_text(window, (w1 - w0) * BITS)

    with concurrent.futures.ThreadPoolExecutor() as pool:
        index_map = sharding.addressable_devices_indices_map((height, nwords))
        unique = {
            tuple((s.start, s.stop) for s in idx): idx for idx in index_map.values()
        }
        blocks = dict(zip(unique, pool.map(load_window, unique.values())))
    return jax.make_array_from_callback(
        (height, nwords),
        sharding,
        lambda idx: blocks[tuple((s.start, s.stop) for s in idx)],
    )


def write_packed(path: str, words: jax.Array, width: int) -> None:
    """Bitpacked device array -> text grid file (no gather, no cell bytes).

    Single-process writes are crash-consistent: the bytes land in a
    ``<path>.inprogress`` sibling that atomically replaces ``path`` only
    once complete, so overwriting a prior snapshot can never leave a torn
    file as the only copy. Multi-process runs keep the in-place shared-file
    write (every host owns disjoint windows of ONE file; a per-host rename
    would commit partial state) — their durability story is the manifested
    checkpoint lane (resilience/checkpoint.py), not this writer.
    """
    height, nwords = words.shape
    if nwords * BITS != width:
        raise ValueError(f"width {width} != {nwords} words x {BITS}")
    atomic = jax.process_count() == 1
    dest = path + STAGING_SUFFIX if atomic else path
    create_sized(dest, height * row_stride(width))
    mm = np.memmap(dest, dtype=np.uint8, mode="r+", shape=(height, row_stride(width)))

    # One unpack pool shared by every shard (bounded by core count): nesting
    # a fresh pool per shard would scale threads as shards x default_workers.
    unpack_pool = concurrent.futures.ThreadPoolExecutor(
        max_workers=os.cpu_count() or 4
    )

    def store_window(shard) -> None:
        rows, wcols = shard.index[0], shard.index[1]
        r0, r1, _ = rows.indices(height)
        w0, w1, _ = wcols.indices(nwords)
        east_edge = w1 == nwords
        window = mm[r0:r1, w0 * BITS : w1 * BITS + (1 if east_edge else 0)]
        data = shard.data
        # Device->host transfers stream chunk-by-chunk, the next piece
        # prefetched while the current one is handed to the codec; unpacking
        # itself fans out over the shared worker pool (the chunk windows are
        # disjoint and the codec releases the GIL).
        chunk_rows = max(1, _WRITE_CHUNK_BYTES // max(data.shape[1] * 4, 1))
        starts = list(range(0, r1 - r0, chunk_rows))
        if not starts:
            return

        def fetch(s):
            return np.ascontiguousarray(data[s : s + chunk_rows])

        def unpack(block, s):
            native.unpack_text(
                block, window[s : s + block.shape[0]], (w1 - w0) * BITS, east_edge
            )

        depth = max(1, _D2H_PREFETCH_DEPTH)
        with concurrent.futures.ThreadPoolExecutor(max_workers=depth) as prefetch:
            # Keep `depth` transfers in flight, and at most 2*depth fetched
            # blocks alive (in-flight + queued-for-unpack): before submitting
            # a new unpack job the oldest outstanding one is drained, so a
            # slow codec cannot let blocks pile up toward whole-shard size.
            inflight = [
                (s, prefetch.submit(fetch, s)) for s in starts[:depth]
            ]
            jobs = collections.deque()
            for i, s in enumerate(starts):
                nxt = i + depth
                if nxt < len(starts):
                    inflight.append(
                        (starts[nxt], prefetch.submit(fetch, starts[nxt]))
                    )
                s0, fut = inflight[i]
                assert s0 == s
                if len(jobs) >= depth:
                    jobs.popleft().result()
                jobs.append(unpack_pool.submit(unpack, fut.result(), s))
                inflight[i] = None  # let the fetched block die with its job
            for job in jobs:
                job.result()

    shards = list(words.addressable_shards)
    try:
        with concurrent.futures.ThreadPoolExecutor() as pool:
            list(pool.map(store_window, shards))
    finally:
        unpack_pool.shutdown()
    mm.flush()
    if atomic:
        os.replace(dest, path)
