"""Grid I/O: text format codec plus serial, gathered and sharded strategies."""

from gol_tpu.io.text_grid import (
    decode,
    encode,
    generate,
    read_grid,
    write_grid,
)

__all__ = ["decode", "encode", "generate", "read_grid", "write_grid"]
