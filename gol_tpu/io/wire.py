"""The packed binary wire format: boards on the wire at 1 bit per cell.

Every hop of the serving stack historically moved boards as '0'/'1' text
(~8.5 bytes per cell once JSON framing and the newline column are counted:
a 4096^2 board is ~17 MB of text for ~2 MB of information). This module
defines the ONE binary frame every hop speaks instead — client submit,
router forward, CAS payload, result response — built on the tree's single
bit-packing convention (``io/bitpack.py``: bit j of word w = column 32w+j,
the exact layout the packed device kernels compute on).

Frame layout (all integers little-endian)::

    offset  size  field
    0       4     magic  b"GOLP"
    4       2     version (=1; unknown versions are rejected as
                  UnsupportedWire so clients can degrade to text)
    6       2     flags (reserved, must be 0)
    8       4     width  (cells)
    12      4     height (cells)
    16      4     meta_len (bytes of UTF-8 JSON following the header)
    20      4     CRC32 of the words payload bytes
    24      ...   meta JSON object (meta_len bytes)
    ...     ...   payload: height rows x ceil(width/32) uint32 words

The payload is exactly the host-staging word array the engine's packed
kernels consume — a packed submit can be staged without re-packing, and a
packed result can be encoded without a text round trip. Widths that are
not a multiple of 32 pad the final word of each row with dead (zero) bits;
``decode`` crops them back off. The meta JSON carries whatever the hop
needs (submit fields minus ``cells``/``width``/``height``; result fields
minus ``grid``) — geometry always rides the header, authoritatively.

Truncated frames, trailing garbage, CRC mismatches, bad magic, and
non-object meta all raise ``WireError`` loudly: a frame either parses
whole or not at all. Numpy-only on purpose (no jax import): the fleet
router peeks frames for placement and must stay jax-free.

Content negotiation (``serve/server.py``, ``fleet/router.py``):
``POST /jobs`` with ``Content-Type: application/x-gol-packed`` submits a
frame; ``GET /result/<id>`` with that token in ``Accept`` answers one.
Text/JSON stays the compat default and is byte-identical to pre-wire
behavior when chosen (test-pinned).
"""

from __future__ import annotations

import dataclasses
import json
import struct
import sys
import zlib

import numpy as np

from gol_tpu.io import bitpack

CONTENT_TYPE = "application/x-gol-packed"
# Unknown members of the family (a future v2 content type, say) answer 415
# — the signal a packed client degrades to text on.
CONTENT_TYPE_FAMILY = "application/x-gol-"

MAGIC = b"GOLP"
VERSION = 1

# -- shard frame meta convention (gol_tpu/shard/halo.py) --------------------
#
# The sharded single-job engine's worker↔worker hops ride this exact frame
# format; the ``kind`` meta key names which shard payload the rows carry so
# a halo frame can never be mistaken for a board submit (a submit's meta
# never carries ``kind``). ``shard-halo`` stacks 4 ring rows (top, bottom,
# left-as-row, right-as-row) per boundary tile; ``shard-tiles`` stacks
# ``tile`` full rows per migrating tile (the elastic-rebalance transfer).
# Both list their tile coords under the ``tiles`` meta key, in row-major
# order matching the payload stacking.
META_KIND = "kind"
SHARD_HALO_KIND = "shard-halo"
SHARD_TILES_KIND = "shard-tiles"

_HEADER = struct.Struct("<4sHHIIII")
HEADER_SIZE = _HEADER.size  # 24 bytes

# -- body caps (shared by worker and router so the two tiers agree) ---------
#
# The 64 MiB text/JSON cap predates this module (PR 2) and is sized for
# text's ~8.5x inflation; it stays byte-identical for text bodies
# (test-pinned). The packed cap bounds the SAME universe of board areas,
# not the same byte count: a board that fits the text cap packs to ~1/8 of
# its text bytes, so capping packed bodies at the text byte count would
# accept boards 8x the area text can carry — an asymmetric DoS surface and
# an accidental format-dependent feature. Exactly TEXT/8 — header + meta
# count against the same budget text's newline column and JSON framing
# consume, which makes both caps flip at the same square-board side
# (8192^2, boundary-pinned by tests); degenerate aspect ratios can only
# diverge in the conservative direction (row-padding makes packed
# stricter, never looser).
MAX_BODY_TEXT = 64 << 20
MAX_BODY_PACKED = MAX_BODY_TEXT // 8


class WireError(ValueError):
    """A frame that does not parse whole: truncated, torn, CRC-poisoned,
    wrong magic, malformed meta. Maps to HTTP 400."""


class UnsupportedWire(WireError):
    """A frame (or content type) from a NEWER wire revision than this
    process speaks. Maps to HTTP 415 — the retry-as-text signal."""


def content_type_of(header_value: str | None) -> str:
    """Normalize a Content-Type header value to its media type (parameters
    such as ``; charset=`` stripped, lowercased); '' when absent."""
    if not header_value:
        return ""
    return header_value.split(";", 1)[0].strip().lower()


def is_packed(header_value: str | None) -> bool:
    return content_type_of(header_value) == CONTENT_TYPE


def accepts_packed(accept_header: str | None) -> bool:
    """Whether an ``Accept`` header asks for the packed format. Plain
    substring membership on the media-type token: clients send either our
    exact type or generic ``application/json``/``*/*`` forms."""
    return bool(accept_header) and CONTENT_TYPE in accept_header


def is_crc_error(payload) -> bool:
    """Whether a 400 error payload reports a frame CRC mismatch — i.e.
    the frame was corrupted on THAT hop and a resend of the same bytes is
    both safe (a 400 created no job) and likely to heal it. The ONE
    definition both transparent-recovery lanes (the router's forward
    retry and the client's packed resend) key off, so neither can drift
    from the error text this module raises."""
    return (isinstance(payload, dict)
            and "crc" in str(payload.get("error", "")).lower())


def max_body_bytes(content_type: str | None) -> int:
    """The request-body byte cap for a Content-Type header value: both
    formats accept the same universe of board AREAS (boundary-pinned by
    tests), so the cap is format-aware rather than one byte count."""
    return MAX_BODY_PACKED if is_packed(content_type) else MAX_BODY_TEXT


def words_per_row(width: int) -> int:
    """uint32 words per payload row (final word zero-padded)."""
    return (width + 31) // 32


def _require_little_endian() -> None:
    # Same gate as engine.resolve_batch_mode: the word payload is defined
    # as little-endian uint32 and the numpy fast paths view native memory.
    if sys.byteorder != "little":
        raise WireError(
            "the packed wire format requires a little-endian host; "
            "use the text format on this machine"
        )


def pack_grid(grid: np.ndarray) -> np.ndarray:
    """(H, W) uint8 {0,1} cells -> (H, words_per_row) uint32 payload words.

    Pads the width up to the next multiple of 32 with dead cells, then
    defers to the one bit-order rule in ``io/bitpack.py``."""
    _require_little_endian()
    grid = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
    if grid.ndim != 2:
        raise WireError(f"grid must be 2-D, got shape {grid.shape}")
    height, width = grid.shape
    wpr = words_per_row(width)
    if height == 0 or width == 0:
        return np.zeros((height, wpr), np.uint32)
    if width % 32:
        padded = np.zeros((height, wpr * 32), np.uint8)
        padded[:, :width] = grid
        grid = padded
    return bitpack.pack_words(grid)


def unpack_grid(words: np.ndarray, width: int) -> np.ndarray:
    """Inverse of ``pack_grid``: payload words -> (H, width) uint8 cells."""
    _require_little_endian()
    words = np.ascontiguousarray(np.asarray(words, dtype=np.uint32))
    height = words.shape[0]
    if height == 0 or width == 0:
        return np.zeros((height, width), np.uint8)
    return np.ascontiguousarray(bitpack.unpack_words(words, width))


@dataclasses.dataclass
class Frame:
    """One decoded wire frame: geometry + meta + the payload words."""

    width: int
    height: int
    meta: dict
    words: np.ndarray  # (height, words_per_row) uint32

    def grid(self) -> np.ndarray:
        """The decoded (height, width) uint8 board."""
        return unpack_grid(self.words, self.width)


def encode_frame(
    meta: dict,
    *,
    grid: np.ndarray | None = None,
    words: np.ndarray | None = None,
    width: int | None = None,
    height: int | None = None,
) -> bytes:
    """Serialize one frame from cells OR pre-packed words.

    ``words`` (with explicit ``width``/``height``) is the zero-re-encode
    lane: a result whose packed words are already in hand — engine output,
    CAS payload — goes to the wire without ever materializing cells. The
    two lanes are byte-identical for the same board (test-pinned)."""
    _require_little_endian()
    if (grid is None) == (words is None):
        raise WireError("pass exactly one of grid/words")
    if not isinstance(meta, dict):
        raise WireError(f"meta must be a dict, got {type(meta).__name__}")
    if grid is not None:
        grid = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
        if grid.ndim != 2:
            raise WireError(f"grid must be 2-D, got shape {grid.shape}")
        height, width = (int(x) for x in grid.shape)
        words = pack_grid(grid)
    else:
        if width is None or height is None:
            raise WireError("words needs explicit width/height")
        width, height = int(width), int(height)
        words = np.ascontiguousarray(np.asarray(words, dtype=np.uint32))
        if words.shape != (height, words_per_row(width)):
            raise WireError(
                f"words shape {words.shape} does not match "
                f"{height}x{width} (need (H, ceil(W/32)))"
            )
    payload = words.tobytes()
    meta_blob = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    header = _HEADER.pack(
        MAGIC, VERSION, 0, width, height, len(meta_blob),
        zlib.crc32(payload),
    )
    return header + meta_blob + payload


def peek(data: bytes) -> tuple[int, int, dict]:
    """(width, height, meta) from the header + meta section ONLY.

    The router's placement parse: no payload read, no CRC pass, no unpack —
    a packed submit is placed from ~24 bytes + the meta JSON and forwarded
    as the same raw buffer. The worker's full ``decode_frame`` stays the
    authoritative validator."""
    if len(data) < HEADER_SIZE:
        raise WireError(
            f"truncated frame: {len(data)} bytes < {HEADER_SIZE}-byte header"
        )
    magic, version, flags, width, height, meta_len, _crc = _HEADER.unpack(
        data[:HEADER_SIZE]
    )
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r} (expected {MAGIC!r})")
    if version != VERSION:
        raise UnsupportedWire(
            f"wire version {version} is newer than this process "
            f"(speaks {VERSION}); resend as text"
        )
    if flags != 0:
        raise UnsupportedWire(f"unknown wire flags {flags:#06x}")
    if len(data) < HEADER_SIZE + meta_len:
        raise WireError(
            f"truncated frame: meta section needs {meta_len} bytes, "
            f"{len(data) - HEADER_SIZE} present"
        )
    try:
        meta = json.loads(data[HEADER_SIZE:HEADER_SIZE + meta_len])
    except (ValueError, UnicodeDecodeError) as err:
        raise WireError(f"malformed meta JSON: {err}") from None
    if not isinstance(meta, dict):
        raise WireError(
            f"meta must be a JSON object, got {type(meta).__name__}"
        )
    return int(width), int(height), meta


def payload_crc(data: bytes) -> int:
    """The header's declared payload CRC32 — read, not recomputed (the
    router's no-unpack routing key; the worker's full decode verifies)."""
    if len(data) < HEADER_SIZE:
        raise WireError(
            f"truncated frame: {len(data)} bytes < {HEADER_SIZE}-byte header"
        )
    return _HEADER.unpack(data[:HEADER_SIZE])[6]


def decode_frame(data: bytes) -> Frame:
    """Parse + verify one frame whole; any defect raises ``WireError``."""
    _require_little_endian()
    width, height, meta = peek(data)
    _magic, _v, _f, _w, _h, meta_len, crc = _HEADER.unpack(data[:HEADER_SIZE])
    payload = data[HEADER_SIZE + meta_len:]
    expected = height * words_per_row(width) * 4
    if len(payload) != expected:
        raise WireError(
            f"payload of {len(payload)} bytes does not match the declared "
            f"{height}x{width} board ({expected} bytes); frame is "
            "truncated or carries trailing garbage"
        )
    if zlib.crc32(payload) != crc:
        raise WireError("payload CRC mismatch: frame corrupted in transit")
    words = np.frombuffer(payload, dtype="<u4").astype(np.uint32)
    words = words.reshape(height, words_per_row(width))
    return Frame(width=width, height=height, meta=meta, words=words)
