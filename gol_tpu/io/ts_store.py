"""TensorStore-backed sharded state storage (BASELINE.md config 5's
"sharded TensorStore I/O").

The text-grid files keep the reference's byte contract on POSIX filesystems
(io/sharded.py, io/packed_io.py — the MPI-IO analog,
src/game_mpi_collective.c:174-196,425-443). This module is the lane those
memmap windows cannot serve: pod object-store filesystems with no shared
POSIX mmap. The bitpacked word state is stored as a zarr array whose chunk
grid aligns with the mesh's shard blocks, so

- every host writes ONLY its addressable shards (no gather, no cross-host
  traffic — the collective-write discipline of MPI_File_write_all),
- reads reassemble a sharded `jax.Array` via per-shard chunk reads,
- the store works over any TensorStore kvstore (file://, gs://, s3://).

Snapshots stored this way carry the same no-sidecar resume property as text
snapshots: the array plus its generation count (in the store path, like
gen_NNNNNN) is a complete checkpoint (engine.resume_scalars).
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.ops.packed_math import BITS
from gol_tpu.parallel.mesh import COL_AXIS, ROW_AXIS

try:  # tensorstore ships with orbax; gate so the POSIX lanes never need it
    import tensorstore as ts

    HAVE_TENSORSTORE = True
except ImportError:  # pragma: no cover - present in this image
    ts = None
    HAVE_TENSORSTORE = False


def _require():
    if not HAVE_TENSORSTORE:
        raise RuntimeError(
            "tensorstore is not installed; the POSIX text/packed lanes "
            "(io/sharded.py, io/packed_io.py) cover shared filesystems"
        )


def _spec(path: str, shape=None, chunks=None):
    spec = {
        "driver": "zarr",
        "kvstore": path if "://" in path else {"driver": "file", "path": path},
    }
    if shape is not None:
        spec["metadata"] = {
            "shape": list(shape),
            "chunks": list(chunks),
            "dtype": "<u4",
        }
    return spec


def _shard_chunks(shape, mesh: Mesh | None):
    """Chunk grid aligned to the mesh decomposition: one chunk per shard
    block (or row-block chunks on a single device so writes parallelize)."""
    h, w = shape
    if mesh is None:
        rows = max(1, min(h, 4096))
        return (rows, w)
    mr = mesh.shape[ROW_AXIS]
    mc = mesh.shape[COL_AXIS]
    return (math.ceil(h / mr), math.ceil(w / mc))


def write_words(path: str, words: jax.Array, width: int) -> None:
    """Bitpacked device state -> sharded zarr store.

    Each process writes only its addressable shards; chunk boundaries equal
    shard boundaries, so no write crosses a chunk another host owns (the
    multi-writer-safety MPI_File_write_all gets from its subarray views).
    """
    _require()
    height, nwords = words.shape
    if nwords * BITS != width:
        raise ValueError(f"width {width} != {nwords} words x {BITS}")
    mesh = getattr(words.sharding, "mesh", None)
    chunks = _shard_chunks((height, nwords), mesh)
    if jax.process_count() > 1:
        # Multi-host: only the lead process creates (a concurrent
        # delete_existing on every host would clobber peers' shards); a
        # device barrier orders create before any peer's write.
        from jax.experimental import multihost_utils

        if jax.process_index() == 0:
            ts.open(
                _spec(path, (height, nwords), chunks),
                create=True,
                delete_existing=True,
            ).result()
        multihost_utils.sync_global_devices(f"gol_tpu.ts_store.create:{path}")
        store = ts.open(_spec(path)).result()
    else:
        store = ts.open(
            _spec(path, (height, nwords), chunks),
            create=True,
            delete_existing=True,
        ).result()
    futures = []
    for shard in words.addressable_shards:
        rows, wcols = shard.index[0], shard.index[1]
        block = np.asarray(shard.data)
        futures.append(store[rows, wcols].write(block))
    for f in futures:
        f.result()


def read_words(
    path: str, width: int, height: int, mesh: Mesh | None = None
) -> jax.Array:
    """Sharded zarr store -> bitpacked (height, width/32) device array."""
    _require()
    from gol_tpu.io.packed_io import words_sharding

    nwords = width // BITS
    if nwords * BITS != width:
        raise ValueError(f"width {width} must be a multiple of {BITS}")
    store = ts.open(_spec(path)).result()
    if tuple(store.shape) != (height, nwords):
        raise ValueError(
            f"{path}: stored shape {tuple(store.shape)} != ({height}, {nwords})"
        )
    if mesh is None:
        return jax.numpy.asarray(store.read().result())
    sharding = words_sharding(mesh)
    index_map = sharding.addressable_devices_indices_map((height, nwords))
    unique = {
        tuple((s.start, s.stop) for s in idx): idx for idx in index_map.values()
    }
    blocks = {
        key: store[idx[0], idx[1]].read().result() for key, idx in unique.items()
    }
    return jax.make_array_from_callback(
        (height, nwords),
        sharding,
        lambda idx: blocks[tuple((s.start, s.stop) for s in idx)],
    )
