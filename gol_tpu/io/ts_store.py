"""TensorStore-backed sharded state storage (BASELINE.md config 5's
"sharded TensorStore I/O").

The text-grid files keep the reference's byte contract on POSIX filesystems
(io/sharded.py, io/packed_io.py — the MPI-IO analog,
src/game_mpi_collective.c:174-196,425-443). This module is the lane those
memmap windows cannot serve: pod object-store filesystems with no shared
POSIX mmap. The bitpacked word state is stored as a zarr array whose chunk
grid aligns with the mesh's shard blocks, so

- every host writes ONLY its addressable shards (no gather, no cross-host
  traffic — the collective-write discipline of MPI_File_write_all),
- reads reassemble a sharded `jax.Array` via per-shard chunk reads,
- the store works over any TensorStore kvstore (file://, gs://, s3://).

Failure semantics (resilience pass): ``write_words`` NEVER deletes the only
durable copy of prior state — overwriting an existing file-backed store
writes to a fresh ``<path>.inprogress`` sibling and swaps it in only after
every shard is durable, so a crash mid-write leaves the previous store
readable (as ``path`` or, in the two-rename commit window, ``path.replaced``
— ``read_words`` checks both). Shard-write failures are awaited to
completion, aggregated, and reported with the failing shard indices; opens,
transient shard writes, and the multihost create barrier retry under the
unified ``resilience.retry`` policy.

Snapshots stored this way carry the same no-sidecar resume property as text
snapshots: the array plus its generation count (in the store path, like
gen_NNNNNN) is a complete checkpoint (engine.resume_scalars).
"""

from __future__ import annotations

import logging
import math
import os
import shutil

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.ops.packed_math import BITS
from gol_tpu.parallel.mesh import COL_AXIS, ROW_AXIS
from gol_tpu.resilience import REPLACED_SUFFIX, STAGING_SUFFIX, faults
from gol_tpu.resilience.retry import (
    DEFAULT_IO_RETRY,
    RetryPolicy,
    is_transient_io,
)

logger = logging.getLogger(__name__)

try:  # tensorstore ships with orbax; gate so the POSIX lanes never need it
    import tensorstore as ts

    HAVE_TENSORSTORE = True
except ImportError:  # pragma: no cover - present in this image
    ts = None
    HAVE_TENSORSTORE = False

# Suffixes of the two-phase overwrite commit (shared package-wide so the
# checkpoint GC sweeps the same names the writers stage). ``.inprogress``
# holds the new store until every shard is durable; ``.replaced`` holds the
# old store for the instant between the two renames of the swap.
_INPROGRESS = STAGING_SUFFIX
_REPLACED = REPLACED_SUFFIX


def _require():
    if not HAVE_TENSORSTORE:
        raise RuntimeError(
            "tensorstore is not installed; the POSIX text/packed lanes "
            "(io/sharded.py, io/packed_io.py) cover shared filesystems"
        )


def _spec(path: str, shape=None, chunks=None):
    spec = {
        "driver": "zarr",
        "kvstore": path if "://" in path else {"driver": "file", "path": path},
    }
    if shape is not None:
        spec["metadata"] = {
            "shape": list(shape),
            "chunks": list(chunks),
            "dtype": "<u4",
        }
    return spec


def _open(path: str, retry: RetryPolicy, shape=None, chunks=None, **kw):
    """ts.open with the fault hook and transient-outage retry applied."""

    def attempt():
        faults.on_ts_open()
        return ts.open(_spec(path, shape, chunks), **kw).result()

    return retry.call(
        attempt,
        retryable=is_transient_io,
        on_retry=lambda n, err, delay: logger.warning(
            "tensorstore open of %s failed (attempt %d, retrying in %.2fs): "
            "%s: %s", path, n, delay, type(err).__name__, err),
    )


def _shard_chunks(shape, mesh: Mesh | None):
    """Chunk grid aligned to the mesh decomposition: one chunk per shard
    block (or row-block chunks on a single device so writes parallelize)."""
    h, w = shape
    if mesh is None:
        rows = max(1, min(h, 4096))
        return (rows, w)
    mr = mesh.shape[ROW_AXIS]
    mc = mesh.shape[COL_AXIS]
    return (math.ceil(h / mr), math.ceil(w / mc))


def _write_shards(store, shards, retry: RetryPolicy) -> None:
    """Submit every shard write, await ALL of them, aggregate failures.

    The old form raised on the first ``f.result()``, leaving later futures
    unawaited and the store silently partial with no record of which shards
    made it. Here every future is drained each round; transient failures are
    re-submitted under the retry policy, and whatever remains raises ONE
    error naming the failed shard indices.
    """
    pending = list(enumerate(shards))
    delay = retry.base_delay
    for attempt in range(1, retry.attempts + 1):
        outcomes = []  # (index, shard, error-or-None)
        futures = []
        for i, shard in pending:
            try:
                faults.on_ts_shard_write(i)
                rows, wcols = shard.index[0], shard.index[1]
                block = np.asarray(shard.data)
                futures.append((i, shard, store[rows, wcols].write(block)))
            except Exception as e:  # submit-time failure still gets awaited peers
                outcomes.append((i, shard, e))
        for i, shard, fut in futures:
            try:
                fut.result()
                outcomes.append((i, shard, None))
            except Exception as e:
                outcomes.append((i, shard, e))
        failures = [(i, shard, e) for i, shard, e in outcomes if e is not None]
        if not failures:
            return
        hard = [(i, e) for i, _, e in failures if not is_transient_io(e)]
        if hard or attempt >= retry.attempts:
            indices = sorted(i for i, _, _ in failures)
            detail = "; ".join(
                f"shard {i}: {type(e).__name__}: {e}" for i, _, e in failures
            )
            raise OSError(
                f"write_words: {len(failures)}/{len(shards)} shard writes "
                f"failed (shard indices {indices}): {detail}"
            )
        logger.warning(
            "write_words: %d transient shard-write failure(s) (indices %s), "
            "retrying in %.2fs", len(failures),
            sorted(i for i, _, _ in failures), delay)
        pending = [(i, shard) for i, shard, _ in failures]
        if delay > 0:
            import time

            time.sleep(delay)
        delay = retry.next_delay(delay)


def _swap_in(path: str, staged: str) -> None:
    """Commit ``staged`` over ``path``: old aside, new in, old gone. Between
    the renames the prior state survives as ``path.replaced`` — at no point
    do zero durable copies exist."""
    old = path + _REPLACED
    if os.path.exists(old):
        shutil.rmtree(old, ignore_errors=True)
    os.rename(path, old)
    os.rename(staged, path)
    shutil.rmtree(old, ignore_errors=True)


def write_words(
    path: str,
    words: jax.Array,
    width: int,
    *,
    retry: RetryPolicy = DEFAULT_IO_RETRY,
) -> None:
    """Bitpacked device state -> sharded zarr store, crash-consistently.

    Each process writes only its addressable shards; chunk boundaries equal
    shard boundaries, so no write crosses a chunk another host owns (the
    multi-writer-safety MPI_File_write_all gets from its subarray views).
    Overwriting an existing file-backed store stages into ``.inprogress``
    and swaps after all shards land (see module docstring); remote kvstores
    (``://`` paths) cannot rename and keep the direct-write behavior.
    """
    _require()
    height, nwords = words.shape
    if nwords * BITS != width:
        raise ValueError(f"width {width} != {nwords} words x {BITS}")
    mesh = getattr(words.sharding, "mesh", None)
    chunks = _shard_chunks((height, nwords), mesh)

    multihost = jax.process_count() > 1
    file_backed = "://" not in path
    stage = file_backed and os.path.exists(path)
    if multihost and file_backed:
        # The staging decision feeds barrier NAMES and the target path, so
        # every process must make the same call: the lead's view of the
        # shared FS wins (a peer with a stale attribute cache disagreeing
        # would otherwise join differently-named barriers, or write its
        # shards into the live store while the lead stages).
        from jax.experimental import multihost_utils

        stage = bool(np.asarray(multihost_utils.process_allgather(
            np.asarray(stage, np.int32))).ravel()[0])
    staged = None
    target = path
    if stage:
        # Never destroy the only durable copy: build the new store beside it.
        staged = path + _INPROGRESS
        target = staged
    if multihost:
        # Multi-host: only the lead process creates (a concurrent
        # delete_existing on every host would clobber peers' shards); a
        # device barrier orders create before any peer's write. Barriers are
        # never retried — a process unilaterally re-entering a barrier its
        # peers already passed can only join the WRONG barrier, so a
        # transient collective failure is fatal by design (the per-process
        # retries cover the tensorstore open/write calls around it).
        from jax.experimental import multihost_utils

        create_err: Exception | None = None
        if jax.process_index() == 0:
            try:
                if staged is not None:
                    shutil.rmtree(staged, ignore_errors=True)
                _open(target, retry, (height, nwords), chunks,
                      create=True, delete_existing=True)
            except Exception as e:
                create_err = e
        # The lead's create failure must reach every process BEFORE peers
        # park at the create barrier (they would wait there until the
        # distributed-runtime timeout while the lead raises alone).
        from gol_tpu.parallel.collectives import host_all_agree

        if not host_all_agree(create_err is None):
            if create_err is not None:
                raise create_err
            raise OSError(
                f"write_words: lead process failed to create {target}")
        multihost_utils.sync_global_devices(
            f"gol_tpu.ts_store.create:{target}")
        store = None  # opened inside the guarded region below
    else:
        if staged is not None:
            shutil.rmtree(staged, ignore_errors=True)
        store = _open(target, retry, (height, nwords), chunks,
                      create=True, delete_existing=True)
    write_err: Exception | None = None
    try:
        if store is None:
            # The post-barrier open is guarded too: an open failure on one
            # process must reach the vote below, not bypass it and leave
            # peers waiting there.
            store = _open(target, retry)
        _write_shards(store, list(words.addressable_shards), retry)
    except Exception as e:
        if not (multihost and staged is not None):
            raise
        write_err = e
    if staged is not None:
        if multihost:
            from jax.experimental import multihost_utils

            from gol_tpu.parallel.collectives import host_all_agree

            # A process whose shard writes failed must not exit while its
            # peers park at the commit barrier below until the
            # distributed-runtime timeout: vote on success first, the
            # failing process voting False before re-raising, so everyone
            # abandons the staged store together (the live store at ``path``
            # stays untouched).
            if not host_all_agree(write_err is None):
                if write_err is not None:
                    raise write_err
                raise OSError(
                    f"write_words: a peer process failed its shard writes; "
                    f"abandoning staged store {staged}")
            # Every shard everywhere is durable before anyone swaps; only
            # the lead renames, and peers wait for the commit.
            multihost_utils.sync_global_devices(
                f"gol_tpu.ts_store.commit:{path}")
            if jax.process_index() == 0:
                _swap_in(path, staged)
            multihost_utils.sync_global_devices(
                f"gol_tpu.ts_store.committed:{path}")
        else:
            _swap_in(path, staged)


def read_words(
    path: str,
    width: int,
    height: int,
    mesh: Mesh | None = None,
    *,
    retry: RetryPolicy = DEFAULT_IO_RETRY,
) -> jax.Array:
    """Sharded zarr store -> bitpacked (height, width/32) device array."""
    _require()
    from gol_tpu.io.packed_io import words_sharding

    nwords = width // BITS
    if nwords * BITS != width:
        raise ValueError(f"width {width} must be a multiple of {BITS}")
    if "://" not in path and not os.path.exists(path):
        # A crash inside _swap_in's two-rename window leaves the prior state
        # as path.replaced: recover it rather than failing the resume.
        displaced = path + _REPLACED
        if os.path.exists(displaced):
            logger.warning(
                "%s missing but %s exists (crash mid-overwrite); recovering "
                "the displaced prior state", path, displaced)
            try:
                os.rename(displaced, path)
            except OSError:
                # A peer process recovering the same shared-FS store won the
                # rename; losing the race is fine as long as someone did.
                if not os.path.exists(path):
                    raise
    store = _open(path, retry)
    if tuple(store.shape) != (height, nwords):
        raise ValueError(
            f"{path}: stored shape {tuple(store.shape)} != ({height}, {nwords})"
        )
    if mesh is None:
        return jax.numpy.asarray(retry.call(lambda: store.read().result()))
    sharding = words_sharding(mesh)
    index_map = sharding.addressable_devices_indices_map((height, nwords))
    unique = {
        tuple((s.start, s.stop) for s in idx): idx for idx in index_map.values()
    }
    blocks = {
        key: retry.call(lambda idx=idx: store[idx[0], idx[1]].read().result())
        for key, idx in unique.items()
    }
    return jax.make_array_from_callback(
        (height, nwords),
        sharding,
        lambda idx: blocks[tuple((s.start, s.stop) for s in idx)],
    )
