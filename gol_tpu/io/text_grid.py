"""Text-grid format codec: '0'/'1' cells, newline-terminated rows.

Format contract (README.md:61-63): ``height`` rows of ``width`` ASCII digits,
each row followed by ``'\\n'`` — i.e. the file is a ``height x (width+1)`` byte
matrix whose last column is newlines (exactly how the reference's collective
MPI-IO models it, src/game_mpi_collective.c:180-186). A written output file is
a valid input file (src/game.c:25-40 emits what src/game.c:154-165 parses), a
property the resume path relies on.

The reference's parser consumes any non-'\\n' byte as a cell and only treats
'1' as alive (src/game.c:158-164, src/game.c:83); this codec does the same but
normalizes storage to numeric {0,1} uint8 on the way in (the CUDA variant's
choice, src/game_cuda.cu:176) and back to ASCII on the way out.
"""

from __future__ import annotations

import numpy as np

NEWLINE = 0x0A  # '\n'
ZERO = 0x30  # '0'
ONE = 0x31  # '1'


def create_sized(path: str, size: int) -> None:
    """Create/size a file without zeroing existing contents.

    ``open(path, 'wb')`` truncates to zero, which on a shared filesystem
    races away bytes other hosts already wrote; ``ftruncate`` to the final
    size is idempotent across processes (the reference's MODE_EXCL
    delete-and-retry dance, src/game_mpi_collective.c:429-436, solved the
    same multi-writer problem)."""
    import os

    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    try:
        os.ftruncate(fd, size)
    finally:
        os.close(fd)


def row_stride(width: int) -> int:
    """Bytes per row on disk: width cells + the newline column."""
    return width + 1


def decode(
    data: bytes | np.ndarray, width: int, height: int, exact: bool = False
) -> np.ndarray:
    """Parse text-grid bytes into a uint8 {0,1} array of shape (height, width).

    Fast path: the file is exactly the height x (width+1) matrix the format
    contract promises — one reshape, no scan. Fallback: the reference's
    skip-newlines scan (src/game.c:154-165) for files with stray newlines or
    trailing bytes.

    ``exact`` rejects any cell-count mismatch instead of truncating extra
    cells the way the reference's parser does — the serving API's contract
    (a submit body whose ``cells`` disagrees with its declared geometry is
    a client error, never a silently-cropped board), while file readers
    keep the reference's lenient scan.
    """
    raw = np.frombuffer(data, dtype=np.uint8) if isinstance(data, bytes) else data
    stride = row_stride(width)
    expected = height * stride
    if raw.size == expected:
        mat = raw.reshape(height, stride)
        if bool((mat[:, width] == NEWLINE).all()) and not bool(
            (mat[:, :width] == NEWLINE).any()
        ):
            return (mat[:, :width] == ONE).astype(np.uint8)
    cells = raw[raw != NEWLINE]
    if cells.size < height * width or (
        exact and cells.size != height * width
    ):
        raise ValueError(
            f"input holds {cells.size} cells; need "
            f"{'exactly ' if exact else ''}{height}x{width}="
            f"{height * width}"
        )
    return (cells[: height * width] == ONE).astype(np.uint8).reshape(height, width)


def _encode_matrix(grid: np.ndarray) -> np.ndarray:
    """The on-disk ``height x (width+1)`` byte matrix of a grid — the ONE
    place the row layout (digits + newline column) is built."""
    grid = np.asarray(grid, dtype=np.uint8)
    height, width = grid.shape
    out = np.empty((height, row_stride(width)), dtype=np.uint8)
    out[:, :width] = grid + ZERO
    out[:, width] = NEWLINE
    return out


def encode(grid: np.ndarray) -> bytes:
    """Serialize a uint8 {0,1} grid to text-grid bytes (src/game.c:25-40)."""
    return _encode_matrix(grid).tobytes()


def read_grid(path: str, width: int, height: int) -> np.ndarray:
    """Read a whole grid file serially (the src/game.c:149-166 path)."""
    with open(path, "rb") as f:
        data = f.read()
    return decode(data, width, height)


def write_grid(path: str, grid: np.ndarray) -> None:
    """Write a whole grid file serially (the src/game.c:25-40 path).

    Same bytes as ``f.write(encode(grid))`` but without materializing the
    intermediate ``bytes`` copy — ``write`` accepts the encoded matrix's
    buffer directly. At checkpoint scale (a 4096^2 payload is 16 MB) that
    copy was a measurable slice of the async checkpoint writer's
    background-thread work (gol_tpu/pipeline/writer.py).
    """
    with open(path, "wb") as f:
        f.write(memoryview(_encode_matrix(grid)).cast("B"))


def generate(
    width: int, height: int, density: float = 0.5, seed: int | None = None
) -> np.ndarray:
    """Random initial grid — generate.sh's $RANDOM%2 per cell (generate.sh:6-13).

    The reference script transposes rows/columns (its loops emit ``width`` rows
    of ``height`` chars; both loops even reuse variable ``i``) and is only
    correct for square grids; this emits the contractual height rows x width
    cols.
    """
    rng = np.random.default_rng(seed)
    return (rng.random((height, width)) < density).astype(np.uint8)


def generate_to_file(
    path: str,
    width: int,
    height: int,
    density: float = 0.5,
    seed: int | None = None,
    chunk_rows: int | None = None,
) -> None:
    """Stream a random grid straight to its file, a row block at a time.

    Identical bytes to ``write_grid(path, generate(...))`` (pinned by test)
    but with O(chunk) host memory — at 65536^2 the whole-array route is a
    4 GB text buffer plus the RNG intermediates; the chunk size scales
    inversely with width so the float64 RNG intermediate (the largest
    per-chunk allocation, 8 bytes/cell) stays ~256 MB at any width.
    """
    if chunk_rows is None:
        chunk_rows = max(1, (256 << 20) // max(width * 8, 1))
    rng = np.random.default_rng(seed)
    mm = np.memmap(path, dtype=np.uint8, mode="w+", shape=(height, row_stride(width)))
    for r0 in range(0, height, chunk_rows):
        r1 = min(height, r0 + chunk_rows)
        block = (rng.random((r1 - r0, width)) < density).astype(np.uint8)
        mm[r0:r1, :width] = block + ZERO
        mm[r0:r1, width] = NEWLINE
    mm.flush()
