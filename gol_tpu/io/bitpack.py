"""The ONE host-side bit-packing convention: bit j of word w = column 32w+j.

Shared by the engine's batch staging (``engine._pack_board_words``) and the
result cache's TensorStore payload lane (``cache/store.py``) so the
convention — little bit-order ``np.packbits`` + a little-endian ``uint32``
view, matching ``ops/packed_math.encode`` — lives exactly once: a change
that reached only one copy would silently scramble columns in the other.

Numpy-only on purpose (no jax import): the cache package must stay loadable
by the jax-free fleet router. Callers gate on ``sys.byteorder`` themselves
where big-endian hosts must take a byte lane instead.
"""

from __future__ import annotations

import numpy as np

BITS = 32


def pack_words(cells: np.ndarray) -> np.ndarray:
    """(..., W) uint8 {0,1} cells -> (..., W/32) uint32 words.

    ``np.packbits`` little bit-order fills byte k with columns 8k..8k+7,
    and the little-endian uint32 view makes byte k bits 8k..8k+7 of its
    word — so bit j of word w is column 32w+j, exactly the device kernels'
    layout. Packing on the host shrinks transfers 32x and keeps
    encode/decode out of compiled programs.
    """
    width = cells.shape[-1]
    if width % BITS:
        raise ValueError(f"width {width} is not a multiple of {BITS}")
    packed = np.packbits(cells, axis=-1, bitorder="little")
    return (
        np.ascontiguousarray(packed)
        .view(np.uint32)
        .reshape(*cells.shape[:-1], width // BITS)
    )


def unpack_words(words: np.ndarray, width: int | None = None) -> np.ndarray:
    """Inverse of ``pack_words``: (..., W/32) uint32 -> (..., W) uint8."""
    nwords = words.shape[-1]
    as_bytes = (
        np.ascontiguousarray(words)
        .view(np.uint8)
        .reshape(*words.shape[:-1], nwords * 4)
    )
    cells = np.unpackbits(as_bytes, axis=-1, bitorder="little")
    return cells if width is None else cells[..., :width]
