"""Run-length-encoded pattern codec (the standard ``.rle`` Life format).

Pattern libraries (Gosper gun, r-pentomino, spaceships) ship as ``.rle``
files: a header line ``x = W, y = H[, rule = B3/S23]`` followed by a token
stream of ``<count><tag>`` items — ``b`` dead, ``o`` alive, ``$`` end of
row, ``!`` end of pattern — with ``#``-prefixed comment lines above the
header. This codec is the giant-universe input path: a 2^16-square board
with five gliders is a few hundred bytes of RLE, where the dense text-grid
form (io/text_grid.py) would be a 4 GB file that must never be
materialized (gol_tpu/sparse/ simulates such boards tile-by-tile).

Numpy-only on purpose (no jax import): the CLI parses patterns before any
engine loads, and sparse boards build straight from the token stream via
``items`` without a dense canvas ever existing.

Dialect notes: counts are unbounded decimals; a missing count means 1;
rows shorter than ``x`` are implicitly dead-padded; ``.`` is accepted as
dead and any other letter as alive (multi-state exports mark live cells
with letters); the rule, when present, must be B3/S23 (``23/3`` in the
legacy survival/birth spelling) — every engine in this tree is B3/S23
(ROADMAP's rule-space generalization is the axis that will relax this).
"""

from __future__ import annotations

import re

import numpy as np

# Dense-materialization guard for `parse`: patterns are meant to be small
# (the universe they are placed into is the big thing). A pattern above
# this cell count is almost certainly a whole-universe dump — parse it
# through the streaming `items` path into a sparse board instead.
MAX_PATTERN_CELLS = 1 << 26

_HEADER_RE = re.compile(
    r"^\s*x\s*=\s*(\d+)\s*,\s*y\s*=\s*(\d+)"
    r"(?:\s*,\s*rule\s*=\s*(.+?))?\s*$",
    re.IGNORECASE,
)
_ITEM_RE = re.compile(r"(\d*)([A-Za-z.$!])")

# Accepted spellings of the one rule this tree implements, compared after
# lowercasing and stripping ALL whitespace: exporters disagree on case
# (``b3/s23``), spacing (``B3 / S23``), and B/S order (``S23/B3``), and
# the legacy survival/birth form spells it ``23/3``. An unsupported rule
# is still a loud error naming the rule — silently running a HighLife
# pattern under Conway semantics would corrupt results, not degrade them.
_B3S23 = frozenset({"b3/s23", "s23/b3", "23/3"})


def _check_rule(rule: str | None) -> None:
    if rule is None:
        return
    canonical = re.sub(r"\s+", "", rule).lower()
    if canonical not in _B3S23:
        raise ValueError(
            f"RLE rule {rule!r} is not B3/S23; only Conway's Life is "
            "implemented (rule-space generalization is a roadmap item)"
        )


def split_header(text: str) -> tuple[int, int, str | None, str]:
    """``(width, height, rule, body)`` of an RLE document.

    ``#`` comment lines (and blank lines) above the header are skipped;
    everything after the header line is the token body."""
    lines = text.splitlines()
    for i, line in enumerate(lines):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        m = _HEADER_RE.match(stripped)
        if not m:
            raise ValueError(
                f"RLE header expected (x = W, y = H[, rule = ...]); "
                f"got {stripped[:60]!r}"
            )
        width, height = int(m.group(1)), int(m.group(2))
        rule = m.group(3)
        _check_rule(rule)
        if width <= 0 or height <= 0:
            raise ValueError(
                f"RLE extents must be positive, got x={width}, y={height}"
            )
        return width, height, rule, "\n".join(lines[i + 1:])
    raise ValueError("RLE document has no header line")


def items(body: str):
    """Yield ``(count, tag)`` runs from an RLE token body.

    ``tag`` is ``'o'`` (alive), ``'b'`` (dead), ``'$'`` (end of row) or
    ``'!'`` (end of pattern; iteration stops there — trailing bytes after
    ``!`` are comment territory by convention and ignored). Any letter
    other than ``b`` maps to alive; ``.`` maps to dead. Garbage between
    tokens raises."""
    pos = 0
    for line in body.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        pos = 0
        while pos < len(line):
            if line[pos].isspace():
                pos += 1
                continue
            m = _ITEM_RE.match(line, pos)
            if not m:
                raise ValueError(
                    f"malformed RLE token at {line[pos:pos + 12]!r}"
                )
            count = int(m.group(1)) if m.group(1) else 1
            if count < 1:
                raise ValueError(f"RLE run count must be >= 1, got {count}")
            tag = m.group(2)
            if tag == "!":
                yield count, "!"
                return
            if tag == "$":
                yield count, "$"
            elif tag in ("b", "."):
                yield count, "b"
            else:
                yield count, "o"
            pos = m.end()
    # A missing '!' is tolerated (several generators omit it on the last
    # line); the pattern simply ends with the body.


def live_runs(text: str):
    """Stream ``(row, col, length)`` live runs of an RLE document, plus its
    extents: returns ``((width, height), iterator)``.

    The geometry-first path: nothing dense is ever built, so a
    whole-universe RLE (a sparse result round-tripping back in) costs
    O(live runs) regardless of the universe area. Runs never cross row
    boundaries; overruns past the declared extents raise."""
    width, height, _rule, body = split_header(text)

    def gen():
        row = col = 0
        for count, tag in items(body):
            if tag == "!":
                return
            if tag == "$":
                row += count
                col = 0
                continue
            if col + count > width:
                raise ValueError(
                    f"RLE row {row} overruns x={width} (run of {count} "
                    f"at column {col})"
                )
            if tag == "o":
                if row >= height:
                    raise ValueError(
                        f"RLE content at row {row} overruns y={height}"
                    )
                yield row, col, count
            col += count

    return (width, height), gen()


def parse(text: str, max_cells: int = MAX_PATTERN_CELLS) -> np.ndarray:
    """Parse an RLE document into a dense uint8 {0,1} array of shape
    ``(height, width)`` — the pattern-stamping form.

    Refuses documents whose declared area exceeds ``max_cells``: a
    whole-universe dump must go through ``live_runs`` into a sparse board,
    never through a dense canvas."""
    (width, height), runs = live_runs(text)
    if width * height > max_cells:
        raise ValueError(
            f"RLE pattern is {height}x{width} = {width * height} cells, "
            f"above the dense-parse cap of {max_cells}; build a sparse "
            "board from live_runs() instead"
        )
    grid = np.zeros((height, width), np.uint8)
    for row, col, count in runs:
        grid[row, col:col + count] = 1
    return grid


def read_file(path: str, max_cells: int = MAX_PATTERN_CELLS) -> np.ndarray:
    """Read + parse one ``.rle`` pattern file."""
    with open(path, "r", encoding="utf-8") as f:
        return parse(f.read(), max_cells=max_cells)


def _row_runs(row: np.ndarray):
    """``(start, end)`` live runs of one dense row."""
    padded = np.zeros(row.size + 2, np.int8)
    padded[1:-1] = row != 0
    d = np.diff(padded)
    starts = np.flatnonzero(d == 1)
    ends = np.flatnonzero(d == -1)
    return list(zip(starts.tolist(), ends.tolist()))


def encode_rows(rows, width: int, height: int,
                comments: tuple[str, ...] = ()) -> str:
    """Serialize ``(row_index, [(start, end), ...])`` live-run rows to an
    RLE document (rows in ascending order, runs sorted and disjoint).

    The ONE emitter both the dense ``encode`` and the sparse board's
    ``to_rle`` ride, so the two can never drift — and the output is
    deterministic byte-for-byte (journaled sparse results and byte-gate
    tests compare these strings directly)."""
    tokens: list[str] = []

    def emit(count: int, tag: str) -> None:
        if count < 1:
            return
        tokens.append((str(count) if count > 1 else "") + tag)

    prev_row = None
    for row, runs in rows:
        if not runs:
            continue
        if prev_row is None:
            emit(row, "$")
        else:
            emit(row - prev_row, "$")
        prev_row = row
        col = 0
        for start, end in runs:
            emit(start - col, "b")
            emit(end - start, "o")
            col = end
    tokens.append("!")
    lines = [f"#C {c}" for c in comments]
    lines.append(f"x = {width}, y = {height}, rule = B3/S23")
    line = ""
    for tok in tokens:
        if line and len(line) + len(tok) > 70:
            lines.append(line)
            line = ""
        line += tok
    if line:
        lines.append(line)
    return "\n".join(lines) + "\n"


def encode(grid: np.ndarray, comments: tuple[str, ...] = ()) -> str:
    """Serialize a dense uint8 {0,1} grid to an RLE document."""
    grid = np.asarray(grid, dtype=np.uint8)
    if grid.ndim != 2:
        raise ValueError(f"grid must be 2D, got shape {grid.shape}")
    height, width = grid.shape
    rows = ((r, _row_runs(grid[r])) for r in range(height))
    return encode_rows(rows, width, height, comments)


def write_file(path: str, grid: np.ndarray,
               comments: tuple[str, ...] = ()) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(encode(grid, comments))
