"""Sharded grid I/O: every shard reads/writes its own file window.

TPU-native counterpart of the reference's MPI-IO paths. The file is modeled as
a ``height x (width+1)`` byte matrix whose last column holds the newline chars
— exactly the ``MPI_Type_create_subarray`` view of the collective variant
(src/game_mpi_collective.c:174-196). Reads go through a strided memmap window
per shard (no rank ever touches another rank's bytes); writes reproduce the
east-edge trick: shards in the last mesh column own their rows' newline bytes
(src/game_mpi_collective.c:382-393), so the collective write is byte-exact
without any gather.

Strategies, mirroring the reference's three I/O engines:

- ``read_sharded`` / ``write_sharded``: the collective path
  (``MPI_File_read_all`` / ``write_all``, src/game_mpi_collective.c:194,441).
- the same with ``parallel=True``: the async path (``MPI_File_iread`` /
  ``iwrite``, src/game_mpi_async.c:194-198,444-446) — except genuinely
  overlapped via a thread pool where the reference waits immediately.
- ``read_gathered`` / ``write_gathered``: the master-scatter path — rank 0
  reads/writes everything and blocks are scattered/gathered
  (src/game_mpi.c:201-239,429-467); kept as the debug-mode I/O.

On a multi-host pod each process only materializes its addressable shards, so
no host ever holds the full grid — the property the reference gets from
MPI-IO file views.
"""

from __future__ import annotations

import concurrent.futures
import os

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.io import text_grid
from gol_tpu.io.text_grid import NEWLINE, ONE, ZERO, row_stride
from gol_tpu.parallel.mesh import grid_sharding


def _file_view(path: str, width: int, height: int, mode: str) -> np.memmap:
    return np.memmap(path, dtype=np.uint8, mode=mode, shape=(height, row_stride(width)))


def read_sharded(
    path: str,
    width: int,
    height: int,
    mesh: Mesh | None,
    parallel: bool = False,
) -> jax.Array:
    """Load a grid file directly into a mesh-sharded device array."""
    size = os.path.getsize(path)
    expected = height * row_stride(width)
    if size != expected:
        raise ValueError(
            f"{path}: size {size} != {expected} for a {height}x{width} text grid "
            f"(sharded I/O requires the exact height x (width+1) layout)"
        )
    mm = _file_view(path, width, height, "r")
    cells = mm[:, :width]  # strided view that excludes the newline column
    if mesh is None:  # single device: one window, plain placement
        return jax.numpy.asarray((np.asarray(cells) == ONE).astype(np.uint8))
    sharding = grid_sharding(mesh)

    def load_window(index) -> np.ndarray:
        # index slices may be slice(None) for unsplit dimensions.
        return (np.asarray(cells[index]) == ONE).astype(np.uint8)

    if parallel:
        # The async variant: overlap the per-shard reads (the reference's
        # iread is nonblocking in API only — it MPI_Waits immediately).
        def key(index):  # slices are only hashable on 3.12+; normalize
            return tuple((s.start, s.stop, s.step) for s in index)

        index_map = sharding.addressable_devices_indices_map((height, width))
        unique = {key(idx): idx for idx in index_map.values()}
        with concurrent.futures.ThreadPoolExecutor() as pool:
            blocks = dict(
                zip(unique, pool.map(load_window, unique.values()))
            )
        return jax.make_array_from_callback(
            (height, width), sharding, lambda idx: blocks[key(idx)]
        )
    return jax.make_array_from_callback((height, width), sharding, load_window)


def write_sharded(path: str, grid: jax.Array, parallel: bool = False) -> None:
    """Write a sharded device array straight to a grid file, no gather.

    The reference opens MODE_EXCL and delete-retries if the file exists
    (src/game_mpi_collective.c:429-436) — net effect is replacement, which is
    what creating/truncating does.
    """
    height, width = grid.shape
    # ftruncate-to-size, not open('wb'): multi-host writers must not zero
    # each other's bytes on a shared filesystem.
    text_grid.create_sized(path, height * row_stride(width))
    mm = _file_view(path, width, height, "r+")
    cells = mm[:, :width]

    def store_window(shard) -> None:
        rows, cols = shard.index[0], shard.index[1]
        cells[rows, cols] = np.asarray(shard.data, dtype=np.uint8) + ZERO
        if cols.indices(width)[1] == width:
            # East-edge shards own their rows' newline column
            # (src/game_mpi_collective.c:382-393).
            mm[rows, width] = NEWLINE

    shards = list(grid.addressable_shards)
    if parallel:
        with concurrent.futures.ThreadPoolExecutor() as pool:
            list(pool.map(store_window, shards))
    else:
        for shard in shards:
            store_window(shard)
    mm.flush()


def read_gathered(path: str, width: int, height: int, mesh: Mesh | None) -> jax.Array:
    """Master-scatter read: one host parses the file, blocks are scattered
    (src/game_mpi.c:201-239)."""
    host_grid = text_grid.read_grid(path, width, height)
    if mesh is None:
        return jax.numpy.asarray(host_grid)
    return jax.device_put(host_grid, grid_sharding(mesh))


def write_gathered(path: str, grid: jax.Array) -> None:
    """Gather-to-master write (src/game_mpi.c:429-467).

    Multi-process: ``jax.device_get`` on the global array would raise on the
    non-addressable shards, so each process assembles its addressable
    windows and the full grid is reconstructed on every host with
    ``multihost_utils.process_allgather`` — the reference's
    MPI_Recv-per-rank gather loop (src/game_mpi.c:441-458) — and the lead
    process writes serially, like its rank 0 (src/game_mpi.c:462). The
    closing barrier keeps peers from reading a half-written file. Every
    host briefly holds the full grid; that is this debug lane's contract
    (the reference's rank 0 does too) — the collective/async lanes
    (write_sharded) stay gather-free.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        height, width = grid.shape
        local = np.zeros((height, width), np.uint8)
        for shard in grid.addressable_shards:
            local[shard.index] = np.asarray(shard.data, dtype=np.uint8)
        stacked = np.asarray(multihost_utils.process_allgather(local))
        # Each global cell is owned by >= 1 process (exactly one unless
        # replicated); everyone else contributed zeros — max reassembles.
        full = stacked.max(axis=0).astype(np.uint8)
        if jax.process_index() == 0:
            text_grid.write_grid(path, full)
        multihost_utils.sync_global_devices("gol_tpu:write_gathered")
        return
    text_grid.write_grid(path, np.asarray(jax.device_get(grid), dtype=np.uint8))
