"""SIGKILL-safe flock leases: the control plane's single-writer primitives.

The PR-15 compactor proved the discipline this module extracts: an advisory
``fcntl.flock`` on a well-known file is the ONE mutual-exclusion primitive
in the tree that a SIGKILL cannot wedge — the kernel drops the lock with
the holder's last fd, no unlock code ever runs, and any survivor acquires
it on its next attempt. No heartbeats, no TTLs, no fencing tokens to
mint: the lock *is* the liveness check. (Contrast the reference's
``mpirun`` world, where the launcher is the lone coordinator and its death
is everyone's death — here coordination is a file on the fleet dir that
any replica can pick up.)

Two shapes, one rule each:

- :func:`acquire` / :func:`release` — a bounded critical *section* (a
  manifest write, a compaction pass). Blocking acquire serializes writers
  that must ALL complete; non-blocking lets the loser skip work that the
  winner's pass already covers.
- :class:`FlockLease` — a long-*held* leadership lease (the single-writer
  ticks: autoscaler, respawn supervision). ``try_acquire`` is idempotent
  and cheap enough to call every tick; holding is just keeping the fd
  open, and death — graceful or SIGKILL — is the release.

The lock file's CONTENT is observability only (holder pid + label for an
operator's ``cat``), never authority: authority is the kernel's lock
table. A reader must never parse the file to decide who leads — the file
outlives every holder, and a stale pid in it is normal, not a bug.

Clocks: none. This module has no timing at all — leases have no expiry
because the kernel's fd lifetime IS the expiry (tests/test_lint.py pins
the package-wide wall-clock ban on this file regardless, so any timing it
ever grows must be ``time.perf_counter``).
"""

from __future__ import annotations

import fcntl
import logging
import os
import threading

logger = logging.getLogger(__name__)


def acquire(path: str, *, blocking: bool = False) -> int | None:
    """Open ``path`` (creating it) and take an exclusive flock on it.

    Returns the locked fd — pass it to :func:`release` when the critical
    section ends — or ``None`` when ``blocking=False`` and another process
    (or another fd in THIS process: flock is per-open-file, so two Fleet
    instances in one test conflict like two processes) holds the lock.
    ``blocking=True`` waits: use it only for short sections every writer
    must complete (the manifest write), never for skippable work."""
    fd = os.open(path, os.O_WRONLY | os.O_CREAT, 0o644)
    try:
        flags = fcntl.LOCK_EX if blocking else fcntl.LOCK_EX | fcntl.LOCK_NB
        fcntl.flock(fd, flags)
    except OSError:
        os.close(fd)
        return None
    return fd


def release(fd: int) -> None:
    """End the critical section: closing the fd releases the flock."""
    os.close(fd)


class FlockLease:
    """A held leadership lease over ``path``, safe to poll every tick.

    ``try_acquire()`` returns whether THIS object holds the lease after
    the call — True immediately when it already does (re-acquiring an
    flock this process holds would succeed trivially; the early return
    keeps the fd stable so release semantics stay obvious). A False
    answer means some other holder is alive *right now*; ask again next
    tick — when the holder dies, by any signal, the kernel frees the
    lock and the next asker wins.

    On winning, the holder stamps ``pid label`` into the file — the
    operator-facing trail ("which router leads?"), explicitly
    non-authoritative (see module docstring).
    """

    def __init__(self, path: str, label: str = ""):
        self.path = path
        self.label = label
        self._fd: int | None = None
        self._mu = threading.Lock()

    @property
    def held(self) -> bool:
        return self._fd is not None

    def try_acquire(self) -> bool:
        with self._mu:
            if self._fd is not None:
                return True
            fd = acquire(self.path, blocking=False)
            if fd is None:
                return False
            try:
                os.ftruncate(fd, 0)
                os.write(fd, f"{os.getpid()} {self.label}\n".encode("utf-8"))
            except OSError:
                pass  # the stamp is best-effort prose, never authority
            self._fd = fd
            logger.info("lease %s acquired (pid %d%s)", self.path,
                        os.getpid(), f", {self.label}" if self.label else "")
            return True

    def release(self) -> None:
        """Voluntary hand-off (drain/shutdown); crash release is the
        kernel's job and needs no call."""
        with self._mu:
            if self._fd is not None:
                release(self._fd)
                self._fd = None
                logger.info("lease %s released", self.path)
