"""Affinity-aware placement: capacity weights for the weighted-HRW layer.

Plain rendezvous placement (fleet/placement.rank) treats every worker as
interchangeable — correct for N identical processes on N identical core
slices, and exactly wrong for the fleets the autoscaler builds: a worker
pinned to 2 cores next to one pinned to 8, a mesh-capable big-lane host
next to packed small-bucket workers, an attached remote whose device kind
differs from the local pool's. The PAPERS process-to-node-mapping framing
applies one level up: placement is an optimization problem over MEASURED
capacity, not hash rank alone.

This module is the policy half of that layer (the mechanism is
``placement.rank_weighted``):

- every ``Worker`` carries an optional **pinned weight** (set at spawn
  time — ``gol fleet --cores-per-worker N`` pins worker k to its own
  N-core ``taskset`` slice and weights it N — or recovered from the
  manifest, so two routers over one fleet agree);
- a worker with no pinned weight may **advertise** one: ``GET /healthz``
  reports the tuner-persisted marginal kernel rates of the worker's own
  plan cache folded to one number (``GolServer.advertised_weight`` — the
  PR-7 measured roofline, cells/s), and the fleet health loop adopts it. A host whose measured
  kernels run at half the rate takes proportionally fewer buckets;
- ``weights_for`` folds both into the weight map ``rank_weighted``
  consumes. Weights are RELATIVE (rendezvous scores scale linearly), so
  cores and cells/s never mix units inside one fleet as long as one
  source dominates — pinned weights win over advertised ones fleet-wide
  whenever any worker has one, keeping the map comparable.

Default OFF: without ``--affinity`` the router never builds a weight map
and ranks through plain HRW, byte-identically (test-pinned). With
``--affinity`` but all-equal weights, ``rank_weighted`` delegates to
plain ``rank`` — the same bytes again, so turning the flag on is safe
before any weight exists to act on.

Jax-free like the rest of the package: weights arrive as numbers (CLI
flags, manifest fields, /healthz payloads), never from a device probe.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)

DEFAULT_WEIGHT = 1.0


def weights_for(workers) -> dict[str, float]:
    """The weighted-HRW map for one candidate pool: worker id -> weight.

    Pinned weights (``Worker.weight``) win over advertised ones
    (``Worker.advertised_weight``); if ANY worker in the pool is pinned,
    advertised values are ignored for the whole pool (cores and measured
    cells/s are different units — mixing them would weight a 4-core
    worker against a 10^8-cells/s one). Workers with neither get
    ``DEFAULT_WEIGHT``. An all-default map still ranks byte-identically
    to plain HRW via ``rank_weighted``'s equal-weight delegation."""
    pool = list(workers)
    pinned = any(_positive(w.weight) for w in pool)
    out: dict[str, float] = {}
    for worker in pool:
        if pinned:
            out[worker.id] = _positive(worker.weight) or DEFAULT_WEIGHT
        else:
            out[worker.id] = (_positive(worker.advertised_weight)
                              or DEFAULT_WEIGHT)
    return out


def _positive(w) -> float | None:
    try:
        w = float(w)
    except (TypeError, ValueError):
        return None
    return w if w > 0 else None


__all__ = ["DEFAULT_WEIGHT", "weights_for"]
