"""Per-worker circuit breakers: stop hammering a failing backend.

The router's spillover walk (PR 8) reacts to each failure AFTER paying for
it — every submit to a down or brownout worker costs a connect timeout or
an ambiguous 504 before the next candidate gets a try. A breaker moves
that cost off the hot path: consecutive failures (a hard-down worker) or a
degraded fraction of recent calls (a brownout: slow answers and resets
mixed into successes) flip the worker's breaker OPEN, and the router ranks
open workers LAST — not removed, so the HRW bucket affinity is intact the
moment the worker recovers, and an open worker is still the last resort
when everything better is gone.

State machine (the textbook shape, perf_counter-clocked)::

    CLOSED --consecutive failures >= fail_threshold,
             or degraded fraction of the last `window` calls
             >= degraded_rate (with min_volume)-->        OPEN
    OPEN   --cooldown_s elapsed, next ranked attempt-->   HALF_OPEN
    HALF_OPEN --probe succeeds--> CLOSED
    HALF_OPEN --probe fails-->    OPEN (cooldown re-arms)

HALF_OPEN admits ONE probe: the first attempt after the cooldown runs at
normal rank; while that probe is in flight the worker ranks last again, so
a recovering worker sees a trickle, not a stampede ("thundering herd" is
the failure mode half-open exists to prevent). "Degraded" counts failures
AND slow calls (latency above ``slow_s``): a worker answering everything
200-in-4-seconds is as routable-around as one refusing connections.

The breaker holds NO HTTP knowledge: the router records outcomes
(``on_success(latency)``/``on_failure()``) and reads ``penalty()`` when
ranking. Transitions fire an optional callback — the router's hook into
metrics gauges and the durable breaker ring.

Clocks: ``time.perf_counter`` only (gol_tpu/fleet wall-clock ban).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

# Gauge encoding (gol_fleet_breaker_state): closed=0, half-open=1, open=2.
STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    """The thresholds (CLI: ``gol fleet`` defaults; bench A/Bs them)."""

    fail_threshold: int = 3  # consecutive failures -> OPEN
    window: int = 20  # recent-call ring for the degraded-rate trip
    degraded_rate: float = 0.5  # degraded fraction of the window -> OPEN
    min_volume: int = 10  # window calls required before the rate can trip
    slow_s: float | None = 1.0  # latency above this counts as degraded
    cooldown_s: float = 5.0  # OPEN holds this long before a probe

    def __post_init__(self):
        if self.fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1, got {self.fail_threshold}"
            )
        if self.window < 1 or self.min_volume < 1:
            raise ValueError("window/min_volume must be >= 1")
        if not 0.0 < self.degraded_rate <= 1.0:
            raise ValueError(
                f"degraded_rate must be in (0, 1], got {self.degraded_rate}"
            )
        if self.slow_s is not None and self.slow_s <= 0:
            raise ValueError(f"slow_s must be > 0, got {self.slow_s}")
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {self.cooldown_s}")


class CircuitBreaker:
    """One worker's breaker. Thread-safe; every router thread records
    outcomes and reads penalties concurrently."""

    def __init__(self, config: BreakerConfig | None = None,
                 clock=time.perf_counter, on_transition=None,
                 label: str = ""):
        self.config = config or BreakerConfig()
        self._clock = clock
        self._on_transition = on_transition  # fn(label, old, new) or None
        self.label = label
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._recent: collections.deque = collections.deque(
            maxlen=self.config.window
        )
        self._opened_at: float | None = None
        self._probing = False
        self.opens = 0  # cumulative transitions into OPEN

    # -- reads --------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def penalty(self) -> int:
        """Ranking penalty for the router's candidate order: 0 = route
        normally (CLOSED, or OPEN-past-cooldown — the would-be probe must
        rank normally or recovery never gets traffic), 1 = rank last."""
        with self._lock:
            if self._state == CLOSED:
                return 0
            if self._state == OPEN and self._cooldown_over_locked():
                return 0
            return 1  # OPEN inside cooldown, or HALF_OPEN probe in flight

    def _cooldown_over_locked(self) -> bool:
        return (self._opened_at is None
                or self._clock() - self._opened_at >= self.config.cooldown_s)

    # -- outcome recording --------------------------------------------------

    def on_attempt(self) -> bool:
        """The router is about to use this worker. An OPEN breaker past
        its cooldown becomes HALF_OPEN with THIS call as its single
        probe. Returns whether the caller holds a normal-rank slot:
        True = proceed (CLOSED, or this call just claimed the probe);
        False = the worker is penalized RIGHT NOW (OPEN inside cooldown,
        or another caller's probe is in flight) — ``penalty()`` may have
        said 0 when the candidates were ranked, but a concurrent caller
        claimed the probe first, and forwarding anyway would stampede the
        recovering worker. The router defers False-answered workers to
        the end of its walk (still the last resort, never skipped)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if (self._state == OPEN and self._cooldown_over_locked()
                    and not self._probing):
                self._transition_locked(HALF_OPEN)
                self._probing = True
                return True
            return False

    def on_success(self, latency_s: float = 0.0) -> None:
        slow = (self.config.slow_s is not None
                and latency_s > self.config.slow_s)
        with self._lock:
            if self._state == HALF_OPEN:
                # The probe answered: a fast answer closes; a degraded one
                # is not recovery — re-open and wait out another cooldown.
                self._probing = False
                if slow:
                    self._open_locked()
                else:
                    self._transition_locked(CLOSED)
                    self._consecutive = 0
                    self._recent.clear()
                return
            self._consecutive = 0
            self._recent.append(bool(slow))
            self._maybe_trip_locked()

    def reopen(self) -> None:
        """Warm-start restore (fleet/replicate.py): re-arm the OPEN a
        previous router incarnation's durable ring recorded, with a fresh
        cooldown from NOW — the successor then makes first contact the
        way every open breaker does, via one half-open probe after the
        cooldown, instead of re-learning the failure on real traffic.
        Fires ``on_transition`` like any trip, so the restore itself
        lands in the ring (keeping warm-start idempotent across
        successive router respawns). No-op unless CLOSED."""
        with self._lock:
            if self._state == CLOSED:
                self._open_locked()

    def on_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._probing = False
                self._open_locked()
                return
            if self._state == OPEN:
                # A last-resort call failed while already open: re-arm the
                # cooldown so the probe clock starts from the fresh evidence.
                self._opened_at = self._clock()
                return
            self._consecutive += 1
            self._recent.append(True)
            if self._consecutive >= self.config.fail_threshold:
                self._open_locked()
                return
            self._maybe_trip_locked()

    def _maybe_trip_locked(self) -> None:
        cfg = self.config
        if len(self._recent) < cfg.min_volume:
            return
        degraded = sum(self._recent) / len(self._recent)
        if degraded >= cfg.degraded_rate:
            self._open_locked()

    def _open_locked(self) -> None:
        self._transition_locked(OPEN)
        self._opened_at = self._clock()
        self._consecutive = 0
        self._recent.clear()
        self._probing = False

    def _transition_locked(self, new: str) -> None:
        old, self._state = self._state, new
        if new == OPEN and old != OPEN:
            self.opens += 1
        if old != new:
            logger.warning("breaker %s: %s -> %s", self.label or "?",
                           old, new)
            if self._on_transition is not None:
                # Fired under the lock on purpose: transitions are rare,
                # and an out-of-order gauge write (open after the re-close
                # that followed it) would be worse than the contention.
                self._on_transition(self.label, old, new)

    def public(self) -> dict:
        """What /fleet and the durable ring record."""
        with self._lock:
            return {
                "state": self._state,
                "opens": self.opens,
                "consecutive_failures": self._consecutive,
                "window": len(self._recent),
                "degraded": (sum(self._recent) / len(self._recent)
                             if self._recent else 0.0),
            }


__all__ = ["BreakerConfig", "CircuitBreaker", "CLOSED", "HALF_OPEN",
           "OPEN", "STATE_VALUE"]
