"""SLO-driven autoscaling: the loop that closes sensors onto actuators.

``gol fleet --workers N`` is static: a human picks N at boot and the
fleet holds it through traffic spikes and dead air alike. Every signal
needed to do better already exists — PR-7's multi-window SLO burn rates,
the queue-saturation gauges, the per-bucket dispatch-gap ratios — and so
does every actuator: PR-8's supervised spawn/respawn, cascaded drain, and
HRW's test-pinned minimal-disruption placement. This module is only the
loop between them:

- **scale up** when the fleet is provably behind: a worker's SLO engine
  reports an objective CRITICAL (by construction that means burn >=
  ``critical_burn`` on EVERY window — the multi-window rule, so one slow
  batch cannot trigger a spawn) or merged queue depth climbs past
  ``up_saturation`` of the fleet-wide admission cap, sustained for
  ``up_sustain`` consecutive ticks. The new worker lands on the lowest
  free partition id (reusing retired partitions, whose journals hold
  only terminal records) and — under ``--cores-per-worker`` pinning — on
  its own core slice. HRW hands it ONLY the buckets it now owns; nothing
  else moves.
- **scale down** when capacity is provably idle: fleet occupancy (queued
  + in-flight over the admission cap) below ``down_occupancy`` with no
  SLO burn, sustained for ``down_sustain`` ticks. The emptiest worker is
  drained (every accepted job finishes and journals its done record),
  then stopped and removed — ``Fleet.retire``'s ordering guarantees the
  partition is never orphaned mid-job, and HRW moves only the retiree's
  buckets back. A drain that fails aborts the retire: capacity is
  cheaper than a job.
- **hysteresis + cooldown** prevent flapping: the up and down conditions
  are separated by a wide dead band (0.8 of cap vs 0.05 of cap by
  default), each needs its sustain streak, and after any scale event no
  new decision fires for ``cooldown_s``.

The tick rides the fleet health loop (``Fleet.add_tick_hook``) — one
cadence, one thread, and the worker /slo payloads the loop already
fetched per tick are the burn signal (no second probe fan-out). Actions
run on a background thread (a spawn blocks in ``_await_ready`` for a
worker boot; the health loop must keep probing meanwhile); one action in
flight at a time.

Every decision is observable three ways (the ISSUE's "why did the fleet
grow" contract): ``fleet.scale`` spans + ``autoscaler_*`` series on the
router registry (merged /metrics, ``gol top``), and a decision record
per tick appended to a PR-10 durable history ring
(``<fleet-dir>/autoscaler-history``) that ``gol history-report`` and the
bench suite replay.

Clocks: ``time.perf_counter`` only (the package-wide wall-clock ban).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time

from gol_tpu.obs import trace as obs_trace

logger = logging.getLogger(__name__)

UP = "up"
DOWN = "down"
HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """The policy knobs (CLI: ``gol fleet --autoscale ...``)."""

    min_workers: int = 1
    max_workers: int = 4
    up_saturation: float = 0.8  # queued / (per-worker cap * workers)
    up_sustain: int = 2  # consecutive ticks the up condition must hold
    down_occupancy: float = 0.05  # (queued + inflight) / cap
    down_sustain: int = 10
    cooldown_s: float = 30.0
    drain_timeout: float = 600.0

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}"
            )
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= min_workers "
                f"({self.min_workers})"
            )
        if not 0.0 < self.up_saturation <= 1.0:
            raise ValueError(
                f"up_saturation must be in (0, 1], got {self.up_saturation}"
            )
        if not 0.0 <= self.down_occupancy < self.up_saturation:
            raise ValueError(
                f"down_occupancy ({self.down_occupancy}) must be >= 0 and "
                f"below up_saturation ({self.up_saturation}) — the dead "
                "band IS the flap protection"
            )
        if self.up_sustain < 1 or self.down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {self.cooldown_s}")


class Autoscaler:
    """One autoscaling loop over one fleet + router pair.

    ``queue_capacity`` is the PER-WORKER admission cap (the workers'
    ``--max-queue-depth``): saturation and occupancy normalize against
    ``cap * live_normal_workers``, so the thresholds mean the same thing
    at every fleet size. ``tick()`` is public and synchronous-decision /
    asynchronous-action; tests drive it deterministically with an
    injected clock and stub fleet/router."""

    def __init__(
        self,
        fleet,
        router,
        config: AutoscaleConfig | None = None,
        queue_capacity: int = 1024,
        history=None,
        clock=time.perf_counter,
        sync_actions: bool = False,
    ):
        self.fleet = fleet
        self.router = router
        self.config = config or AutoscaleConfig()
        self.queue_capacity = max(1, int(queue_capacity))
        self.history = history  # obs/history.HistoryWriter or None
        self._clock = clock
        self._sync_actions = sync_actions  # tests: act inline, no thread
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._last_event_at: float | None = None
        self._action_thread: threading.Thread | None = None
        self._acting = False
        self._closed = False
        self._ticks = 0
        self._last_decision: dict | None = None
        self._last_scale: dict | None = None
        self._target: int | None = None

    # -- signals -----------------------------------------------------------

    def _normals(self) -> list:
        """The scalable pool: local, non-big, non-retiring workers (the
        big lane and attached workers are not the autoscaler's to move)."""
        return [w for w in self.fleet.workers()
                if not w.big and not w.attached and not w.retiring]

    def signals(self) -> dict:
        """One tick's sensor read, scoped to the pool a scale event can
        actually help: queue/inflight summed over the NORMAL-bucket
        workers (big-lane queues are a separate pool — spawning a normal
        worker cannot absorb them; retiring workers take nothing new and
        their stored /slo is frozen), burn/criticality from the same
        pool (attached normals share the bucket load, so their burn IS a
        legitimate scale-up signal even though only local workers can be
        spawned/retired), per-bucket dispatch-gap ratios as context.
        Saturation/occupancy normalize by the serving pool's aggregate
        admission cap; the min/max clamps in ``decide`` count only the
        SCALABLE (local) workers."""
        snaps, merged = self.router._merged_snapshot()
        gauges = merged.get("gauges") or {}
        # pool = everyone absorbing normal-bucket load; scalable = the
        # subset the actuators can add/remove.
        pool = [w for w in self.fleet.workers()
                if not w.big and not w.retiring]
        pool_ids = {w.id for w in pool}
        cap = float(self.queue_capacity * max(1, len(pool)))
        queued = inflight = 0.0
        per_worker = {}
        for wid, snap in snaps.items():
            wg = (snap or {}).get("gauges") or {}
            load_q = float(wg.get("queue_depth") or 0.0)
            load_i = float(wg.get("inflight_batches") or 0.0)
            per_worker[wid] = load_q + load_i
            if wid in pool_ids:
                queued += load_q
                inflight += load_i
        burn = 0.0
        critical = []
        for worker in pool:
            if not worker.healthy or worker.respawning:
                # check_worker only refreshes .slo on a successful probe:
                # an unreachable attached worker (never respawned) or a
                # local worker stuck in a respawn loop carries a payload
                # frozen at its last good tick, and a frozen CRITICAL
                # would pin the up-condition true on dead data.
                continue
            slo = worker.slo
            if not slo:
                continue
            for obj in slo.get("objectives") or []:
                burn = max(burn, float(obj.get("burn") or 0.0))
                if obj.get("status") == "critical":
                    critical.append(f"{worker.id}:{obj.get('name')}")
        gaps = {
            name[len("dispatch_gap_ratio_"):]: round(float(value), 4)
            for name, value in gauges.items()
            if name.startswith("dispatch_gap_ratio_")
        }
        return {
            "workers": len(self._normals()),
            "pool": len(pool),
            "queued": queued,
            "inflight": inflight,
            "saturation": queued / cap,
            "occupancy": (queued + inflight) / cap,
            "burn": round(burn, 4),
            "critical": critical,
            "gap_ratios": gaps,
            "per_worker_load": per_worker,
        }

    # -- decision ----------------------------------------------------------

    def decide(self, signals: dict) -> dict:
        """Pure-ish policy: fold one tick's signals into the streaks and
        return the decision record (``action`` in {up, down, hold} plus
        the triggering signal). Mutates only the hysteresis state."""
        cfg = self.config
        n = signals["workers"]
        up_condition = bool(signals["critical"]) or (
            signals["saturation"] >= cfg.up_saturation
        )
        down_condition = (
            not signals["critical"]
            and signals["burn"] < 1.0
            and signals["occupancy"] <= cfg.down_occupancy
        )
        self._up_streak = self._up_streak + 1 if up_condition else 0
        self._down_streak = self._down_streak + 1 if down_condition else 0
        now = self._clock()
        cooling = (self._last_event_at is not None
                   and now - self._last_event_at < cfg.cooldown_s)
        action, reason = HOLD, ""
        if self._acting:
            reason = "action in flight"
        elif cooling:
            reason = "cooldown"
        elif (up_condition and self._up_streak >= cfg.up_sustain
                and n < cfg.max_workers):
            action = UP
            reason = ("slo critical: " + ",".join(signals["critical"])
                      if signals["critical"] else
                      f"queue saturation {signals['saturation']:.2f} >= "
                      f"{cfg.up_saturation:.2f}")
        elif up_condition and self._up_streak >= cfg.up_sustain:
            reason = f"at max_workers {cfg.max_workers}"
        elif (down_condition and self._down_streak >= cfg.down_sustain
                and n > cfg.min_workers):
            action = DOWN
            reason = (f"occupancy {signals['occupancy']:.3f} <= "
                      f"{cfg.down_occupancy:.3f} for {self._down_streak} "
                      "ticks")
        elif down_condition and self._down_streak >= cfg.down_sustain:
            reason = f"at min_workers {cfg.min_workers}"
        target = n + (1 if action == UP else -1 if action == DOWN else 0)
        return {
            "action": action,
            "reason": reason,
            "target": target,
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            **{k: v for k, v in signals.items() if k != "per_worker_load"},
        }

    # -- the tick ----------------------------------------------------------

    def tick(self) -> dict | None:
        """One autoscaler evaluation (rides ``Fleet.health_tick``)."""
        if self._closed or getattr(self.router, "_draining", False):
            return None
        if not getattr(self.fleet, "supervise", True):
            # Leader-gated (fleet/lease.py): only the lease holder makes
            # scale decisions — two replicas double-counting one queue
            # spike would spawn twice the workers. A follower also skips
            # the streak/cooldown bookkeeping on purpose: when it takes
            # over, it starts from clean hysteresis instead of streaks
            # accumulated while powerless to act.
            return None
        signals = self.signals()
        decision = self.decide(signals)
        victim = None
        if decision["action"] == DOWN:
            # Resolved BEFORE the decision is exported/recorded: a DOWN
            # with no retireable worker demotes to HOLD everywhere —
            # gauges, the `gol top` panel, and the durable ring must
            # never disagree about what this tick decided.
            victim = self._pick_victim(signals)
            if victim is None:
                decision["action"] = HOLD
                decision["reason"] = "no retireable worker"
                decision["target"] = signals["workers"]
            else:
                decision["victim"] = victim
        self._ticks += 1
        self._last_decision = decision
        self._target = decision["target"]
        self._export(decision)
        if decision["action"] == UP:
            self._launch_action(UP, None, decision)
        elif decision["action"] == DOWN:
            self._launch_action(DOWN, victim, decision)
        self._record(decision)
        return decision

    def _pick_victim(self, signals: dict) -> str | None:
        """The emptiest retireable worker (least queued + in-flight per
        this tick's scrape; drain finishes whatever it does hold)."""
        load = signals.get("per_worker_load") or {}
        normals = self._normals()
        if len(normals) <= self.config.min_workers:
            return None
        return min(normals, key=lambda w: (load.get(w.id, 0.0), w.id)).id

    # -- actuation ---------------------------------------------------------

    def _launch_action(self, action: str, victim: str | None,
                       decision: dict) -> None:
        with self._lock:
            # _closed is re-checked HERE, under the lock close() takes to
            # set it: a tick already past its entry check when shutdown
            # begins must not launch a spawn that close() never joins
            # (an orphaned serve process after `gol fleet` exits).
            if self._acting or self._closed:
                return
            self._acting = True

        def run():
            try:
                with obs_trace.span("fleet.scale", action=action,
                                    worker=victim or "",
                                    reason=decision["reason"],
                                    target=decision["target"]):
                    ok = (self._scale_up() if action == UP
                          else self._scale_down(victim))
                outcome = {
                    "action": action, "ok": ok,
                    "worker": victim, "reason": decision["reason"],
                    "target": decision["target"],
                }
                self._last_scale = outcome
                self._record({"record_kind": "scale", **outcome})
            finally:
                with self._lock:
                    self._acting = False
                    self._last_event_at = self._clock()
                    self._up_streak = 0
                    self._down_streak = 0

        if self._sync_actions:
            run()
            return
        with self._lock:
            if self._closed:
                self._acting = False
                return
            # Assigned AND started under the lock: close() reads
            # _action_thread under the same lock, so any launched action
            # is always alive by the time close() decides whether to join.
            thread = threading.Thread(
                target=run, name="gol-fleet-autoscale", daemon=True
            )
            self._action_thread = thread
            thread.start()

    def _scale_up(self) -> bool:
        try:
            worker = self.fleet.spawn()
        except (RuntimeError, OSError) as err:
            logger.error("autoscaler: scale-up spawn failed (%s); will "
                         "retry after cooldown", err)
            self.router.registry.inc("autoscaler_scale_failures_total")
            return False
        self.router.registry.inc("autoscaler_scale_ups_total")
        logger.warning("autoscaler: scaled UP to %d workers (+%s)",
                       len(self._normals()), worker.id)
        return True

    def _scale_down(self, victim: str) -> bool:
        ok = self.fleet.retire(victim,
                               drain_timeout=self.config.drain_timeout)
        if ok:
            self.router.registry.inc("autoscaler_scale_downs_total")
            logger.warning("autoscaler: scaled DOWN to %d workers (-%s)",
                           len(self._normals()), victim)
        else:
            self.router.registry.inc("autoscaler_scale_failures_total")
        return ok

    # -- observability -----------------------------------------------------

    def _export(self, decision: dict) -> None:
        reg = self.router.registry
        reg.set_gauge("autoscaler_workers", decision["workers"])
        reg.set_gauge("autoscaler_target_workers", decision["target"])
        reg.set_gauge("autoscaler_queue_saturation",
                      round(decision["saturation"], 4))
        reg.set_gauge("autoscaler_occupancy",
                      round(decision["occupancy"], 4))
        reg.inc("autoscaler_ticks_total")

    def _record(self, decision: dict) -> None:
        if self.history is None:
            return
        self.history.append({"autoscaler": decision})

    def public(self) -> dict:
        """The ``gol top`` / merged-metrics panel payload."""
        cfg = self.config
        return {
            "enabled": True,
            "min": cfg.min_workers,
            "max": cfg.max_workers,
            "workers": len(self._normals()),
            "target": self._target,
            "scaling": self._acting,
            "ticks": self._ticks,
            "last_decision": self._last_decision,
            "last_scale": self._last_scale,
        }

    def close(self, timeout: float = 30.0) -> None:
        """Stop deciding and join any in-flight action (shutdown must not
        race a spawn it will never supervise)."""
        with self._lock:
            self._closed = True
            thread = self._action_thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=timeout)
        if self.history is not None:
            self.history.close()


__all__ = ["AutoscaleConfig", "Autoscaler", "DOWN", "HOLD", "UP"]
