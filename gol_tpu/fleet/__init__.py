"""The horizontal serving tier: a router front-end over N serve workers.

``gol serve`` (PRs 2-7) is one process on one device; this package is the
fleet around it — the analog of the reference promoting one rank's loop to
an ``MPI_Cart_create`` topology of ranks:

- ``placement``  — deterministic bucket -> worker mapping (rendezvous
  hashing; the process-to-node mapping problem of PAPERS, solved so each
  worker's <= 7-program-per-bucket compile budget and resident rings stay
  hot on one worker);
- ``workers``    — membership: spawn local ``gol serve`` subprocesses on
  journal partitions, or attach multi-host workers by URL; the manifest,
  health/burn probing, supervised respawn, fleet-wide drain;
- ``router``     — the HTTP front-end: single-server API unchanged,
  bucket-routed submits with 429/unreachable spillover, fleet-merged
  ``/metrics`` + ``/slo``, ``/fleet`` membership, cascaded ``/drain``;
- ``client``     — the stdlib HTTP JSON client all of the above share.

The whole package is jax-free on purpose: the router owns no device, and a
fleet process must boot (and restart) in milliseconds, not at import-jax
speed. Exactly-once across the fleet is the sum of the per-partition
journals — the router persists nothing but the membership manifest.
"""

from gol_tpu.fleet.placement import PLACEMENT_QUANTUM, PlacementKey  # noqa: F401
from gol_tpu.fleet.router import RouterServer  # noqa: F401
from gol_tpu.fleet.workers import Fleet, Worker  # noqa: F401
