"""Fleet membership: worker processes, the partitioned journal, health.

One ``Fleet`` owns N serving workers. A worker is either

- **local** — a ``gol serve`` subprocess this process spawned, bound to its
  own journal *partition* (``<fleet_dir>/<worker_id>/``). Local workers are
  supervised: a dead or unresponsive one is SIGKILLed (never leave two
  writers on one journal) and respawned on the SAME partition, whose
  replay-on-start (PR 2) finishes every accepted job exactly once; or
- **attached** — an externally managed ``gol serve`` reached by URL (the
  multi-host lane: boot workers wherever ``parallel/bootstrap.py`` put the
  devices, hand the router their URLs). Attached workers are health-checked
  and routed around, never respawned — their journals are theirs.

The **manifest** (``<fleet_dir>/manifest.json``, written atomically) is the
router-side membership record: every partition's id, journal subdir, last
URL, and pid. A restarted router reads it and *reattaches* — workers that
survived the router keep serving uninterrupted (probed live by URL), dead
local partitions are respawned and replay themselves. Fleet-wide
exactly-once needs nothing more: every job lives in exactly one partition,
and each partition's journal already guarantees exactly-once within it.

Health rides the PR-7 obs/SLO surfaces: liveness is ``GET /healthz``,
burn-awareness is ``GET /slo`` — a worker whose SLO status is critical (or
actively shedding 429s) is marked ``backpressure`` and drained of NEW work
by the router's placement before clients ever see a 429.

Clocks: ``time.perf_counter`` only (the serve/obs wall-clock ban extends to
this package via tests/test_lint.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import signal
import subprocess
import sys
import threading
import time

from gol_tpu.fleet import client, lease

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"
# Cross-process serialization of manifest writes (fleet/lease.py, the
# compaction.lock discipline): an attached second router, a respawning
# supervisor, and an offline `gol compact` may all hold Fleet objects on
# one fleet dir — the in-process _manifest_lock cannot see each other.
MANIFEST_LOCK = "manifest.lock"
# The leader lease: whoever flocks it runs the single-writer ticks
# (respawn supervision, autoscaling). SIGKILL-safe — the kernel releases
# it with the holder's last fd, and any survivor acquires it next tick.
LEADER_LOCK = "leader.lock"
_URL_RE = re.compile(rb"serving on (http://\S+)")


def core_slice_prefix(width: int, ncores: int | None = None):
    """A ``Fleet(spawn_prefix=...)`` hook pinning worker k to its own
    equal ``taskset`` core slice (the fixed per-worker budget of a
    one-worker-per-device deployment, on a shared host).

    The slice index comes from the digits of the worker id, so a respawn
    keeps its slice and an autoscaled spawn lands on a distinct one. The
    modulo wraps slices once the host runs out of distinct cores — the
    CLI rejects ``width > cpu_count`` up front, because taskset fails
    outright on a range that names CPUs the host does not have. One
    definition shared by ``gol fleet --cores-per-worker`` and the bench
    fleet lanes: the bench must pin exactly like production."""
    if width < 1:
        raise ValueError(f"core slice width must be >= 1, got {width}")
    ncores = ncores or os.cpu_count() or width
    if width > ncores:
        raise ValueError(
            f"core slice width {width} exceeds the host's {ncores} cores"
        )

    def prefix(worker):
        index = int("".join(c for c in worker.id if c.isdigit()) or 0)
        lo = (index * width) % max(1, ncores - width + 1)
        return ["taskset", "-c", f"{lo}-{lo + width - 1}"]

    return prefix


@dataclasses.dataclass
class Worker:
    """One serving worker as the fleet sees it."""

    id: str
    url: str | None = None
    journal_dir: str | None = None  # partition dir; None for attached
    big: bool = False  # the oversized-board lane
    attached: bool = False  # by-URL: never spawned or respawned here
    proc: subprocess.Popen | None = None
    pid: int | None = None  # survives manifest round-trips (proc does not)
    log_path: str | None = None
    log_offset: int = 0  # where THIS boot's log starts (the log appends)
    healthy: bool = True
    backpressure: bool = False  # SLO-critical / shedding: no NEW work
    failures: int = 0  # consecutive failed liveness probes
    restarts: int = 0
    respawning: bool = False  # a background respawn is in flight
    retiring: bool = False  # autoscaler drain->retire in flight: no NEW work
    # Affinity weights (fleet/affinity.py): ``weight`` is the operator-
    # pinned capacity (e.g. the --cores-per-worker slice width, manifest-
    # persisted so routers agree across restarts); ``advertised_weight``
    # is what the worker's own /healthz reported (its tuned marginal
    # kernel rate) — adopted by the health loop, never persisted.
    weight: float | None = None
    advertised_weight: float | None = None
    # The worker's last GET /slo payload, stored by the health tick so
    # the autoscaler reads burn rates without a second probe fan-out.
    slo: dict | None = None

    def manifest_record(self) -> dict:
        return {
            "id": self.id,
            "url": self.url,
            "journal": (os.path.basename(self.journal_dir)
                        if self.journal_dir else None),
            "big": self.big,
            "attached": self.attached,
            "pid": self.pid,
            **({"weight": self.weight} if self.weight is not None else {}),
        }

    def public(self) -> dict:
        """What GET /fleet shows (and what tools/fleet_smoke.py kills by)."""
        return {
            "id": self.id,
            "url": self.url,
            "big": self.big,
            "attached": self.attached,
            "healthy": self.healthy,
            "backpressure": self.backpressure,
            "retiring": self.retiring,
            "pid": self.pid,
            "restarts": self.restarts,
            **({"weight": self.weight} if self.weight is not None else {}),
        }


class Fleet:
    """Membership + manifest + supervision for one set of workers."""

    def __init__(
        self,
        fleet_dir: str,
        serve_args: tuple | list = (),
        fail_after: int = 3,
        boot_timeout: float = 180.0,
        probe=client.probe,
        http=client.http_json,
        spawn_prefix=None,
        spawn_weight: float | None = None,
        replica: bool = False,
    ):
        self.fleet_dir = fleet_dir
        os.makedirs(fleet_dir, exist_ok=True)
        self.serve_args = list(serve_args)
        # Optional command prefix per worker (callable Worker -> [str]):
        # e.g. a `taskset -c` core slice so every worker gets an equal,
        # fixed resource budget on a shared host (the bench suite's
        # scale-out control; a real fleet gives each worker its own device).
        self._spawn_prefix = spawn_prefix
        # Default pinned affinity weight for local spawns (the
        # --cores-per-worker slice width): every spawned worker — incl.
        # autoscaled ones — carries it unless spawn() pins its own.
        self._spawn_weight = spawn_weight
        self.fail_after = fail_after
        self.boot_timeout = boot_timeout
        self._probe = probe
        self._http = http
        self._lock = threading.Lock()
        self._workers: dict[str, Worker] = {}
        self._health_thread: threading.Thread | None = None
        self._health_stop = threading.Event()
        self._respawns: dict[str, threading.Thread] = {}
        self._manifest_lock = threading.Lock()
        # Replica mode (`gol router`): this Fleet is a READ view of a
        # membership some other process owns — load() adopts without
        # respawning, the manifest is never written while following, and
        # supervision stays off until the leader lease is won. The data
        # plane (placement, forwards, probes) is identical either way:
        # HRW is deterministic, so every replica routes like the leader.
        self.replica = replica
        # Whether THIS process runs the single-writer ticks (respawn,
        # and — via the autoscaler's gate — scale decisions). Flips
        # False -> True exactly once, when the leader lease is won; a
        # live holder never loses it (flock releases only on death).
        self.supervise = not replica
        self._lease: lease.FlockLease | None = None
        # Optional fleet-level config block carried IN the manifest (the
        # serve args, router flags, and autoscale settings a replica
        # needs to take over as leader): set by the spawning CLI before
        # the first write, adopted by load()/reconcile on replicas —
        # membership AND configuration share one source of truth.
        self.manifest_config: dict | None = None
        # Per-tick hooks (the autoscaler's ride on the health loop): each
        # is called after the worker probes of every health tick, inside
        # the tick's own exception guard.
        self._tick_hooks: list = []

    # -- membership --------------------------------------------------------

    def workers(self) -> list[Worker]:
        with self._lock:
            return list(self._workers.values())

    def worker(self, worker_id: str) -> Worker | None:
        with self._lock:
            return self._workers.get(worker_id)

    def shard_pool(self) -> list[Worker]:
        """The workers eligible to HOLD a shard of a sharded single-job
        run (gol_tpu/shard): routable, healthy, not mid-drain. Stricter
        than the submit walk on purpose — a shard assignment is sticky
        for the whole job (its checkpoints live in the owner's journal
        partition), so a wobbling worker that a submit would merely
        deprioritize must not anchor a shard. Sorted by id: every caller
        derives the same membership list, and the HRW partition is a
        pure function of that list."""
        with self._lock:
            pool = [w for w in self._workers.values()
                    if w.url and w.healthy and not w.retiring
                    and not w.respawning]
        return sorted(pool, key=lambda w: w.id)

    def _add(self, worker: Worker) -> Worker:
        with self._lock:
            if worker.id in self._workers:
                raise ValueError(f"duplicate worker id {worker.id}")
            self._workers[worker.id] = worker
        self.write_manifest()
        return worker

    def _next_id(self, big: bool) -> str:
        with self._lock:
            prefix = "big" if big else "w"
            n = 0
            while f"{prefix}{n}" in self._workers:
                n += 1
            return f"{prefix}{n}"

    def attach(self, url: str, worker_id: str | None = None,
               big: bool = False, weight: float | None = None) -> Worker:
        """Adopt an externally managed worker by URL (multi-host lane).

        Idempotent on the URL: a restarted ``gol fleet`` passes the same
        ``--attach`` flags it was launched with AND recovers the same URLs
        from the manifest — re-adding would double-count the worker in
        membership, merged metrics, and round-robin sharding."""
        url = url.rstrip("/")
        with self._lock:
            for worker in self._workers.values():
                if worker.url == url:
                    return worker
        return self._add(Worker(
            id=worker_id or self._next_id(big),
            url=url,
            attached=True,
            big=big,
            weight=weight,
        ))

    def spawn(self, worker_id: str | None = None, big: bool = False,
              weight: float | None = None) -> Worker:
        """Spawn one local worker and wait until it serves.

        A boot that never becomes ready ROLLS BACK: the half-booted
        process is killed and the membership entry removed, so a failed
        autoscaler scale-up leaves no zombie for the health loop to
        respawn in a tight loop (the autoscaler's cooldown, not the
        supervisor, paces retries against a broken boot) and no phantom
        worker inflating the fleet's apparent capacity."""
        if weight is None:
            weight = self._spawn_weight
        worker = self._launch(Worker(id=worker_id or self._next_id(big),
                                     big=big, weight=weight))
        self._add(worker)
        try:
            self._await_ready(worker)
        except BaseException:
            if worker.proc is not None and worker.proc.poll() is None:
                worker.proc.kill()
                try:
                    worker.proc.wait(timeout=10)
                except (subprocess.TimeoutExpired, OSError):
                    pass
            with self._lock:
                self._workers.pop(worker.id, None)
            self.write_manifest()
            raise
        self.write_manifest()
        return worker

    def spawn_fleet(self, n_workers: int, big_lane: bool = False) -> None:
        """Bring the LOCAL worker count up to ``n_workers`` (+ the big lane),
        launching every missing process first and then waiting for all —
        boots overlap, so N workers cost one boot of wall clock."""
        launched = []
        with self._lock:
            locals_ = [w for w in self._workers.values()
                       if not w.attached and not w.big]
            have_big = any(w.big for w in self._workers.values())
        for _ in range(max(0, n_workers - len(locals_))):
            worker = self._launch(Worker(id=self._next_id(big=False),
                                         weight=self._spawn_weight))
            self._add(worker)
            launched.append(worker)
        if big_lane and not have_big:
            worker = self._launch(Worker(id=self._next_id(big=True), big=True,
                                         weight=self._spawn_weight))
            self._add(worker)
            launched.append(worker)
        for worker in launched:
            self._await_ready(worker)
        if launched:
            self.write_manifest()

    # -- local process management ------------------------------------------

    def _launch(self, worker: Worker) -> Worker:
        """Start the ``gol serve`` subprocess for one partition (does not
        wait for readiness — ``_await_ready`` does)."""
        import gol_tpu

        worker.journal_dir = worker.journal_dir or os.path.join(
            self.fleet_dir, worker.id
        )
        os.makedirs(worker.journal_dir, exist_ok=True)
        worker.log_path = worker.log_path or os.path.join(
            self.fleet_dir, f"{worker.id}.log"
        )
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(gol_tpu.__file__)
        ))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        prefix = list(self._spawn_prefix(worker)) if self._spawn_prefix else []
        cmd = [
            *prefix,
            sys.executable, "-m", "gol_tpu", "serve",
            "--port", "0",
            "--journal-dir", worker.journal_dir,
            *self.serve_args,
        ]
        # Log to a file, not a pipe: nothing to drain, boots can overlap,
        # and the worker's logs survive it for post-mortems.
        with open(worker.log_path, "ab") as logf:
            logf.write(b"\n")  # boot boundary
            logf.flush()
            # Parse only THIS boot's output for the URL banner: the log
            # appends across respawns, and the previous boot's banner names
            # a port nobody listens on anymore.
            worker.log_offset = logf.tell()
            worker.proc = subprocess.Popen(
                cmd, env=env, stdout=logf, stderr=subprocess.STDOUT
            )
        worker.pid = worker.proc.pid
        worker.url = None  # learned from the boot banner
        logger.info("fleet: launched worker %s (pid %d) on partition %s",
                    worker.id, worker.pid, worker.journal_dir)
        return worker

    def _await_ready(self, worker: Worker) -> None:
        """Wait for the worker's ``serving on <url>`` banner, then for
        ``/healthz``. Raises RuntimeError (with a log tail) on a dead boot."""
        deadline = time.perf_counter() + self.boot_timeout
        while time.perf_counter() < deadline:
            if worker.proc is not None and worker.proc.poll() is not None:
                raise RuntimeError(
                    f"worker {worker.id} died on boot "
                    f"(rc={worker.proc.returncode}):\n{self._log_tail(worker)}"
                )
            if worker.url is None:
                matches = _URL_RE.findall(
                    self._read_log(worker)[worker.log_offset:]
                )
                if matches:
                    worker.url = matches[0].decode("ascii").rstrip("/")
            if worker.url is not None and self._probe(worker.url) is not None:
                worker.healthy = True
                worker.failures = 0
                return
            time.sleep(0.1)
        raise RuntimeError(
            f"worker {worker.id} did not become healthy within "
            f"{self.boot_timeout:.0f}s:\n{self._log_tail(worker)}"
        )

    def _read_log(self, worker: Worker) -> bytes:
        try:
            with open(worker.log_path, "rb") as f:
                return f.read()
        except OSError:
            return b""

    def _log_tail(self, worker: Worker, n: int = 3000) -> str:
        return self._read_log(worker)[-n:].decode("utf-8", "replace")

    @staticmethod
    def _looks_like_worker(pid: int) -> bool:
        """Whether the pid is (still) a gol_tpu process. Guards manifest-
        recovered pids against reuse: after a host reboot the partition's
        recorded pid may belong to a stranger, and 'never two journal
        writers' only requires the ORIGINAL worker dead — killing whatever
        now holds the number would be a supervision bug, not supervision."""
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                return b"gol_tpu" in f.read()
        except OSError:
            return False  # gone, or no /proc: never kill blind

    @classmethod
    def _ensure_dead(cls, pid: int | None, timeout: float = 10.0) -> None:
        """SIGKILL a (cmdline-verified) worker pid and wait for it to
        vanish. Called before EVERY respawn of an adopted partition: two
        live processes appending one partition's journal would weld records
        and break the exactly-once replay contract — an unresponsive-but-
        alive worker must die before its successor boots."""
        if pid is None or not cls._looks_like_worker(pid):
            return
        try:
            os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            return
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return
            time.sleep(0.05)
        logger.error("fleet: pid %d survived SIGKILL for %.0fs", pid, timeout)

    def _respawn(self, worker: Worker) -> None:
        if worker.proc is not None:
            # Our own child: the Popen handle cannot suffer pid reuse
            # (the zombie holds the pid until we reap it here).
            if worker.proc.poll() is None:
                worker.proc.kill()
            try:
                worker.proc.wait(timeout=10)
            except (subprocess.TimeoutExpired, OSError):
                pass
        else:
            # Adopted from the manifest: cmdline-verified kill.
            self._ensure_dead(worker.pid)
        worker.restarts += 1
        worker.healthy = False
        worker.backpressure = False
        worker.failures = 0
        logger.warning(
            "fleet: respawning worker %s on partition %s (restart #%d); "
            "its journal replays every unfinished job",
            worker.id, worker.journal_dir, worker.restarts,
        )
        try:
            self._launch(worker)
            self._await_ready(worker)
        except (RuntimeError, OSError) as err:
            logger.error("fleet: respawn of %s failed (%s); retrying on the "
                         "next health tick", worker.id, err)
            return
        self.write_manifest()

    def _respawn_async(self, worker: Worker) -> None:
        """Respawn off the health thread: ``_respawn`` blocks in
        ``_await_ready`` for up to ``boot_timeout``, and a tick stalled
        there would leave every OTHER worker unprobed — a second
        concurrent death (or a drain/shed recovery) unhandled for
        minutes. The ``respawning`` flag keeps later ticks off the worker
        until its respawn resolves (one respawner per partition: never
        two writers on one journal)."""
        if worker.respawning:
            return
        worker.respawning = True

        def run():
            try:
                # A shutdown that began after this thread was scheduled
                # must not boot a fresh worker terminate() never sees.
                if not self._health_stop.is_set():
                    self._respawn(worker)
            finally:
                worker.respawning = False

        thread = threading.Thread(
            target=run, name=f"gol-fleet-respawn-{worker.id}", daemon=True
        )
        with self._lock:
            self._respawns[worker.id] = thread
        thread.start()

    # -- manifest ----------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.fleet_dir, MANIFEST)

    def write_manifest(self) -> None:
        # Serialized end to end: concurrent background respawns (and the
        # health thread's banner adoption) share one .tmp path — two
        # interleaved truncate/write/replace sequences would publish a
        # garbled manifest and break the router-restart recovery lane.
        # The threading lock covers THIS process; the blocking flock on
        # manifest.lock covers every other one (a second router replica,
        # an offline tool) — both writers complete, strictly in turn, so
        # the .tmp stage can never interleave across processes either.
        if self.replica and not self.supervise:
            return  # a follower READS membership; only the leader writes
        with self._manifest_lock:
            with self._lock:
                doc = {
                    "version": 1,
                    **({"config": self.manifest_config}
                       if self.manifest_config else {}),
                    "partitions": [w.manifest_record()
                                   for w in self._workers.values()],
                }
            lock_fd = lease.acquire(
                os.path.join(self.fleet_dir, MANIFEST_LOCK), blocking=True
            )
            try:
                tmp = self.manifest_path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(doc, f, indent=1)
                    f.write("\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.manifest_path)
            finally:
                if lock_fd is not None:
                    lease.release(lock_fd)

    def load(self) -> int:
        """Reattach the fleet a previous router left behind (the router-
        restart lane). For every manifest partition: a worker answering at
        its recorded URL is adopted live (its jobs were never in danger);
        a dead LOCAL partition is respawned there and replays its journal;
        a dead attached worker is kept unhealthy and probed by the health
        loop until it returns. Returns the number of partitions recovered."""
        if not os.path.exists(self.manifest_path):
            return 0
        with open(self.manifest_path, "r", encoding="utf-8") as f:
            doc = json.load(f)
        if isinstance(doc.get("config"), dict):
            self.manifest_config = doc["config"]
        n = 0
        for rec in doc.get("partitions", []):
            weight = rec.get("weight")
            worker = Worker(
                id=rec["id"],
                url=rec.get("url"),
                journal_dir=(os.path.join(self.fleet_dir, rec["journal"])
                             if rec.get("journal") else None),
                big=bool(rec.get("big")),
                attached=bool(rec.get("attached")),
                pid=rec.get("pid"),
                weight=float(weight) if weight else None,
            )
            alive = worker.url is not None and self._probe(worker.url) is not None
            if alive:
                logger.info("fleet: reattached live worker %s at %s",
                            worker.id, worker.url)
            elif worker.attached or self.replica:
                # A replica never respawns at boot — the partition is the
                # LEADER's to revive; adopt it unhealthy and keep probing
                # (exactly the dead-attached-worker posture). If this
                # replica later wins the lease, its supervised ticks take
                # over the respawn.
                worker.healthy = False
                logger.warning("fleet: %s worker %s unreachable at %s; "
                               "will keep probing",
                               "attached" if worker.attached else "adopted",
                               worker.id, worker.url)
            else:
                self._add(worker)
                self._respawn(worker)
                n += 1
                continue
            self._add(worker)
            n += 1
        return n

    def reconcile_from_manifest(self) -> int:
        """Follower-side membership sync: adopt what the leader's manifest
        says, without writing anything back. New partitions (a scale-up)
        appear, a respawned worker's fresh URL replaces the dead one, and
        partitions the leader retired (a scale-down) drop out — so every
        replica routes over the same membership the leader supervises,
        one tick behind at most. Returns the number of changes applied.

        Never touches a worker whose subprocess THIS fleet owns
        (``proc`` set): reconciliation is for adopted views only, and a
        follower never spawns."""
        try:
            with open(self.manifest_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0  # no manifest yet; writes are atomic, so never torn
        if isinstance(doc.get("config"), dict):
            self.manifest_config = doc["config"]
        recs = {rec["id"]: rec for rec in doc.get("partitions", [])
                if rec.get("id")}
        changed = 0
        with self._lock:
            for wid, rec in recs.items():
                url = rec.get("url")
                url = url.rstrip("/") if url else None
                worker = self._workers.get(wid)
                if worker is None:
                    weight = rec.get("weight")
                    self._workers[wid] = Worker(
                        id=wid,
                        url=url,
                        journal_dir=(
                            os.path.join(self.fleet_dir, rec["journal"])
                            if rec.get("journal") else None),
                        big=bool(rec.get("big")),
                        attached=bool(rec.get("attached")),
                        pid=rec.get("pid"),
                        weight=float(weight) if weight else None,
                        healthy=False,  # this tick's probe promotes it
                    )
                    changed += 1
                elif worker.proc is None:
                    if url is not None and worker.url != url:
                        # The leader respawned it: route to the new
                        # process once the probe (same tick) confirms it.
                        worker.url = url
                        worker.pid = rec.get("pid")
                        worker.failures = 0
                        worker.healthy = False
                        changed += 1
                    elif worker.pid != rec.get("pid"):
                        worker.pid = rec.get("pid")
                        changed += 1
            for wid in [w for w in self._workers if w not in recs]:
                worker = self._workers[wid]
                if (worker.proc is None and not worker.respawning
                        and not worker.retiring):
                    del self._workers[wid]  # the leader retired it
                    changed += 1
        if changed:
            logger.info("fleet: reconciled %d membership change(s) from "
                        "the manifest", changed)
        return changed

    # -- leader election ----------------------------------------------------

    def enable_leader_election(self, label: str = "") -> bool:
        """Arm the SIGKILL-safe leader lease on ``<fleet_dir>/leader.lock``
        and contest it once now; every later health tick re-contests.
        Returns whether this process leads right now. While not leading,
        ``supervise`` is False: no respawns, no manifest writes, and the
        autoscaler's tick no-ops — single-writer control with an
        active-active data plane."""
        if self._lease is None:
            self._lease = lease.FlockLease(
                os.path.join(self.fleet_dir, LEADER_LOCK), label=label
            )
        self.supervise = self._lease.try_acquire()
        return self.supervise

    @property
    def leading(self) -> bool:
        """Whether this process runs the single-writer ticks (True for a
        lease-holding or lease-less fleet — a plain one-router fleet
        supervises unconditionally, exactly as before elections existed)."""
        return self.supervise

    def _poll_leadership(self) -> None:
        if self._lease is None or self.supervise:
            return  # lease-less fleet, or already the holder (for life)
        if self._lease.try_acquire():
            self.supervise = True
            logger.warning(
                "fleet: leader lease acquired — this router now owns the "
                "single-writer ticks (respawn supervision, scale "
                "decisions); adopting membership from the manifest"
            )
            self.reconcile_from_manifest()

    def release_leadership(self) -> None:
        """Voluntary hand-off at shutdown so a survivor wins the lease
        without waiting for the kernel to reap this process."""
        if self._lease is not None:
            self._lease.release()
            if self.replica:
                self.supervise = False

    # -- health ------------------------------------------------------------

    def note_shed(self, worker_id: str) -> None:
        """The router observed this worker 429 a submit: stop routing new
        work there until the health loop sees its SLO recover."""
        worker = self.worker(worker_id)
        if worker is not None and not worker.backpressure:
            worker.backpressure = True
            logger.warning("fleet: worker %s is shedding; draining it of "
                           "new work", worker_id)

    def check_worker(self, worker: Worker) -> None:
        """One health tick for one worker: liveness via /healthz, burn via
        /slo, respawn for dead local processes."""
        if worker.respawning:
            return  # a background respawn owns this worker right now
        if worker.retiring:
            # The autoscaler's drain->retire thread owns this worker: no
            # respawn (a retiring worker dying mid-drain is the retire
            # thread's failure to handle), no backpressure churn.
            return
        if worker.proc is not None and worker.proc.poll() is not None:
            logger.warning("fleet: worker %s (pid %s) exited rc=%s",
                           worker.id, worker.pid, worker.proc.returncode)
            if self.supervise:
                self._respawn_async(worker)
            return
        if worker.url is None:
            if worker.proc is None:
                # Adopted from a manifest written mid-boot (the previous
                # supervisor died between launch and banner): there is no
                # log offset to scan — only the leader may relaunch the
                # partition (its _respawn kills any half-booted orphan
                # first; never two journal writers).
                if self.supervise and not worker.attached:
                    self._respawn_async(worker)
                return
            # A boot that outlived _await_ready's patience (e.g.
            # --warm-plans compiling on a loaded host) but whose process is
            # alive: keep looking for its banner every tick — otherwise the
            # worker serves forever on a port the router never learns and
            # its partition is stranded.
            if worker.proc.poll() is not None:
                return
            matches = _URL_RE.findall(
                self._read_log(worker)[worker.log_offset:]
            )
            if not matches:
                return
            worker.url = matches[0].decode("ascii").rstrip("/")
            self.write_manifest()
        hz = self._probe(worker.url)
        if hz is None:
            worker.failures += 1
            if worker.failures >= self.fail_after:
                if worker.healthy:
                    logger.warning(
                        "fleet: worker %s failed %d consecutive liveness "
                        "probes; routing around it", worker.id, worker.failures,
                    )
                worker.healthy = False
                if not worker.attached and self.supervise:
                    self._respawn_async(worker)
            return
        worker.failures = 0
        worker.healthy = True
        if worker.weight is None and isinstance(hz, dict):
            # Affinity (fleet/affinity.py): a worker with no operator-
            # pinned weight may advertise its measured capacity on
            # /healthz (the tuned marginal kernel rate of its own plan
            # cache). Adopted, not persisted — it re-advertises per boot.
            advertised = hz.get("weight")
            if isinstance(advertised, (int, float)) and advertised > 0:
                worker.advertised_weight = float(advertised)
        slo = self._probe(worker.url, "/slo")
        worker.slo = slo  # the autoscaler's burn signal: one probe per tick
        if slo is not None:
            burning = (
                slo.get("status") == "critical"
                or bool((slo.get("shed") or {}).get("active"))
            )
            if burning and not worker.backpressure:
                logger.warning("fleet: worker %s SLO burn is critical; "
                               "draining it of new work", worker.id)
            if worker.backpressure and not burning:
                logger.info("fleet: worker %s recovered; routing to it again",
                            worker.id)
            worker.backpressure = burning

    def health_tick(self) -> None:
        # Leadership first: a survivor must claim the dead leader's lease
        # on THIS tick (the takeover latency the zero-SPOF story promises
        # is one health interval), then probe with its new authority.
        self._poll_leadership()
        if not self.supervise:
            # Followers track the leader's membership instead of writing
            # their own: the manifest is the single source of truth.
            self.reconcile_from_manifest()
        for worker in self.workers():
            self.check_worker(worker)
        for hook in list(self._tick_hooks):
            hook()

    def add_tick_hook(self, hook) -> None:
        """Ride the health loop: ``hook()`` runs after every tick's worker
        probes (the autoscaler's cadence), under the loop's exception
        guard — a raising hook costs one tick, never the loop."""
        self._tick_hooks.append(hook)

    def start_health(self, interval: float = 1.0) -> None:
        if self._health_thread is not None:
            return
        self._health_stop.clear()

        def loop():
            while not self._health_stop.wait(interval):
                try:
                    self.health_tick()
                except Exception:  # noqa: BLE001 - supervision must survive
                    logger.exception("fleet: health tick failed")

        self._health_thread = threading.Thread(
            target=loop, name="gol-fleet-health", daemon=True
        )
        self._health_thread.start()

    def stop_health(self) -> None:
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=self.boot_timeout + 15)
            self._health_thread = None
        # In-flight background respawns must resolve before terminate():
        # a worker launched after the kill sweep would outlive the fleet.
        with self._lock:
            respawns = list(self._respawns.values())
            self._respawns.clear()
        for thread in respawns:
            thread.join(timeout=self.boot_timeout + 15)

    # -- scale-down: drain -> retire ---------------------------------------

    def retire(self, worker_id: str, drain_timeout: float = 600.0) -> bool:
        """Retire one LOCAL worker: cascade drain -> stop -> remove.

        The scale-down actuator (fleet/autoscale.py). Ordering is the
        whole contract:

        1. mark ``retiring`` — the router stops routing NEW work there
           (and the health loop stops supervising it) immediately;
        2. ``POST /drain`` — the worker finishes every accepted job and
           journals its done records; a drain that fails or times out
           ABORTS the retire (losing capacity must never risk losing
           jobs). A drain may have REACHED the worker before failing
           here, and a draining scheduler 429s new work forever — so the
           abort path restores the worker via the supervised respawn on
           its own partition (journal replay finishes anything the
           partial drain left; exactly-once holds as for any crash)
           rather than pretending the old process still serves;
        3. stop the process (SIGTERM first — it already drained, so this
           is quick — SIGKILL past ``timeout``) and remove the worker
           from membership + manifest.

        The journal partition STAYS on disk, fully drained: every job it
        ever accepted has a done record, and the next scale-up reuses the
        lowest free worker id — landing on this same partition, whose
        replay finds only terminal records. Retired capacity is never an
        orphaned journal. Attached workers are not ours to retire."""
        worker = self.worker(worker_id)
        if worker is None or worker.attached or worker.big:
            return False
        if worker.retiring or worker.respawning:
            return False
        worker.retiring = True
        drained = False
        if worker.url is not None:
            try:
                status, payload = self._http(
                    "POST", worker.url + "/drain", body={},
                    timeout=drain_timeout,
                )
                drained = status == 200 and bool(
                    isinstance(payload, dict) and payload.get("drained")
                )
            except (OSError, ValueError) as err:
                logger.error("fleet: drain of retiring worker %s failed "
                             "(%s)", worker_id, err)
        if not drained:
            # The drain may have landed (its scheduler then refuses new
            # work forever — there is no un-drain), so "keep serving" is
            # not an option: respawn on the same partition. The replay
            # finishes whatever the partial drain left, and the fresh
            # process admits work again.
            logger.error("fleet: worker %s did not drain; ABORTING its "
                         "retire and respawning it on its partition "
                         "(journal replays; a possibly-draining process "
                         "cannot be returned to service)", worker_id)
            try:
                self._respawn(worker)
            finally:
                worker.retiring = False
            return False
        if worker.proc is not None:
            if worker.proc.poll() is None:
                worker.proc.terminate()
                try:
                    worker.proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    worker.proc.kill()
                    try:
                        worker.proc.wait(timeout=10)
                    except (subprocess.TimeoutExpired, OSError):
                        pass
        elif worker.pid is not None and self._looks_like_worker(worker.pid):
            try:
                os.kill(worker.pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            self._ensure_dead(worker.pid)
        with self._lock:
            self._workers.pop(worker_id, None)
        self.write_manifest()
        logger.warning("fleet: retired worker %s (partition %s drained; "
                       "its journal holds only terminal records)",
                       worker_id, worker.journal_dir)
        return True

    # -- fleet-wide drain / shutdown ---------------------------------------

    def drain_all(self, timeout: float = 600.0) -> dict:
        """Cascade POST /drain to every worker concurrently; returns
        {worker_id: {"drained": bool, ...}} when all are quiescent (or
        unreachable)."""
        results: dict[str, dict] = {}
        lock = threading.Lock()

        def drain_one(worker: Worker):
            out = {"drained": False}
            if worker.url is not None:
                try:
                    status, payload = self._http(
                        "POST", worker.url + "/drain", body={},
                        timeout=timeout,
                    )
                    if status == 200 and isinstance(payload, dict):
                        out = payload
                    else:
                        out = {"drained": False, "status": status}
                except (OSError, ValueError) as err:
                    out = {"drained": False, "error": str(err)}
            with lock:
                results[worker.id] = out

        threads = [
            threading.Thread(target=drain_one, args=(w,), daemon=True)
            for w in self.workers()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 10)
        return results

    def terminate(self, timeout: float = 30.0) -> None:
        """SIGTERM every LOCAL worker (their own graceful-drain path) and
        wait; escalate to SIGKILL past the timeout. Attached workers are
        not ours to stop."""
        victims = [w for w in self.workers() if not w.attached]
        for worker in victims:
            if worker.proc is not None:
                if worker.proc.poll() is None:
                    worker.proc.terminate()
            elif worker.pid is not None and self._looks_like_worker(worker.pid):
                try:
                    os.kill(worker.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.perf_counter() + timeout
        for worker in victims:
            if worker.proc is not None:
                try:
                    worker.proc.wait(
                        timeout=max(0.1, deadline - time.perf_counter())
                    )
                except subprocess.TimeoutExpired:
                    logger.error("fleet: worker %s ignored SIGTERM; killing",
                                 worker.id)
                    worker.proc.kill()
                    worker.proc.wait(timeout=10)
            elif worker.pid is not None:
                while time.perf_counter() < deadline:
                    try:
                        os.kill(worker.pid, 0)
                    except ProcessLookupError:
                        break
                    time.sleep(0.1)
                else:
                    self._ensure_dead(worker.pid)

    def stats(self) -> dict:
        workers = self.workers()
        return {
            "workers": len(workers),
            "healthy": sum(w.healthy for w in workers),
            "backpressured": sum(w.backpressure for w in workers),
            "retiring": sum(w.retiring for w in workers),
            "big_lane": any(w.big for w in workers),
            "restarts": sum(w.restarts for w in workers),
        }
