"""The fleet router: one HTTP front-end over N serving workers.

Exposes the single-server job API **unchanged** — clients built against
``gol serve`` (``gol submit``, ``gol top``, curl loops) talk to a router
without modification — and adds the fleet surfaces:

- ``POST /jobs``      — placed by padding bucket (``fleet/placement``:
  rendezvous-hashed, so a bucket's compiled programs and resident rings
  stay hot on ONE worker), forwarded verbatim. With ``--cache-route`` the
  HRW key is the job's result FINGERPRINT instead (gol_tpu/cache), so
  repeats land on the worker whose cache tiers hold the answer. A worker that 429s or is
  unreachable spills to the next-ranked worker before the client sees an
  error; oversized boards (padded edge > ``big_edge``) go to the dedicated
  big-lane worker when the fleet has one. The 202 payload gains a
  ``worker`` field. Packed wire bodies (``Content-Type:
  application/x-gol-packed``, io/wire.py) are placed from the frame
  header + meta alone — no payload read, no unpack — and the raw buffer
  is forwarded under the same content type: the router's cost per packed
  submit is independent of board size.
- ``GET /result/<id>`` with ``Accept: application/x-gol-packed`` relays
  the worker's packed frame bytes verbatim (text/JSON results and every
  error stay parsed-JSON, byte-identical to pre-wire routing).
- ``GET /jobs/<id>``, ``/jobs/<id>/timeline``, ``GET /result/<id>``,
  ``DELETE /jobs/<id>`` — forwarded to the owning worker (an in-memory
  id->worker map, rebuilt lazily by broadcast after a router restart: the
  workers' journals are the durable truth, the router keeps none).
- ``GET /metrics``    — fleet-merged: counters and gauges sum across
  workers, histogram quantiles take the worst worker (a conservative
  upper bound — true fleet quantiles would need raw samples);
  ``?format=json`` carries the merged view top-level (same schema as one
  worker, so dashboards work unchanged) plus per-worker snapshots under
  ``workers`` and membership under ``fleet``.
- ``GET /slo``        — overall status is the worst worker's; objectives
  are every worker's, names prefixed ``<worker>:``.
- ``GET /fleet``      — membership: per-worker id/url/pid/health (what
  ``gol submit --shard-across`` and ``gol top`` read).
- ``POST /drain``     — fleet-wide cascade: admission stops here first,
  then every worker drains concurrently; responds when all are quiescent.
- ``GET /healthz``    — router liveness + fleet stats.

The router owns no device and no journal: exactly-once is the sum of the
partitions' journals (see ``fleet/workers``), which is why killing the
router loses nothing — restart, ``Fleet.load()``, keep serving.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs

import time

from gol_tpu.fleet import affinity, client, placement
from gol_tpu.fleet.breaker import (
    BreakerConfig, CircuitBreaker, STATE_VALUE,
)
from gol_tpu.fleet.workers import Fleet, Worker
from gol_tpu.io import wire
from gol_tpu.obs import propagate, registry as obs_registry, trace as obs_trace
from gol_tpu.obs.registry import Registry, _fmt
from gol_tpu.resilience import retry as _retry_mod

logger = logging.getLogger(__name__)

# Body caps ride io/wire.py (wire.max_body_bytes — numpy-only, jax-free,
# importable here), the same constants the workers enforce: the router
# must never be tighter than a worker, and the packed cap bounds the same
# board-area universe as the text cap rather than the same byte count.

# SLO status ordering for the fleet-wide worst-of merge.
_SLO_RANK = {"ok": 0, "warning": 1, "critical": 2}


# Spill safety: only failures that guarantee the worker never saw the
# request may move a submit to another worker (shared with `gol submit`'s
# POST auto-retry — both re-sends have the same double-run hazard).
_delivery_impossible = _retry_mod.delivery_impossible


# -- pure merge helpers (unit-tested without HTTP) --------------------------

class MonotonicCounters:
    """Per-worker high-water offsets so fleet-merged cumulative series —
    counters AND histogram ``count``/``sum`` — never go backwards across
    worker respawns.

    A respawned worker restarts its cumulative series at zero, so summing
    raw per-worker values makes the fleet-merged "counter" DECREASE
    exactly during the restart windows operators are watching — Prometheus
    ``rate()``/``increase()`` then report spurious resets and spikes. The
    router banks, per (worker, series), the total a previous incarnation
    reached (a value going backwards — or a lazily-created key vanishing —
    is the respawn signal) and adds it back before merging, keeping the
    merged series monotonic — including through the outage window itself,
    when the dead worker answers no scrape at all and its last-known
    totals stand in. Gauges and histogram quantiles are instantaneous and
    pass through untouched: only LIVE workers contribute those."""

    def __init__(self):
        self._lock = threading.Lock()
        # (worker id, series key) -> float; a series key is ("c", name)
        # for a counter or ("h", name, "count"|"sum") for a histogram.
        self._last: dict[tuple, float] = {}
        self._base: dict[tuple, float] = {}
        self._incarnation: dict[str, int] = {}  # worker id -> restarts seen

    @staticmethod
    def _series(snap: dict) -> dict[tuple, float]:
        series: dict[tuple, float] = {}
        for name, value in (snap.get("counters") or {}).items():
            series[("c", name)] = float(value)
        for name, summary in (snap.get("histograms") or {}).items():
            for field in ("count", "sum"):
                series[("h", name, field)] = float(summary.get(field) or 0)
        return series

    def _floor(self, wid: str, series: dict[tuple, float]) -> dict:
        """Bank resets and return every known-or-present series floored.
        A known key absent from this scrape reads as zero — registries
        create series lazily, so a fresh incarnation that has not counted
        an event yet omits the key entirely: the same reset signal."""
        known = {skey for (w, skey) in self._last if w == wid}
        floored = {}
        for skey in known | set(series):
            value = series.get(skey, 0.0)
            key = (wid, skey)
            last = self._last.get(key, 0.0)
            if value < last:  # the worker respawned: bank its old run
                self._base[key] = self._base.get(key, 0.0) + last
            self._last[key] = value
            floored[skey] = self._base.get(key, 0.0) + value
        return floored

    @staticmethod
    def _rebuild(snap: dict, floored: dict[tuple, float]) -> dict:
        counters: dict[str, float] = {}
        hists = {name: dict(summary)
                 for name, summary in (snap.get("histograms") or {}).items()}
        for skey, value in floored.items():
            if skey[0] == "c":
                counters[skey[1]] = value
            else:
                hists.setdefault(skey[1], {})[skey[2]] = value
        return {**snap, "counters": counters, "histograms": hists}

    def adjust(self, snapshots: dict[str, dict],
               incarnations: dict[str, int] | None = None) -> dict[str, dict]:
        out: dict[str, dict] = {}
        with self._lock:
            # Bank on KNOWN respawns first (the fleet's restart
            # generation): a new incarnation that already overtook the
            # old total by the next scrape shows no value regression,
            # and inferring resets from value order alone would silently
            # drop the old run from the merge. Attached workers (the
            # fleet never respawns them) still rely on the value-
            # regression fallback below.
            for wid, gen in (incarnations or {}).items():
                seen = self._incarnation.get(wid)
                self._incarnation[wid] = gen
                if seen is None or gen == seen:
                    continue
                for (w, skey), last in list(self._last.items()):
                    if w == wid and last > 0:
                        self._base[(w, skey)] = (
                            self._base.get((w, skey), 0.0) + last
                        )
                        self._last[(w, skey)] = 0.0
            for wid, snap in snapshots.items():
                out[wid] = self._rebuild(snap, self._floor(wid,
                                                           self._series(snap)))
            # A worker missing from this scrape ENTIRELY (dead, mid-
            # respawn, network blip) still contributes its last-known
            # totals: the events it counted happened, and dropping them
            # dips the merged series for the whole outage window. No
            # banking here — a worker back from a blip with its series
            # intact just continues them.
            known_wids = {w for (w, _) in self._last}
            for wid in known_wids - set(snapshots):
                floored = {
                    skey: self._base.get((w, skey), 0.0) + last
                    for (w, skey), last in self._last.items() if w == wid
                }
                out[wid] = self._rebuild({}, floored)
        return out

    def state(self) -> dict:
        """JSON-round-trippable floors (``fleet/replicate.FloorsStore``
        persists it after every fresh scrape): the banked bases, the
        last-seen values — without which a dead worker's stand-in totals
        and the regression fallback vanish on router restart — and the
        incarnation generations."""
        with self._lock:
            return {
                "version": 1,
                "last": [[wid, list(skey), value]
                         for (wid, skey), value in self._last.items()],
                "base": [[wid, list(skey), value]
                         for (wid, skey), value in self._base.items()],
                "incarnations": dict(self._incarnation),
            }

    def seed(self, state: dict | None) -> None:
        """Adopt floors a previous router incarnation persisted. Only a
        fresh instance seeds (floors already in motion outrank any file).
        Live workers then simply continue their series (value >= seeded
        last: no bank); a worker that restarted while no router watched
        shows value < seeded last and banks the lost run — the merged
        series stays monotonic through the ROUTER's own outage window."""
        if not state:
            return
        with self._lock:
            if self._last or self._base:
                return
            for wid, skey, value in state.get("last") or []:
                self._last[(wid, tuple(skey))] = float(value)
            for wid, skey, value in state.get("base") or []:
                self._base[(wid, tuple(skey))] = float(value)
            for wid, gen in (state.get("incarnations") or {}).items():
                self._incarnation[wid] = int(gen)


def merge_metrics(snapshots: dict[str, dict]) -> dict:
    """Merge per-worker /metrics JSON snapshots into one fleet view.

    Counters and extensive gauges SUM (fleet queue depth is the sum of
    worker queues; fleet boards/sec is the sum of worker rates). INTENSIVE
    gauges — ratios and occupancies, which live in [0, 1] per worker — take
    the MAX (summing four workers' 0.9 dispatch-gap ratios into 3.6 would
    be nonsense; the worst worker is the figure an operator acts on).
    Disk-pressure gauges are intensive too, with their own directions:
    ``disk_free_bytes`` merges by MIN (the binding constraint — the fleet
    is as full as its fullest partition) and ``disk_pressure_level`` by
    MAX (the deepest degradation any partition is in). Histogram
    ``count``/``sum`` sum; quantiles take the MAX across workers — the
    honest aggregate without raw samples is "no worker is worse than
    this", which is the bound an operator alerts on anyway."""
    counters: dict[str, float] = {}
    gauges: dict[str, float] = {}
    hists: dict[str, dict] = {}
    for snap in snapshots.values():
        for name, value in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + value
        for name, value in (snap.get("gauges") or {}).items():
            prev = gauges.get(name)
            if name == "disk_free_bytes":
                gauges[name] = value if prev is None else min(prev, value)
            elif name == "disk_pressure_level" or any(
                hint in name for hint in ("ratio", "occupancy")
            ):
                gauges[name] = value if prev is None else max(prev, value)
            else:
                gauges[name] = (prev or 0) + value
        for name, summary in (snap.get("histograms") or {}).items():
            out = hists.setdefault(name, {"count": 0, "sum": 0.0})
            out["count"] += summary.get("count") or 0
            out["sum"] += summary.get("sum") or 0.0
            for key, value in summary.items():
                if key.startswith("p") and value is not None:
                    prev = out.get(key)
                    out[key] = value if prev is None else max(prev, value)
    return {"counters": counters, "gauges": gauges, "histograms": hists}


def merged_prometheus(merged: dict, fleet_gauges: dict,
                      fleet_counters: dict | None = None) -> str:
    """Prometheus text for the merged snapshot, in the worker registry's
    exposition shape (same ``gol_serve_`` series names, sum semantics) plus
    ``gol_fleet_*`` membership gauges and router counters."""
    lines: list[str] = []
    for name, value in sorted(merged.get("counters", {}).items()):
        lines.append(f"# TYPE gol_serve_{name} counter")
        lines.append(f"gol_serve_{name} {_fmt(value)}")
    for name, value in sorted(merged.get("gauges", {}).items()):
        lines.append(f"# TYPE gol_serve_{name} gauge")
        lines.append(f"gol_serve_{name} {_fmt(value)}")
    for name, summary in sorted(merged.get("histograms", {}).items()):
        lines.append(f"# TYPE gol_serve_{name} summary")
        for q in (0.5, 0.95, 0.99):
            v = summary.get(f"p{int(q * 100)}")
            if v is not None:
                lines.append(f'gol_serve_{name}{{quantile="{q}"}} {_fmt(v)}')
        lines.append(f"gol_serve_{name}_sum {_fmt(summary['sum'])}")
        lines.append(f"gol_serve_{name}_count {_fmt(summary['count'])}")
    for name, value in sorted((fleet_counters or {}).items()):
        lines.append(f"# TYPE gol_fleet_{name} counter")
        lines.append(f"gol_fleet_{name} {_fmt(value)}")
    for name, value in sorted(fleet_gauges.items()):
        lines.append(f"# TYPE gol_fleet_{name} gauge")
        lines.append(f"gol_fleet_{name} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def merge_slo(statuses: dict[str, dict | None]) -> dict:
    """Merge per-worker /slo payloads: worst status wins, every objective
    is listed under ``<worker>:<name>``, shedding is any-worker. An
    unreachable worker degrades the headline — at least ``warning``, and
    ``critical`` when NO worker answered: a fleet serving nothing must
    never show a green status to the surface that exists to catch it."""
    overall = "ok"
    objectives = []
    windows = None
    shed_enabled = shed_active = False
    unreachable = []
    for worker_id, status in sorted(statuses.items()):
        if not status:
            unreachable.append(worker_id)
            continue
        if _SLO_RANK.get(status.get("status"), 0) > _SLO_RANK[overall]:
            overall = status["status"]
        if windows is None:
            windows = status.get("windows_s")
        shed = status.get("shed") or {}
        shed_enabled = shed_enabled or bool(shed.get("enabled"))
        shed_active = shed_active or bool(shed.get("active"))
        for obj in status.get("objectives") or []:
            objectives.append({**obj, "name": f"{worker_id}:{obj['name']}"})
    if unreachable:
        floor = "critical" if len(unreachable) == len(statuses) else "warning"
        if _SLO_RANK[floor] > _SLO_RANK[overall]:
            overall = floor
    return {
        "status": overall,
        "windows_s": windows or [],
        "shed": {"enabled": shed_enabled, "active": shed_active},
        "objectives": objectives,
        "unreachable": unreachable,
        "workers": {
            wid: (status if status else {"status": "unreachable"})
            for wid, status in statuses.items()
        },
    }


class RouterServer:
    """The routing process: membership + placement + HTTP front end."""

    def __init__(
        self,
        fleet: Fleet,
        host: str = "127.0.0.1",
        port: int = 0,
        big_edge: int = 1024,
        http=client.http_json,
        http_exchange=client.http_exchange,
        submit_timeout: float = 120.0,
        cache_route: bool = False,
        affinity_route: bool = False,
        breakers: bool = False,
        breaker_config: BreakerConfig | None = None,
        breaker_history=None,
        chaos=None,
        router_id: str = "r0",
        state_dir: str | None = None,
    ):
        if big_edge < placement.PLACEMENT_QUANTUM:
            raise ValueError(
                f"big_edge must be >= {placement.PLACEMENT_QUANTUM}, "
                f"got {big_edge}"
            )
        self.fleet = fleet
        self.big_edge = big_edge
        self.http = http
        # The byte-level exchange (packed wire result relay): separate
        # injectable so tests stubbing the JSON client keep working
        # unchanged — only Accept-packed result fetches ride this one.
        self.http_exchange = http_exchange
        self.submit_timeout = submit_timeout
        # The fleet cache tier (gol_tpu/cache): rank workers by the job's
        # RESULT FINGERPRINT instead of its padding bucket, so every repeat
        # of a board lands on the one worker whose cache tiers hold its
        # answer — the cache shard for a fingerprint lives on its HRW
        # owner, deterministically across router restarts, and hot patterns
        # spread across the fleet by fingerprint instead of hammering one
        # bucket owner. The trade (documented in README): one padding
        # bucket's boards may now compile on several workers — a one-time
        # cost per (bucket, worker), bought back by every repeat that
        # skips its engine run. ``no_cache`` submissions keep bucket
        # routing; spillover/health/big-lane ordering is identical.
        self.cache_route = cache_route
        # Affinity-aware placement (fleet/affinity.py): rank by weighted
        # HRW over per-worker capacity weights instead of the raw hash.
        # Default OFF, and OFF is byte-identical plain HRW (test-pinned);
        # ON with no weights configured delegates back to plain HRW, so
        # the flag is safe before any weight exists.
        self.affinity_route = affinity_route
        # The autoscaler (fleet/autoscale.py), attached by the CLI after
        # construction (it needs this router's merged scrape): surfaces
        # in /metrics, /fleet, and `gol top` when present.
        self.autoscaler = None
        # Per-worker circuit breakers (fleet/breaker.py). Default OFF and
        # byte-identical to the pre-breaker router (test-pinned: ranking,
        # bodies, call shapes); `gol fleet` turns them on unless
        # --no-breakers. Breakers re-RANK (open workers last), never
        # remove: HRW affinity survives recovery untouched.
        self.breakers_enabled = bool(breakers)
        self._breaker_config = breaker_config or BreakerConfig()
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        # Durable breaker transition ring (obs/history.HistoryWriter or
        # None): every open/half-open/close lands beside the autoscaler's
        # decisions, so "when did we rank w1 out and back in" is
        # answerable after the fact.
        self._breaker_history = breaker_history
        # The chaos mount (gol_tpu/chaos.ProxyPool or None): when present,
        # every DATA-path forward (submits, per-job GET/DELETE, result
        # relays) resolves its target through ``chaos.url_for`` — one
        # faulty hop per worker. Health probes and metrics scrapes stay
        # direct: chaos tests the data plane's defenses, not the
        # supervisor's eyesight.
        self.chaos = chaos
        # Replica identity: which router THIS process is ("r0" is the
        # `gol fleet` primary; `gol router` replicas pick their own).
        # Stamped on /healthz and /fleet so clients and smokes can tell
        # which replica answered.
        self.router_id = router_id
        self.registry = Registry(prefix="gol_fleet")
        self._counter_floors = MonotonicCounters()
        # Durable coordination state (fleet/replicate.py): with a state
        # dir mounted, the counter floors persist after every fresh
        # scrape and re-seed on boot (merged across ALL replicas' dirs),
        # and breakers some incarnation left open re-arm warm. Without
        # one, behavior is byte-identical to the in-memory-only router.
        self._floors_store = None
        self._state_dir = state_dir
        if state_dir is not None:
            from gol_tpu.fleet import replicate as _replicate

            self._floors_store = _replicate.FloorsStore(state_dir)
            self._counter_floors.seed(
                _replicate.load_merged_floors(fleet.fleet_dir)
            )
            if self.breakers_enabled:
                # Re-arm, don't re-learn: every worker some replica's
                # durable ring last recorded open/half-open starts OPEN
                # here, with a fresh cooldown — first contact is one
                # half-open probe, not fail_threshold real jobs.
                for wid in sorted(_replicate.warm_breaker_states(
                        fleet.fleet_dir)):
                    br = self.breaker(wid)
                    if br is not None:
                        br.reopen()
                        logger.warning(
                            "router %s: breaker for %s restored OPEN from "
                            "the durable ring", self.router_id, wid)
        # Single-flight scrape state (all guarded by the condition).
        self._scrape_done = threading.Condition()
        self._scrape_busy = False
        self._scrape_epoch = 0
        self._scrape_cache: tuple[dict, dict] | None = None
        self._scrape_cache_epoch = 0  # epoch that produced the cache
        # job id -> worker id, memory only (the partitions are the truth;
        # a miss rebuilds by broadcast). Bounded: entries evict when their
        # result/cancellation is fetched, with a FIFO cap as the backstop
        # for jobs whose results nobody ever collects — a router fronting
        # millions of jobs must not grow a dict forever.
        self._jobs: dict[str, str] = {}
        self._jobs_cap = 65536
        self._jobs_lock = threading.Lock()
        self._draining = False
        # The sharded single-job lane (gol_tpu/shard): job id -> entry
        # {state, workers, result, error, coordinator}. Coordinators run
        # on daemon threads in THIS process, leader-only (a follower
        # answers 409 and the client resubmits at the leader after
        # failover — the flock lease guarantees one driver per job).
        self._shard_jobs: dict[str, dict] = {}
        self._shard_lock = threading.Lock()
        # Durable metrics history (obs/history.py), mounted by
        # start_history: one tick thread appending the FLOORED merged
        # snapshot — the MonotonicCounters pass above is exactly what
        # makes the durable record monotonic through worker respawns.
        self._history = None
        self._history_stop = threading.Event()
        self._history_thread: threading.Thread | None = None
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def _advertise(self) -> None:
        if self._state_dir is not None:
            from gol_tpu.fleet import replicate as _replicate

            _replicate.advertise(self.fleet.fleet_dir, self.router_id,
                                 self.url)

    def start(self) -> None:
        self._advertise()
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="gol-fleet-http", daemon=True
        )
        self._thread.start()
        logger.info("fleet router listening on %s", self.url)

    def serve_forever(self) -> None:
        self._advertise()
        logger.info("fleet router listening on %s", self.url)
        self.httpd.serve_forever()

    def drain(self, timeout: float = 600.0) -> dict:
        """Fleet-wide graceful drain: stop admission HERE first (new jobs
        get 429 at the front door), then cascade to every worker."""
        self._draining = True
        results = self.fleet.drain_all(timeout=timeout)
        return {
            "drained": bool(results) and all(
                r.get("drained") for r in results.values()
            ),
            "workers": results,
        }

    def start_history(self, directory: str, interval: float = 1.0,
                      total_bytes: int | None = None) -> None:
        """Mount the router-side durable metrics history: every
        ``interval`` seconds one fleet-merged (and respawn-floored)
        snapshot appends to the ring in ``directory``. Default OFF — a
        router without the flag ticks nothing and allocates nothing."""
        from gol_tpu.obs import history as obs_history

        if interval <= 0:
            raise ValueError(f"history interval must be > 0, got {interval}")
        if self._history is not None:
            return
        kwargs = {}
        if total_bytes is not None:
            kwargs["total_bytes"] = total_bytes
            kwargs["segment_bytes"] = min(
                obs_history.DEFAULT_SEGMENT_BYTES, max(1, total_bytes // 4)
            )
        self._history = obs_history.HistoryWriter(
            directory, source="router", **kwargs
        )
        self._history_stop.clear()

        def loop():
            while not self._history_stop.wait(interval):
                try:
                    self.history_tick()
                except Exception:  # noqa: BLE001 - telemetry must survive
                    logger.exception("router history tick failed")

        self._history_thread = threading.Thread(
            target=loop, name="gol-fleet-history", daemon=True
        )
        self._history_thread.start()

    def history_tick(self) -> None:
        """One history sample (public so tests drive it deterministically):
        the merged view the operators' dashboards read, plus the fleet
        membership gauges — the durable record answers "what was the fleet
        doing" without a second artifact."""
        if self._history is None:
            return
        _, merged = self._merged_snapshot()
        stats = self.fleet.stats()
        sample = {
            "counters": dict(merged.get("counters") or {}),
            "gauges": {
                **(merged.get("gauges") or {}),
                "fleet_workers": stats["workers"],
                "fleet_workers_healthy": stats["healthy"],
                "fleet_worker_restarts": stats["restarts"],
            },
            "histograms": dict(merged.get("histograms") or {}),
        }
        self._history.append(sample)

    def shutdown(self, cascade: bool = True) -> None:
        """Stop serving; with ``cascade`` (the SIGTERM path) drain the
        whole fleet and SIGTERM local workers first. ``cascade=False``
        abandons the workers untouched — the router-restart lane."""
        if self.autoscaler is not None:
            # Before anything else: an in-flight scale action must resolve
            # (a spawn the shutdown's kill sweep never saw would outlive
            # the fleet), and a closed autoscaler makes no new decisions.
            self.autoscaler.close()
        if self._history_thread is not None:
            self._history_stop.set()
            self._history_thread.join(timeout=5)
            self._history_thread = None
        if self._history is not None:
            self._history.close()
            self._history = None
        if self._breaker_history is not None:
            self._breaker_history.close()
            self._breaker_history = None
        if self.chaos is not None:
            self.chaos.close()
        if cascade:
            self.drain()
            self.fleet.stop_health()
            self.fleet.terminate()
        else:
            self.fleet.stop_health()
        # Voluntary lease hand-off: a surviving replica should win on its
        # very next tick, not wait for the kernel to reap this process.
        self.fleet.release_leadership()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- circuit breakers ---------------------------------------------------

    def breaker(self, worker_id: str) -> CircuitBreaker | None:
        """The worker's breaker (created lazily), or None when disabled."""
        if not self.breakers_enabled:
            return None
        with self._breakers_lock:
            br = self._breakers.get(worker_id)
            if br is None:
                br = CircuitBreaker(
                    self._breaker_config,
                    on_transition=self._on_breaker_transition,
                    label=worker_id,
                )
                self._breakers[worker_id] = br
            return br

    def breaker_states(self) -> dict[str, str]:
        """{worker id: state} for every breaker that exists ({} when the
        feature is off) — what /fleet, metrics_json, and `gol top` show."""
        if not self.breakers_enabled:
            return {}
        with self._breakers_lock:
            return {wid: br.state for wid, br in self._breakers.items()}

    def prune_breakers(self) -> None:
        """Membership-driven breaker cleanup (the chaos-proxy prune's
        sibling, same health-tick cadence): a RETIRED worker's breaker
        must not haunt /fleet, the state gauges, and the ranking forever
        — especially since scale-up reuses the lowest free partition id,
        which would hand a brand-new worker the dead one's open breaker
        and half-open trickle. Supervised respawns keep their id and so
        their breaker history ON PURPOSE: the single half-open probe is
        exactly the right first contact with a fresh process."""
        if not self.breakers_enabled:
            return
        live = {w.id for w in self.fleet.workers()}
        with self._breakers_lock:
            dead = [wid for wid in self._breakers if wid not in live]
            for wid in dead:
                del self._breakers[wid]
        for wid in dead:
            self.registry.remove_gauge("breaker_state_" + wid)

    def _on_breaker_transition(self, worker_id: str, old: str,
                               new: str) -> None:
        self.registry.set_gauge("breaker_state_" + worker_id,
                                STATE_VALUE[new])
        if new == "open":
            self.registry.inc("breaker_opens_total")
        elif new == "closed":
            self.registry.inc("breaker_closes_total")
        if self._breaker_history is not None:
            try:
                self._breaker_history.append({"breaker": {
                    "worker": worker_id, "from": old, "to": new,
                }})
            except Exception:  # noqa: BLE001 - telemetry must not break routing
                logger.exception("breaker history append failed")

    def _breaker_order(self, pool: list[Worker]) -> list[Worker]:
        """Stable-sort one already-ranked tier so open-breaker workers sink
        to ITS tail: the breaker refines the order inside each
        health/backpressure tier, it never promotes a worker past one."""
        if not self.breakers_enabled:
            return pool
        return sorted(pool, key=lambda w: (
            br.penalty() if (br := self.breaker(w.id)) is not None else 0
        ))

    def _data_url(self, worker: Worker) -> str:
        """The worker's data-path URL — through the chaos hop when one is
        mounted (`gol fleet --chaos`), direct otherwise."""
        if self.chaos is not None:
            return self.chaos.url_for(worker.url)
        return worker.url

    # -- placement + forwarding --------------------------------------------

    def candidates(self, key: placement.PlacementKey,
                   rank_label: str | None = None) -> list[Worker]:
        """Ranked forwarding order for one bucket: the rendezvous owner
        first, spillover next; workers the health loop marked unhealthy or
        backpressured sink to the tail (tried only when nothing better is
        left — routing around a worker must not turn into rejecting jobs
        the moment the last healthy worker wobbles). ``rank_label``
        overrides the HRW key (the cache tier ranks by fingerprint; the
        health/big-lane ordering is identical either way)."""
        label = rank_label if rank_label is not None else key.label()
        # Retiring workers are mid-drain (fleet/autoscale.py): they finish
        # what they hold but take NOTHING new — excluded from the walk
        # entirely, unlike backpressured workers, which tail it.
        workers = {w.id: w for w in self.fleet.workers()
                   if w.url and not w.retiring}
        if not workers:
            return []
        normal = [w for w in workers.values() if not w.big]
        bigs = [w for w in workers.values() if w.big]
        pool = normal or list(workers.values())
        ranked = [workers[wid] for wid in self._rank(label, pool)]
        if bigs and key.max_edge > self.big_edge:
            big_ranked = [workers[wid] for wid in self._rank(label, bigs)]
            ranked = big_ranked + [w for w in ranked if not w.big]
        order = self._breaker_order(
            [w for w in ranked if w.healthy and not w.backpressure]
        )
        order += self._breaker_order(
            [w for w in ranked if w.healthy and w.backpressure]
        )
        order += [w for w in ranked if not w.healthy]
        # Small jobs normally never touch the big lane (its compile budget
        # and rings are reserved for mesh-sharded boards), but a healthy
        # big worker beats a fleet-wide 503 when every normal worker is
        # unreachable — workers re-bucket jobs themselves, so spillover
        # there is correctness-safe. Tail it as the true last resort.
        in_order = {w.id for w in order}
        order += [w for w in bigs if w.healthy and w.id not in in_order]
        return order

    def _rank(self, label: str, pool: list[Worker]) -> list[str]:
        """One pool's HRW order: plain rank, or — with ``--affinity`` —
        weighted rank over the pool's capacity weights. The weighted path
        with all-equal weights delegates to plain rank inside placement,
        so affinity-on-with-no-weights is byte-identical to off."""
        if self.affinity_route:
            return placement.rank_weighted(label, affinity.weights_for(pool))
        return placement.rank(label, [w.id for w in pool])

    # -- the sharded single-job lane (gol_tpu/shard) -----------------------

    def _shard_participant(self, worker_id: str):
        """An HttpParticipant whose URL is re-read from the fleet record
        on EVERY call (a respawned partition answers on a new port) and
        resolved through the chaos hop when one is mounted — halo peers
        and coordinator RPCs ride the same faulty data path as submits."""
        from gol_tpu.shard.coordinator import HttpParticipant

        def url():
            worker = self.fleet.worker(worker_id)
            if worker is None or not worker.url:
                return None
            return self._data_url(worker)

        return HttpParticipant(worker_id, url)

    def _shard_membership(self, initial_ids):
        """The coordinator's elastic-membership hook: consulted at
        checkpoint barriers, reporting a change only when the eligible
        pool GREW (the autoscaler added workers — HRW moves only the
        tiles the new workers win). Shrinks are deliberately ignored: a
        dead worker is a RECOVERY (its journal replays), not a
        membership change, and a retiring one finishes its shard."""
        state = {"ids": set(initial_ids)}

        def hook():
            pool = self.fleet.shard_pool()
            ids = {w.id for w in pool}
            if not ids > state["ids"]:
                return None
            merged = sorted(state["ids"] | ids)
            state["ids"] = set(merged)
            return [self._shard_participant(wid) for wid in merged]

        return hook

    def _submit_shard(self, body: dict):
        """``POST /jobs`` with ``"shard": true`` — one giant universe
        spanning the worker set. 202 with the job id; progress and the
        merged result come from the usual GET endpoints."""
        if not self.fleet.leading:
            return 409, {
                "error": "shard jobs run on the leader router; this "
                         "replica holds no flock lease",
            }
        missing = [k for k in ("rle", "width", "height") if k not in body]
        if missing:
            raise ValueError(
                f"missing required field(s) for a shard job: {missing}"
            )
        pool = self.fleet.shard_pool()
        if not pool:
            return 503, {"error": "fleet has no routable workers"}
        from gol_tpu.shard.coordinator import ShardCoordinator

        job_id = uuid.uuid4().hex
        spec = {
            k: body[k] for k in (
                "rle", "x", "y", "width", "height", "tile", "convention",
                "gen_limit", "check_similarity", "similarity_frequency",
            ) if k in body
        }
        ids = [w.id for w in pool]
        coordinator = ShardCoordinator(
            job_id, spec,
            [self._shard_participant(wid) for wid in ids],
            checkpoint_every=int(body.get("checkpoint_every", 0) or 8),
            registry=self.registry,
            membership=self._shard_membership(ids),
        )
        entry = {
            "id": job_id, "state": "running", "workers": ids,
            "result": None, "error": None, "coordinator": coordinator,
        }
        with self._shard_lock:
            self._shard_jobs[job_id] = entry
        thread = threading.Thread(
            target=self._run_shard, args=(job_id, coordinator),
            name=f"gol-shard-{job_id[:8]}", daemon=True,
        )
        thread.start()
        return 202, {"id": job_id, "state": "running", "shard": True,
                     "workers": ids}

    def _run_shard(self, job_id: str, coordinator) -> None:
        try:
            result = coordinator.run()
        except Exception as e:  # noqa: BLE001 — the job must reach a
            # terminal state whatever the coordinator died of; the error
            # is surfaced verbatim on GET.
            logger.error("shard job %s failed: %s", job_id, e)
            with self._shard_lock:
                entry = self._shard_jobs[job_id]
                entry["state"] = "failed"
                entry["error"] = str(e)
            self.registry.inc("shard_jobs_failed_total")
            return
        with self._shard_lock:
            entry = self._shard_jobs[job_id]
            entry["state"] = "done"
            entry["result"] = result
        self.registry.inc("shard_jobs_done_total")

    def shard_job_json(self, job_id: str) -> dict | None:
        """GET /jobs/<id> for a shard job (None: not a shard job — the
        caller falls through to the forwarding path)."""
        with self._shard_lock:
            entry = self._shard_jobs.get(job_id)
            if entry is None:
                return None
            out = {"id": job_id, "state": entry["state"], "shard": True,
                   "workers": list(entry["workers"])}
            coordinator = entry["coordinator"]
            out["superstep"] = coordinator.k
            out["durable_superstep"] = coordinator.durable
            out["recoveries"] = coordinator.recoveries
            if entry["error"]:
                out["error"] = entry["error"]
            if entry["state"] == "done":
                result = dict(entry["result"])
                result.pop("rle", None)  # the board rides /result/<id>
                out["result"] = result
            return out

    def shard_result(self, job_id: str):
        """GET /result/<id> for a shard job: (status, payload), or None
        to fall through to the forwarding path."""
        with self._shard_lock:
            entry = self._shard_jobs.get(job_id)
            if entry is None:
                return None
            if entry["state"] == "failed":
                return 410, {"id": job_id, "state": "failed",
                             "error": entry["error"]}
            if entry["state"] != "done":
                return 409, {"id": job_id, "state": entry["state"],
                             "error": "shard job is still running"}
            return 200, {"id": job_id, "state": "done",
                         **entry["result"]}

    def shard_cancel(self, job_id: str):
        """DELETE /jobs/<id> for a shard job: running super-steps are not
        cancellable mid-barrier (the answer the single-server scheduler
        gives for claimed jobs)."""
        with self._shard_lock:
            entry = self._shard_jobs.get(job_id)
            if entry is None:
                return None
            return 409, {
                "id": job_id, "state": entry["state"],
                "error": "shard jobs are not cancellable",
            }

    def route_submit(self, raw: bytes, content_type: str | None = None,
                     deadline_header: str | None = None):
        """(status, payload) for POST /jobs: place, forward, spill.

        A PACKED body (``Content-Type: application/x-gol-packed``) is
        placed from its frame header + meta alone (``wire.peek``: ~24
        bytes plus the meta JSON — no payload read, no CRC pass, no board
        unpack) and forwarded as the SAME raw buffer under the same
        content type: the router touches a few dozen bytes of a multi-MB
        submit instead of JSON-parsing all of it. The text path is
        byte-identical to pre-wire routing (test-pinned).

        ``deadline_header`` is the client's ``X-Gol-Deadline`` remaining
        budget: enforced here (a spent budget answers 504 without any
        forward) and DECREMENTED by the router's own elapsed time before
        every hop of the spillover walk — each worker sees only what is
        genuinely left. Absent (every old client), nothing changes
        (pinned); malformed values drop silently."""
        if self._draining:
            self.registry.inc("jobs_rejected_total")
            return 429, {"error": "fleet is draining; not accepting jobs"}
        deadline = None
        budget = propagate.decode_deadline(deadline_header)
        if budget is not None:
            if budget <= 0:
                self.registry.inc("deadline_expired_total")
                return 504, {
                    "error": f"deadline budget spent before the router "
                             f"could place the job ({budget:.3f}s "
                             "remaining)",
                }
            deadline = (budget, time.perf_counter())
        ctype = wire.content_type_of(content_type)
        packed = ctype == wire.CONTENT_TYPE
        if not packed and ctype.startswith(wire.CONTENT_TYPE_FAMILY):
            # A gol wire revision this router does not speak: 415 without
            # forwarding (the router could not even place it), the same
            # retry-as-text signal the workers emit.
            return 415, {
                "error": f"unsupported content type {ctype}; this router "
                         f"speaks {wire.CONTENT_TYPE} and application/json",
            }
        if packed:
            width, height, meta = wire.peek(raw)  # UnsupportedWire -> 415
            body = {**meta, "width": width, "height": height}
        else:
            body = json.loads(raw.decode("utf-8"))
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
        if body.get("shard"):
            # The sharded single-job lane: this router COORDINATES the
            # job across its workers instead of forwarding it to one.
            if packed:
                return 400, {
                    "error": "shard jobs take the text form (rle field); "
                             "the packed frame cannot be re-sliced here",
                }
            return self._submit_shard(body)
        key = placement.key_for(body)  # raises -> handler's 400
        rank_label = None
        if self.cache_route and not body.get("no_cache"):
            # Fleet cache tier: repeats of a board must land where its
            # answer is cached, so the HRW key is the result fingerprint
            # (jax-free; gol_tpu/cache/fingerprint.py). Packed bodies key
            # through the frame's own payload CRC (packed_body_fingerprint
            # — no unpack; format-scoped, so packed repeats of a board
            # deterministically share an owner). A body the fingerprinter
            # rejects falls back to bucket routing — the worker's full
            # validation still answers the client.
            from gol_tpu.cache import fingerprint as fp_mod

            try:
                if packed:
                    rank_label = "fp:" + fp_mod.packed_body_fingerprint(raw)
                else:
                    rank_label = "fp:" + fp_mod.body_fingerprint(body)
                self.registry.inc("jobs_cache_routed_total")
            except (ValueError, TypeError, KeyError):
                rank_label = None
        order = self.candidates(key, rank_label=rank_label)
        if not order:
            return 503, {"error": "fleet has no routable workers"}
        # Trace-context propagation (obs/propagate.py), ONLY while tracing
        # is enabled (`gol fleet --trace`): one fleet-wide trace id per
        # submit — spillover hops re-send the SAME id, so however many
        # workers the walk visits, the job is one flow chain. The flow
        # START is stamped at forward time; the adopting worker's claim
        # point closes the router→worker fleet-queueing gap that
        # `gol trace-report` measures. Disabled (the default), this block
        # allocates nothing and the forwarded request is byte-identical
        # to the headerless PR-8 wire format (test-pinned).
        wire_ct = wire.CONTENT_TYPE if packed else None
        if not obs_trace.enabled():
            # The disabled path builds NOTHING extra — no header, no span
            # attributes, no candidate-ranking string: byte-identical
            # requests and PR-8 work per submit (test-pinned).
            return self._forward_submit(raw, key, order, None, wire_ct,
                                        deadline)
        trace_id = propagate.new_trace_id()
        headers = {propagate.TRACE_HEADER: propagate.encode(
            trace_id, propagate.sender_label()
        )}
        obs_trace.flow("job", trace_id, "s", bucket=key.label())
        with obs_trace.span(
            "fleet.submit", bucket=key.label(),
            candidates=",".join(w.id for w in order),
            cache_route=bool(rank_label),
        ):
            return self._forward_submit(raw, key, order, headers, wire_ct,
                                        deadline)

    def _forward_submit(self, raw: bytes, key: placement.PlacementKey,
                        order: list[Worker], headers: dict | None,
                        content_type: str | None = None,
                        deadline: tuple[float, float] | None = None):
        """The spillover walk: try workers in ranked order; spans/events
        record each hop without ever changing a status code. ``raw`` is
        forwarded verbatim under ``content_type`` (the zero-copy contract:
        a packed frame leaves this process as the byte buffer it arrived
        in; the kwarg is omitted entirely for text, keeping the pre-wire
        call shape byte-identical).

        With breakers on, every hop's outcome feeds the worker's breaker
        (an HTTP answer of any status is a live worker; connection-level
        failures are not). With a ``deadline`` (budget, received_at), each
        hop re-derives the remaining budget, stamps it on the forwarded
        header, and caps the hop's timeout by it — a walk never spends
        more wall clock than the client has left."""
        last = (503, {"error": "no worker accepted the job"})
        small = key.max_edge <= self.big_edge
        shed_seen = False  # any 429: keep it as the client's answer
        normal_shed = False  # a NORMAL worker shed: skip big-lane tails
        http_kwargs = {"headers": headers} if headers else {}
        if content_type is not None:
            http_kwargs["content_type"] = content_type
        # Two-pass walk: a worker whose breaker answers on_attempt()=False
        # at forward time (another caller's half-open probe is in flight,
        # or the ranking raced the breaker opening) is DEFERRED, not
        # forwarded — the single-probe contract holds under concurrency —
        # and retried only after every normally-ranked candidate failed:
        # an open worker stays the last resort, never removed.
        queue = list(order)
        deferred: list[Worker] = []
        while queue or deferred:
            if queue:
                worker = queue.pop(0)
                last_resort = False
            else:
                worker = deferred.pop(0)
                last_resort = True
            if worker.big and small and normal_shed:
                # The big lane is the last resort for small jobs ONLY
                # against unreachable normals. A normal worker's 429
                # means the fleet is alive and load-shedding on purpose:
                # the client must see that backpressure, not have its
                # overflow silently compiled onto the lane reserved for
                # mesh-sharded boards. (A 429 from a BIG worker sets no
                # such signal — when bigs are the pool, or the tail is
                # mid-walk, the next big still gets its try.)
                continue
            br = self.breaker(worker.id)
            if br is not None and not br.on_attempt() and not last_resort:
                deferred.append(worker)
                continue
            crc_retried = False
            while True:
                # Stamped PER ATTEMPT: the CRC re-forward below must
                # re-derive the remaining budget (and re-check expiry) —
                # reusing the first attempt's header would hand the
                # worker the time a slow corrupted hop already spent.
                hop_kwargs = dict(http_kwargs)
                timeout = self.submit_timeout
                if deadline is not None:
                    budget, received = deadline
                    remaining = budget - (time.perf_counter() - received)
                    if remaining <= 0:
                        # The walk itself spent the budget (slow earlier
                        # hops): stop forwarding — the client is gone.
                        self.registry.inc("deadline_expired_total")
                        return 504, {
                            "error": "deadline budget spent during the "
                                     f"spillover walk ({budget:.3f}s "
                                     "granted)",
                        }
                    hdrs = dict(hop_kwargs.get("headers") or {})
                    hdrs[propagate.DEADLINE_HEADER] = (
                        propagate.encode_deadline(remaining)
                    )
                    hop_kwargs["headers"] = hdrs
                    timeout = min(self.submit_timeout, max(0.05, remaining))
                hop_started = time.perf_counter()
                try:
                    with obs_trace.span("fleet.forward", worker=worker.id,
                                        big=worker.big):
                        status, payload = self.http(
                            "POST", self._data_url(worker) + "/jobs",
                            raw=raw, timeout=timeout,
                            **hop_kwargs,
                        )
                except (urllib.error.URLError, ConnectionError,
                        OSError) as err:
                    self.registry.inc("route_errors_total")
                    if br is not None:
                        br.on_failure()
                    if not _delivery_impossible(err):
                        # A timeout/reset AFTER the bytes went out is
                        # ambiguous — the worker may have accepted and
                        # journaled the job (first-dispatch compiles can
                        # outlive submit_timeout). Spilling here would run
                        # the board twice under two ids; surface the
                        # ambiguity — naming WHERE the outcome is unknown
                        # and that worker's breaker state, so the client
                        # (and the operator reading its stderr) knows which
                        # partition to audit — and let the client decide
                        # (poll /fleet, resubmit knowingly).
                        obs_trace.event("fleet.ambiguous", worker=worker.id,
                                        error=type(err).__name__)
                        return 504, {
                            "error": f"worker {worker.id} did not answer "
                                     "the submit in time; outcome unknown "
                                     "— the job may have been accepted "
                                     "there",
                            "worker": worker.id,
                            **({"breaker": br.state} if br is not None
                               else {}),
                        }
                    # Nothing was delivered: spilling is safe. A 429
                    # already seen stays the answer — Retry-After is
                    # actionable, "unreachable" is not.
                    obs_trace.event("fleet.spill", worker=worker.id,
                                    reason="unreachable")
                    if not shed_seen:
                        last = (503, {
                            "error": f"worker {worker.id} unreachable: "
                                     f"{err}",
                        })
                    status = None  # spill to the next-ranked worker
                    break
                if br is not None:
                    br.on_success(time.perf_counter() - hop_started)
                if (status == 400 and not crc_retried
                        and wire.is_crc_error(payload)):
                    # The worker's CRC gate caught a frame corrupted ON
                    # THIS HOP (the router placed the frame from a
                    # well-formed header, and a 400 created no job, so a
                    # re-send is unconditionally safe): one retry of the
                    # same buffer turns a transit bit-flip into a
                    # transparent recovery instead of a client-visible
                    # 400. A second CRC failure returns — the corruption
                    # is then upstream of this router.
                    self.registry.inc("wire_crc_retries_total")
                    obs_trace.event("fleet.crc_retry", worker=worker.id)
                    crc_retried = True
                    continue
                break
            if status is None:
                continue  # unreachable: next candidate
            if status == 429:
                # The worker is shedding (SLO burn) or full: drain it of
                # new work and spill to the next-ranked worker — the
                # client only sees a 429 when the WHOLE fleet sheds.
                self.fleet.note_shed(worker.id)
                self.registry.inc("route_sheds_total")
                obs_trace.event("fleet.spill", worker=worker.id,
                                reason="shed")
                shed_seen = True
                normal_shed = normal_shed or not worker.big
                last = (status, payload)
                continue
            if status == 202 and isinstance(payload, dict) and "id" in payload:
                with self._jobs_lock:
                    self._jobs[payload["id"]] = worker.id
                    while len(self._jobs) > self._jobs_cap:
                        # FIFO: dict order is insertion order; a dropped
                        # mapping costs one broadcast on the next lookup.
                        self._jobs.pop(next(iter(self._jobs)))
                self.registry.inc("jobs_routed_total")
                self.registry.inc(
                    "jobs_routed_total_" + ("big" if worker.big else worker.id)
                )
                payload = {**payload, "worker": worker.id}
            # Client errors (400) return from the first worker verbatim:
            # a malformed job is malformed everywhere.
            return status, payload
        return last

    def forward_job(self, method: str, job_id: str, suffix: str = "",
                    accept: str | None = None):
        """(status, payload) for the per-job endpoints: the mapped worker
        first, then broadcast (the map is memory-only; after a router
        restart the workers' journals are the only truth and whoever
        answers non-404 owns the job).

        ``accept`` forwards the client's Accept header (the packed wire
        result fetch): when the worker answers in the packed content type,
        ``payload`` comes back as the raw frame BYTES — relayed verbatim,
        never decoded here — and the handler writes them out under the
        worker's content type. Every other response (and every error)
        stays the parsed-JSON contract."""
        path = ("/result/" if suffix == "result" else "/jobs/") + job_id
        if suffix not in ("", "result"):
            path = f"/jobs/{job_id}/{suffix}"
        with self._jobs_lock:
            owner = self._jobs.get(job_id)
        workers = self.fleet.workers()
        ordered = sorted(
            [w for w in workers if w.url],
            key=lambda w: w.id != owner,  # mapped owner first
        )
        # A worker mid-(re)boot has no URL yet; the job may be in its
        # partition (replaying right now), so "not found" would be a lie —
        # it counts as unreachable, which clients treat as transient.
        unreachable = sum(1 for w in workers if not w.url)
        for worker in ordered:
            try:
                if accept is not None:
                    status, ctype, body = self.http_exchange(
                        method, self._data_url(worker) + path, timeout=30,
                        headers={"Accept": accept},
                    )
                    if wire.is_packed(ctype):
                        payload = body  # relay the frame bytes untouched
                    else:
                        payload = client._parse(body)
                else:
                    status, payload = self.http(
                        method, self._data_url(worker) + path, timeout=30
                    )
            except (urllib.error.URLError, ConnectionError, OSError):
                unreachable += 1
                continue
            if status == 404:
                continue
            # The mapping's useful life ends when the client collects the
            # terminal answer: a fetched result (200) or tombstone (410 =
            # failed/cancelled), or a successful DELETE. Evict then — the
            # rare re-fetch pays one broadcast; the map stays bounded.
            terminal = (
                (suffix == "result" and status in (200, 410))
                or (method == "DELETE" and status == 200)
            )
            with self._jobs_lock:
                if terminal:
                    self._jobs.pop(job_id, None)
                elif owner is None:
                    self._jobs.setdefault(job_id, worker.id)
                    while len(self._jobs) > self._jobs_cap:
                        self._jobs.pop(next(iter(self._jobs)))
            return status, payload
        if unreachable:
            # The job may live on the unreachable worker(s): "not found"
            # would be a lie, and clients treat 5xx as transient (the
            # worker-respawn window) — exactly the semantics wanted here.
            return 503, {"error": f"job {job_id} not found on reachable "
                                  f"workers; {unreachable} worker(s) "
                                  "unreachable"}
        return 404, {"error": f"unknown job {job_id}"}

    # -- merged observability ----------------------------------------------

    def _collect(self, path: str) -> dict[str, dict | None]:
        """Fetch one path from every worker CONCURRENTLY: with a serial
        sweep, each unreachable worker would add its full connect timeout
        to every /metrics and /slo response — freezing `gol top` and
        blowing scrape deadlines exactly during the outage the operator
        is debugging."""
        workers = self.fleet.workers()
        out: dict[str, dict | None] = {w.id: None for w in workers}
        lock = threading.Lock()

        def fetch(worker: Worker):
            payload = None
            if worker.url is not None:
                try:
                    status, body = self.http("GET", worker.url + path,
                                             timeout=5)
                    if status == 200 and isinstance(body, dict):
                        payload = body
                except (urllib.error.URLError, ConnectionError, OSError):
                    payload = None
            with lock:
                out[worker.id] = payload

        threads = [threading.Thread(target=fetch, args=(w,), daemon=True)
                   for w in workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        # Copy under the lock: a straggler fetch outliving its join
        # timeout still writes to `out` — the caller's dict (cached and
        # shared across scraper threads) must never mutate underfoot.
        with lock:
            return dict(out)

    def _merged_snapshot(self) -> tuple[dict, dict]:
        """Collect + floor + merge, SINGLE-FLIGHT: concurrent scrapes
        (gol top's JSON view and the Prometheus text view run on separate
        HTTP threads) must not feed MonotonicCounters out of snapshot
        order — a pre-respawn snapshot adjusted AFTER a newer post-respawn
        one would bank the old incarnation's total twice and inflate the
        merged series forever. Scrapes therefore never overlap, but a
        late arrival does not queue its OWN full fan-out behind the
        in-flight one (which lasts up to a dead worker's connect timeout
        — exactly the frozen-`gol top`-mid-outage latency the concurrent
        _collect exists to avoid): it waits for the in-flight scrape and
        shares its result."""
        with self._scrape_done:
            while self._scrape_busy:
                epoch = self._scrape_epoch
                self._scrape_done.wait(timeout=30)
                # Share a result only if the scrape we waited on SET it:
                # a scrape that raised bumps the epoch without updating
                # the cache, and serving an arbitrarily old snapshot as
                # if fresh would silently freeze /metrics — fall through
                # and scrape (and likely surface the same error).
                if (self._scrape_epoch != epoch
                        and self._scrape_cache_epoch == self._scrape_epoch
                        and self._scrape_cache is not None):
                    return self._scrape_cache
            # not busy (anymore): this thread does the scrape
            self._scrape_busy = True
        result = None
        try:
            # Restart generations are read BEFORE collecting: a respawn
            # completing in between yields (old generation, fresh
            # snapshot) — the value-regression fallback banks it. The
            # reverse pairing (new generation, stale snapshot) would
            # bank the old run twice.
            incarnations = {w.id: w.restarts for w in self.fleet.workers()}
            snaps = self._collect("/metrics?format=json")
            merged = merge_metrics(self._counter_floors.adjust(
                {k: v for k, v in snaps.items() if v}, incarnations
            ))
            if self._floors_store is not None:
                # Persist what this scrape banked (no-op when unmoved):
                # the merged series' monotonicity now survives THIS
                # router dying, not just the workers.
                self._floors_store.save(self._counter_floors.state())
            result = (snaps, merged)
            return result
        finally:
            with self._scrape_done:
                self._scrape_busy = False
                self._scrape_epoch += 1
                if result is not None:
                    self._scrape_cache = result
                    self._scrape_cache_epoch = self._scrape_epoch
                self._scrape_done.notify_all()

    def metrics_json(self) -> dict:
        self.registry.set_gauge("router_leader",
                                1 if self.fleet.leading else 0)
        snaps, merged = self._merged_snapshot()
        # The snapshot may be shared with concurrent scrapers: never
        # mutate it in place.
        merged = dict(merged)
        health = {w.id: w.public() for w in self.fleet.workers()}
        workers = {}
        for wid, snap in snaps.items():
            entry = dict(snap) if snap else {"unreachable": True}
            entry["health"] = health.get(wid, {})
            workers[wid] = entry
        merged["workers"] = workers
        routers = []
        if self._state_dir is not None:
            from gol_tpu.fleet import replicate as _replicate

            routers = _replicate.list_routers(self.fleet.fleet_dir)
        merged["fleet"] = {
            **self.fleet.stats(),
            # Which replica answered this scrape, whether it leads, and
            # the advertised replica roster — `gol top`'s control-plane
            # panel (absent for embedded routers with no state dir, so
            # their payloads stay byte-identical).
            "router_id": self.router_id,
            "leader": self.fleet.leading,
            **({"routers": routers} if routers else {}),
            "draining": self._draining,
            "router": self.registry.snapshot(),
            **({"breakers": self.breaker_states()}
               if self.breakers_enabled else {}),
            **({"autoscaler": self.autoscaler.public()}
               if self.autoscaler is not None else {}),
        }
        return merged

    def metrics_prometheus(self) -> str:
        _, merged = self._merged_snapshot()
        stats = self.fleet.stats()
        fleet_gauges = {
            "workers": stats["workers"],
            "workers_healthy": stats["healthy"],
            "workers_backpressured": stats["backpressured"],
            # 1 on the replica that holds the leader lease (or on any
            # lease-less single-router fleet) — sum across replicas on a
            # dashboard and alert on != 1.
            "router_leader": 1 if self.fleet.leading else 0,
        }
        fleet_counters = {
            "worker_restarts": stats["restarts"],
            "jobs_routed_total": self.registry.counter("jobs_routed_total"),
            "route_sheds_total": self.registry.counter("route_sheds_total"),
            "route_errors_total": self.registry.counter("route_errors_total"),
        }
        # Deadline enforcement and the CRC-retry lane run whether or not
        # breakers are mounted — their counters export unconditionally
        # (a --no-breakers fleet 504ing on spent deadlines must not show
        # zero expiries on the dashboard).
        for name in ("deadline_expired_total", "wire_crc_retries_total"):
            fleet_counters[name] = self.registry.counter(name)
        if self.breakers_enabled:
            # The breaker series (same flat-name convention as the
            # per-worker jobs_routed_total_<wid> counters): per-worker
            # state gauges plus the open/close transition counters.
            for name in ("breaker_opens_total", "breaker_closes_total"):
                fleet_counters[name] = self.registry.counter(name)
            for wid, state in sorted(self.breaker_states().items()):
                fleet_gauges["breaker_state_" + wid] = STATE_VALUE[state]
        if self.autoscaler is not None:
            snap = self.registry.snapshot()
            for name, value in (snap.get("gauges") or {}).items():
                if name.startswith("autoscaler_"):
                    fleet_gauges[name] = value
            for name, value in (snap.get("counters") or {}).items():
                if name.startswith("autoscaler_"):
                    fleet_counters[name] = value
        return merged_prometheus(merged, fleet_gauges, fleet_counters)

    def slo_json(self) -> dict:
        return merge_slo(self._collect("/slo"))

    def fleet_json(self) -> dict:
        routers = []
        if self._state_dir is not None:
            from gol_tpu.fleet import replicate as _replicate

            routers = _replicate.list_routers(self.fleet.fleet_dir)
        return {
            "fleet_dir": self.fleet.fleet_dir,
            "router_id": self.router_id,
            "leader": self.fleet.leading,
            **({"routers": routers} if routers else {}),
            "draining": self._draining,
            "big_edge": self.big_edge,
            "cache_route": self.cache_route,
            "affinity": self.affinity_route,
            **({"breakers": self.breaker_states()}
               if self.breakers_enabled else {}),
            **({"chaos": self.chaos.stats()}
               if self.chaos is not None else {}),
            **({"autoscaler": self.autoscaler.public()}
               if self.autoscaler is not None else {}),
            "workers": [w.public() for w in self.fleet.workers()],
        }


def _make_handler(router: RouterServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        timeout = 120  # a submit forward can sit behind a worker compile

        def log_message(self, format, *args):  # noqa: A002 - stdlib signature
            logger.debug("%s - %s", self.address_string(), format % args)

        def _reply(self, code: int, payload, content_type="application/json",
                   headers=None):
            if isinstance(payload, (bytes, bytearray)):
                body = bytes(payload)  # packed wire frames relay verbatim
            elif content_type == "application/json":
                body = json.dumps(payload).encode("utf-8")
            else:
                body = payload.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            if code >= 400:
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            self.wfile.write(body)

        def _read_raw(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            cap = wire.max_body_bytes(self.headers.get("Content-Type"))
            if length > cap:
                raise ValueError(f"body of {length} bytes exceeds {cap}")
            return self.rfile.read(length) if length else b"{}"

        def do_POST(self):
            path = urlparse(self.path).path
            try:
                if path == "/jobs":
                    status, payload = router.route_submit(
                        self._read_raw(),
                        content_type=self.headers.get("Content-Type"),
                        deadline_header=self.headers.get(
                            propagate.DEADLINE_HEADER
                        ),
                    )
                    headers = None
                    if status == 429 and "retry_after_s" in (payload or {}):
                        headers = {"Retry-After":
                                   str(int(payload["retry_after_s"]))}
                    self._reply(status, payload, headers=headers)
                elif path == "/drain":
                    self._read_raw()
                    self._reply(200, router.drain())
                else:
                    self._read_raw()
                    self._reply(404, {"error": f"no such endpoint {path}"})
            except wire.UnsupportedWire as e:
                # A newer wire revision than this router speaks: 415, the
                # client's retry-as-text signal (same as the workers).
                self._reply(415, {"error": str(e)})
            except (ValueError, KeyError, TypeError,
                    json.JSONDecodeError) as e:
                self._reply(400, {"error": str(e)})

        def do_DELETE(self):
            path = urlparse(self.path).path
            if not path.startswith("/jobs/"):
                self._reply(404, {"error": f"no such endpoint {path}"})
                return
            job_id = path[len("/jobs/"):]
            shard = router.shard_cancel(job_id)
            if shard is not None:
                self._reply(*shard)
                return
            self._reply(*router.forward_job("DELETE", job_id))

        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path
            if path.startswith("/jobs/"):
                rest = path[len("/jobs/"):]
                if rest.endswith("/timeline"):
                    self._reply(*router.forward_job(
                        "GET", rest[: -len("/timeline")], "timeline"
                    ))
                else:
                    shard = router.shard_job_json(rest)
                    if shard is not None:
                        self._reply(200, shard)
                    else:
                        self._reply(*router.forward_job("GET", rest))
            elif path.startswith("/result/"):
                shard = router.shard_result(path[len("/result/"):])
                if shard is not None:
                    self._reply(*shard)
                    return
                accept = self.headers.get("Accept")
                if wire.accepts_packed(accept):
                    status, payload = router.forward_job(
                        "GET", path[len("/result/"):], "result",
                        accept=wire.CONTENT_TYPE,
                    )
                    self._reply(
                        status, payload,
                        content_type=(
                            wire.CONTENT_TYPE
                            if isinstance(payload, (bytes, bytearray))
                            else "application/json"
                        ),
                    )
                else:
                    self._reply(*router.forward_job(
                        "GET", path[len("/result/"):], "result"
                    ))
            elif path == "/metrics":
                fmt = parse_qs(parsed.query).get("format", ["prometheus"])[0]
                if fmt == "json":
                    self._reply(200, router.metrics_json())
                else:
                    self._reply(200, router.metrics_prometheus(),
                                content_type="text/plain; version=0.0.4")
            elif path == "/slo":
                self._reply(200, router.slo_json())
            elif path == "/debug/trace":
                # The router's span ring, same shape as the worker
                # endpoint — what `gol fleet-trace` stitches per process.
                tracer = obs_trace.tracer()
                self._reply(200, {
                    "enabled": tracer.enabled,
                    "meta": tracer.metadata(),
                    "spans": tracer.snapshot(),
                    "registry": obs_registry.default().snapshot(),
                })
            elif path == "/fleet":
                self._reply(200, router.fleet_json())
            elif path == "/healthz":
                self._reply(200, {
                    "ok": True,
                    "router": True,
                    "id": router.router_id,
                    "leader": router.fleet.leading,
                    "draining": router._draining,
                    "fleet": router.fleet.stats(),
                })
            else:
                self._reply(404, {"error": f"no such endpoint {path}"})

    return Handler
