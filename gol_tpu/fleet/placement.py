"""Bucket -> worker placement: the fleet's process-to-node mapping.

The reference's MPI ranks get their neighbors from ``MPI_Cart_create`` —
a *pre-planned, deterministic* topology every rank derives independently,
no negotiation per message. The fleet asks the same question one level up
(the PAPERS process-to-node-mapping framing): which worker owns a padding
bucket? The answer must be

- **deterministic** — router restarts, or two routers over the same fleet,
  place a bucket identically without shared state;
- **stable under membership change** — losing one worker must move only
  that worker's buckets (every bucket that moves pays a fresh XLA compile
  on its new worker, so minimal movement IS the compile-budget story);
- **orderable** — when the first-choice worker is down or shedding, the
  spillover target must be just as deterministic.

Highest-random-weight (rendezvous) hashing gives all three: every
(bucket, worker) pair gets a score from one stable hash, and a bucket's
preference list is its workers sorted by score. Removing a worker deletes
one entry from every list and moves nothing else; the second-ranked worker
is the canonical spillover.

Placement keys are computed router-side WITHOUT importing the engine (the
router owns no device, so this package stays jax-free): extents round up to
``PLACEMENT_QUANTUM`` (the serve batcher's built-in quantum). When a tuned
plan widens the worker-side quantum, one serve bucket can span several
placement keys — a locality coarsening that costs at most a duplicate
compile on a second worker, never correctness (workers re-bucket every job
themselves; placement only decides WHERE).
"""

from __future__ import annotations

import dataclasses
import hashlib
import math

# The serve batcher's built-in PAD_QUANTUM, restated here so the router
# never imports the jax-loading serve stack. tests/test_fleet.py pins the
# two constants equal.
PLACEMENT_QUANTUM = 32


@dataclasses.dataclass(frozen=True)
class PlacementKey:
    """The router's view of a padding bucket (a coarsening of the serve
    ``BucketKey``: kernel flavor is a worker-side decision and every job
    that shares a serve bucket shares this key)."""

    height: int
    width: int
    convention: str
    check_similarity: bool = True
    similarity_frequency: int = 3

    def label(self) -> str:
        return (
            f"{self.height}x{self.width}/{self.convention}"
            + ("" if self.check_similarity
               else f"/nosim/{self.similarity_frequency}")
            + (f"/sim{self.similarity_frequency}"
               if self.check_similarity and self.similarity_frequency != 3
               else "")
        )

    @property
    def max_edge(self) -> int:
        return max(self.height, self.width)


def pad_dim(n: int) -> int:
    """Round an extent up to the placement quantum (>= one quantum)."""
    q = PLACEMENT_QUANTUM
    return max(q, -(-int(n) // q) * q)


def key_for(body: dict) -> PlacementKey:
    """Placement key from a submit body (the same JSON POST /jobs takes).

    Only the placement-relevant fields are touched; full validation stays
    with the worker's ``Job.__post_init__`` (the router forwards the body
    verbatim). Raises ValueError/TypeError on fields too malformed to
    place — the router maps those to HTTP 400 exactly as a worker would.
    """
    width, height = int(body["width"]), int(body["height"])
    if width <= 0 or height <= 0:
        raise ValueError(f"dimensions must be positive, got {height}x{width}")
    check = body.get("check_similarity", True)
    if not isinstance(check, bool):
        raise TypeError(
            f"check_similarity must be a JSON boolean, got "
            f"{type(check).__name__}"
        )
    return PlacementKey(
        height=pad_dim(height),
        width=pad_dim(width),
        convention=str(body.get("convention", "c")),
        check_similarity=check,
        similarity_frequency=int(body.get("similarity_frequency", 3)),
    )


def _score(bucket_label: str, worker_id: str) -> tuple[int, str]:
    digest = hashlib.sha1(
        f"{bucket_label}|{worker_id}".encode("utf-8")
    ).digest()
    # The worker id tiebreaks identical digests (not reachable with sha1,
    # but determinism must not rest on that).
    return int.from_bytes(digest[:8], "big"), worker_id


def rank(bucket_label: str, worker_ids) -> list[str]:
    """Worker ids by descending rendezvous score for this bucket: [0] is
    the owner, [1] the canonical spillover, and so on. Deterministic in
    the (bucket, ids) pair alone."""
    return sorted(worker_ids, key=lambda w: _score(bucket_label, w),
                  reverse=True)


def _weighted_score(bucket_label: str, worker_id: str,
                    weight: float) -> tuple[float, str]:
    """Logarithm-method weighted rendezvous score (Thaler/Ravishankar):
    map the hash to h in (0, 1) and score ``-weight / ln(h)``. The score
    distribution makes each bucket's owner worker ``i`` with probability
    w_i / sum(w) while keeping HRW's minimal-disruption property — and
    because the score is strictly increasing in h at any fixed weight,
    EQUAL weights order exactly like the raw hash, i.e. like ``rank``."""
    digest, _ = _score(bucket_label, worker_id)
    # (digest + 0.5) / 2^64 keeps h strictly inside (0, 1): ln(0) and
    # ln(1) are both poles of the formula.
    h = (digest + 0.5) / float(1 << 64)
    return -weight / math.log(h), worker_id


def rank_weighted(bucket_label: str, weights: dict[str, float]) -> list[str]:
    """``rank`` with per-worker capacity weights (the affinity layer,
    gol_tpu/fleet/affinity.py): a worker with twice the weight owns about
    twice the buckets. Deterministic in (bucket, weights) alone; changing
    one worker's weight only moves buckets between that worker and the
    rest (never reshuffles third parties — the weighted-rendezvous
    analog of the minimal-disruption property, test-pinned).

    All-equal weights DELEGATE to plain ``rank`` — not just
    order-equivalent but the same code path, so ``--affinity`` with
    no weights configured is byte-identical to affinity off (pinned).
    Non-positive weights are treated as the 1.0 default (a zero weight
    would be "never place here", which is membership's job, not
    placement's)."""
    ids = list(weights)
    cleaned = {w: (float(weights[w]) if float(weights[w]) > 0 else 1.0)
               for w in ids}
    if len(set(cleaned.values())) <= 1:
        return rank(bucket_label, ids)
    return sorted(
        ids,
        key=lambda w: _weighted_score(bucket_label, w, cleaned[w]),
        reverse=True,
    )
