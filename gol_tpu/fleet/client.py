"""Stdlib HTTP JSON client for the fleet tier.

Deliberately jax-free (like the rest of ``gol_tpu/fleet``): the router is a
front-end process — it parses a request far enough to *place* it and then
moves bytes; the workers own the devices. Everything here is urllib over
persistent-nothing (one request per connection is fine at router rates;
the hot path is the worker's compute, not the hop).

``http_json`` mirrors ``gol_tpu.cli._http_json``'s contract — HTTP errors
come back as (status, payload) so callers branch on codes, while genuine
connection trouble (refused, reset, timeout) raises ``OSError``/``URLError``
for the caller's liveness logic to classify.
"""

from __future__ import annotations

import http.client
import json
import urllib.error
import urllib.request


def http_exchange(
    method: str,
    url: str,
    body: dict | None = None,
    *,
    raw: bytes | None = None,
    timeout: float = 30.0,
    headers: dict | None = None,
    content_type: str | None = None,
):
    """One HTTP exchange -> (status, response content type, body bytes).

    The format-agnostic primitive under ``http_json``: the packed wire
    paths (io/wire.py) ride it directly — a packed result relay must hand
    the frame bytes through untouched, and a packed submit forward must
    carry its own Content-Type. ``content_type`` overrides the request
    body's type (default ``application/json``, byte-identical to the
    pre-wire client for every JSON caller). HTTP error statuses return
    normally; connection-level failures raise (URLError/OSError)."""
    if body is not None and raw is not None:
        raise ValueError("pass body or raw, not both")
    data = raw
    hdrs = {"Accept": "application/json"}
    if body is not None:
        data = json.dumps(body).encode("utf-8")
    if data is not None:
        hdrs["Content-Type"] = content_type or "application/json"
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(url, data=data, headers=hdrs, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), resp.read()
    except urllib.error.HTTPError as e:
        try:
            data = e.read()
        except http.client.HTTPException as torn:
            # An error response truncated mid-body: e.read() raises from
            # INSIDE this handler, where the sibling HTTPException clause
            # below cannot see it — normalize here too or the raw
            # IncompleteRead escapes every caller's classification.
            if isinstance(torn, OSError):
                raise
            raise ConnectionError(f"{type(torn).__name__}: {torn}") from torn
        return e.code, e.headers.get("Content-Type", ""), data
    except http.client.HTTPException as e:
        # Torn/garbled HTTP that is NOT already an OSError — a response
        # truncated mid-body raises IncompleteRead (an HTTPException
        # only), which every caller's transient-failure classification
        # would otherwise miss and crash on. A truncation IS connection
        # trouble: normalize it so liveness logic treats it like a reset.
        # RemoteDisconnected (HTTPException AND ConnectionResetError)
        # re-raises untouched — it already speaks OSError.
        if isinstance(e, OSError):
            raise
        raise ConnectionError(f"{type(e).__name__}: {e}") from e


def http_json(
    method: str,
    url: str,
    body: dict | None = None,
    *,
    raw: bytes | None = None,
    timeout: float = 30.0,
    headers: dict | None = None,
    content_type: str | None = None,
):
    """One JSON exchange -> (status, payload).

    ``raw`` forwards pre-encoded bytes verbatim (the router's submit path:
    the client's body was already parsed for placement; re-encoding a 17 MB
    board a second time would be pure tax). ``headers`` adds/overrides
    request headers (the router's trace-context stamp, obs/propagate.py —
    receivers that don't know a header ignore it). ``content_type``
    overrides the body's Content-Type (the packed wire forward). HTTP
    error statuses return normally; connection-level failures raise
    (URLError/OSError).
    """
    status, _ctype, data = http_exchange(
        method, url, body, raw=raw, timeout=timeout, headers=headers,
        content_type=content_type,
    )
    return status, _parse(data)


def _parse(raw: bytes):
    try:
        return json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return {"error": raw[:200].decode("utf-8", "replace")}


def probe(url: str, path: str = "/healthz", timeout: float = 2.0) -> dict | None:
    """GET url+path -> payload dict, or None when unreachable/unhealthy —
    the liveness primitive the health loop and manifest reattach share."""
    try:
        status, payload = http_json("GET", url.rstrip("/") + path,
                                    timeout=timeout)
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        return None
    if status != 200 or not isinstance(payload, dict):
        return None
    return payload
