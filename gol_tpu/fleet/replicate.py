"""Durable per-router coordination state: what a respawn must not forget.

The router is stateless-by-construction for the DATA plane (the manifest
plus deterministic HRW placement rebuild everything a replica needs to
route), but two pieces of coordination state used to live only in process
memory, and a router respawn silently reset both:

- **MonotonicCounters floors** — the banked per-(worker, series) totals
  that keep the fleet-merged cumulative series monotonic through WORKER
  respawns. Lose them and the merged counters drop by every banked run at
  once: exactly the spurious reset the floors exist to prevent, now
  triggered by a *router* restart.
- **Breaker states** — a breaker that was OPEN when the router died
  protected the fleet from a worker it had evidence against. A successor
  that starts every breaker CLOSED re-learns that evidence the expensive
  way: ``fail_threshold`` real jobs sent into a known-bad hop.

Each router replica owns one state directory, ``<fleet_dir>/routers/<id>/``
(single writer per directory — the obs/history ring's own discipline), and
*merges across all of them on load*: a replacement router under a fresh id
still inherits every sibling's floors and breaker evidence.

Formats, chosen per access pattern:

- floors are a bounded SNAPSHOT (``floors.json``, atomic tmp+fsync+
  replace): the state is a small dict that supersedes itself wholesale,
  so a ring would only defer the fold to every reader;
- breaker transitions stay an append-only RING (``breaker-history/``, the
  PR-14 ``obs/history.HistoryWriter``) because the sequence itself is the
  operator's audit trail; warm-start folds it to last-state-per-worker.

Merge rules are deliberately conservative: floors take the LARGER banked
total per series (floors only ever grow; the bigger one has seen more),
and a worker reads as warm-OPEN if ANY replica's last word on it was
open/half-open — the cost of being wrong is one cooldown plus one
half-open probe, the cost of the liberal rule is a storm of real jobs
into a dead worker.

Clocks: none here either (the lint pin covers this file) — persisted
state carries no timestamps, because perf_counter anchors do not compare
across processes and wall clocks step.
"""

from __future__ import annotations

import json
import logging
import os

logger = logging.getLogger(__name__)

ROUTERS_SUBDIR = "routers"
FLOORS_FILENAME = "floors.json"
BREAKER_RING = "breaker-history"
ADVERT_FILENAME = "advert.json"


def routers_root(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, ROUTERS_SUBDIR)


def state_dir(fleet_dir: str, router_id: str) -> str:
    """One replica's durable-state home: floors, breaker ring, and the
    advertisement file live under it; nothing else ever writes there."""
    return os.path.join(routers_root(fleet_dir), router_id)


class FloorsStore:
    """Atomic snapshot persistence for ``MonotonicCounters.state()``.

    ``save`` never raises (coordination durability must not take down the
    scrape path that feeds it) and skips the write entirely when the
    state has not moved — an idle fleet costs zero I/O. ``load`` is
    torn-tolerant: the write is atomic, so a parse failure means external
    damage, and the honest response is to start floors empty (the
    value-regression fallback still catches future worker respawns)."""

    def __init__(self, directory: str):
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, FLOORS_FILENAME)
        self._last_saved: dict | None = None

    def load(self) -> dict | None:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                state = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(state, dict):
            return None
        self._last_saved = state
        return state

    def save(self, state: dict) -> None:
        if state == self._last_saved:
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(state, f, separators=(",", ":"))
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._last_saved = state
        except OSError as err:
            logger.error("router floors save failed (%s); merged counters "
                         "would reset if this router dies before it "
                         "recovers", err)


def _floor_pairs(state: dict) -> dict[tuple, tuple[float, float]]:
    """{(worker, series-key): (base, last)} from one persisted state."""
    pairs: dict[tuple, tuple[float, float]] = {}
    for kind, slot in (("base", 0), ("last", 1)):
        for entry in state.get(kind) or []:
            try:
                wid, skey, value = entry
                key = (str(wid), tuple(skey))
            except (TypeError, ValueError):
                continue
            base, last = pairs.get(key, (0.0, 0.0))
            pairs[key] = ((float(value), last) if slot == 0
                          else (base, float(value)))
    return pairs


def load_merged_floors(fleet_dir: str) -> dict | None:
    """The union of every replica's persisted floors, larger-total-wins
    per (worker, series) — what a (re)starting router seeds its
    ``MonotonicCounters`` with. None when no replica ever persisted."""
    root = routers_root(fleet_dir)
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return None
    merged: dict[tuple, tuple[float, float]] = {}
    incarnations: dict[str, int] = {}
    found = False
    for name in entries:
        state = FloorsStore(os.path.join(root, name)).load()
        if state is None:
            continue
        found = True
        for key, (base, last) in _floor_pairs(state).items():
            prev = merged.get(key)
            if prev is None or base + last > prev[0] + prev[1]:
                merged[key] = (base, last)
        for wid, gen in (state.get("incarnations") or {}).items():
            try:
                incarnations[wid] = max(incarnations.get(wid, 0), int(gen))
            except (TypeError, ValueError):
                continue
    if not found:
        return None
    return {
        "version": 1,
        "base": [[wid, list(skey), base]
                 for (wid, skey), (base, _) in merged.items() if base],
        "last": [[wid, list(skey), last]
                 for (wid, skey), (_, last) in merged.items()],
        "incarnations": incarnations,
    }


def advertise(fleet_dir: str, router_id: str, url: str) -> None:
    """Publish this replica's URL + pid into its state dir (atomic, best
    effort): the operator-facing replica roster behind ``GET /fleet`` and
    ``gol top``. Display only, like the lease file's stamp — routing
    authority is the manifest, leadership authority is the flock."""
    directory = state_dir(fleet_dir, router_id)
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, ADVERT_FILENAME)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"id": router_id, "url": url, "pid": os.getpid()}, f)
            f.write("\n")
        os.replace(tmp, path)
    except OSError as err:
        logger.warning("router advert write failed (%s)", err)


def list_routers(fleet_dir: str) -> list[dict]:
    """Every replica that ever advertised, with a best-effort ``alive``
    bit (pid still exists — pid reuse can lie, which is why nothing but
    dashboards reads it; a dead replica's advert lingering is normal)."""
    root = routers_root(fleet_dir)
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    out = []
    for name in entries:
        path = os.path.join(root, name, ADVERT_FILENAME)
        try:
            with open(path, "r", encoding="utf-8") as f:
                advert = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(advert, dict):
            continue
        pid = advert.get("pid")
        alive = False
        if isinstance(pid, int):
            try:
                os.kill(pid, 0)
                alive = True
            except ProcessLookupError:
                alive = False
            except OSError:
                alive = True  # EPERM: the pid exists, just not ours
        out.append({**advert, "alive": alive})
    return out


def warm_breaker_states(fleet_dir: str) -> dict[str, str]:
    """{worker id: "open"} for every worker some replica's durable breaker
    ring last recorded as open or half-open — the evidence a fresh router
    re-arms instead of re-learning. Half-open folds to open: the probe
    that was in flight died with the old router, and re-arming OPEN hands
    the successor a fresh cooldown before ITS single probe."""
    root = routers_root(fleet_dir)
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return {}
    from gol_tpu.obs import history as obs_history

    warm: dict[str, str] = {}
    for name in entries:
        ring = os.path.join(root, name, BREAKER_RING)
        if not os.path.isdir(ring):
            continue
        last: dict[str, str] = {}
        for record in obs_history.read_records(ring):
            event = record.get("breaker")
            if isinstance(event, dict) and event.get("worker"):
                last[str(event["worker"])] = str(event.get("to") or "")
        for wid, to_state in last.items():
            if to_state in ("open", "half-open"):
                warm[wid] = "open"
    return warm
