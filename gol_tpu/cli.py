"""CLI entry point — the reference's ``./a.out <width> <height> <input_file>``.

One binary replaces six: ``--variant`` selects the reference program being
reproduced (same output filename, same printed lines, same accounting). The
contract mirrored from the reference mains (src/game.c:224-245,
src/game_mpi_collective.c:466-489):

- ``width = atoi(argv[1])``, ``height = atoi(argv[2])`` — C atoi semantics,
  non-numeric parses to 0;
- non-positive dimensions default to 30x30;
- distributed variants force ``height = width`` (src/game_mpi.c:504);
- with no input file the simulation is skipped and only ``Finished`` prints
  (src/game.c:238-241) — and the openmp variant prints nothing at all, since
  its final printf is commented out (src/game_openmp.c:501);
- timings print as ``<Phase>:\\t<ms> msecs`` from the lead process only.

Additional subcommand: ``generate <width> <height>`` replaces generate.sh
(emitting the contractual height rows x width cols; the script transposes,
generate.sh:6-13).

Divergences (documented, deliberate): Execution time is wall-clock for every
variant (the serial reference prints CPU time via clock(), src/game.c:175,199);
the cuda variant validates argv instead of segfaulting (src/game_cuda.cu:
155-156 reads argv unchecked); compile time is excluded from Execution time —
the analog of the reference building its persistent requests before starting
the loop timer (src/game_mpi_collective.c:278-328).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys
import time

import numpy as np

from gol_tpu.platform_env import configure_cli_logging, honor_platform_env

# Applied at import time, before the jax-importing gol_tpu modules below
# load — main() calls it again (idempotent), but the import-time call is
# what guarantees no transitive module-level device touch can precede it.
honor_platform_env()

from gol_tpu import engine, oracle
from gol_tpu.config import DEFAULT_HEIGHT, DEFAULT_WIDTH, GameConfig
from gol_tpu.obs import trace as obs_trace
from gol_tpu.io import sharded, text_grid
from gol_tpu.variants import VARIANTS, Variant, get_variant


def atoi(s: str | None) -> int:
    """C atoi: optional sign + leading digits, anything else is 0."""
    if not s:
        return 0
    m = re.match(r"\s*([+-]?\d+)", s)
    return int(m.group(1)) if m else 0


def _parse_mesh_arg(
    spec: str | None,
    distributed: bool,
    width: int | None = None,
    height: int | None = None,
):
    import jax

    from gol_tpu.parallel.mesh import make_mesh

    if not distributed:
        if spec:
            raise ValueError(
                "--mesh only applies to distributed variants "
                "(mpi/collective/async/openmp/tpu); this variant is single-device"
            )
        return None
    if spec:
        m = re.fullmatch(r"(\d+)x(\d+)", spec)
        if not m:
            raise ValueError(f"--mesh must look like RxC, got {spec!r}")
        return make_mesh(int(m.group(1)), int(m.group(2)))
    # Default factorization: row-heaviest that divides the grid, unless the
    # width would push full-width shards past the temporal kernel's VMEM cap.
    return make_mesh(devices=jax.devices(), width=width, height=height)


def _warn_if_huge_byte_lane(width: int, height: int, mesh=None) -> bool:
    """Steer 2GB+-per-device byte-lane runs toward --packed-io before XLA OOMs.

    The byte lane carries two uint8 buffers through the loop; at 2GB+ of
    cells per device that flirts with (65536^2 single-chip: exceeds) a 16GB
    chip's HBM, and the XLA OOM it dies with names no remedy. The packed
    lane is 32x smaller — say so up front, but only where --packed-io would
    actually accept the shape (width divisible by 32 x mesh cols,
    io/packed_io.py). Returns whether the warning fired."""
    devices = cols = 1
    if mesh is not None:
        devices = mesh.devices.size
        from gol_tpu.parallel.mesh import COL_AXIS

        cols = mesh.shape[COL_AXIS]
    per_device = width * height // devices
    if per_device < (2 << 30) or width % (32 * cols) != 0:
        return False
    print(
        f"warning: {width}x{height} as bytes is "
        f"{per_device / (1 << 30):.1f} GB per buffer per device; "
        "if this runs out of device memory, use --packed-io "
        "(bit-packed state, 32x smaller)",
        file=sys.stderr,
    )
    return True


def _read_phase(variant: Variant, path: str, width: int, height: int, mesh):
    if variant.io == "serial":
        return engine.put_grid(text_grid.read_grid(path, width, height), mesh)
    if variant.io == "gathered":
        return sharded.read_gathered(path, width, height, mesh)
    return sharded.read_sharded(
        path, width, height, mesh, parallel=(variant.io == "sharded_async")
    )


def _write_phase(variant: Variant, path: str, grid) -> None:
    if variant.io == "serial":
        text_grid.write_grid(path, np.asarray(grid, dtype=np.uint8))
    elif variant.io == "gathered":
        sharded.write_gathered(path, grid)
    else:
        sharded.write_sharded(path, grid, parallel=(variant.io == "sharded_async"))


def _checkpointing(args) -> bool:
    # `is not None`, not truthiness: --checkpoint-every 0 must reach the
    # validator and be rejected loudly, not silently disable the lane.
    return (
        args.checkpoint_every is not None
        or args.auto_resume
        or args.checkpoint_dir is not None
    )


def _validate_checkpoint_args(args) -> None:
    """Normalize and cross-check the crash-safety flags before any lane runs
    (so a contradictory combination never half-starts a checkpoint dir)."""
    if not _checkpointing(args):
        return
    if args.checkpoint_dir is None:
        args.checkpoint_dir = "./checkpoints"
    if args.checkpoint_every is None and not args.auto_resume:
        raise ValueError(
            "--checkpoint-dir needs --checkpoint-every N (write checkpoints) "
            "and/or --auto-resume (restart from the newest one)"
        )
    if args.checkpoint_every is not None and args.checkpoint_every <= 0:
        raise ValueError(
            f"--checkpoint-every must be positive, got {args.checkpoint_every}"
        )
    if args.checkpoint_keep < 1:
        raise ValueError(
            f"--checkpoint-keep must be >= 1, got {args.checkpoint_keep}"
        )
    if args.snapshot_every:
        raise ValueError(
            "checkpointing does not compose with --snapshot-every: a "
            "checkpoint IS a resumable snapshot plus a crash-consistent "
            "manifest — use one or the other"
        )
    if args.auto_resume and args.resume_gen:
        raise ValueError(
            "--auto-resume discovers the resume generation from the "
            "checkpoint manifests; --resume-gen contradicts it"
        )
    if args.host:
        raise ValueError(
            "checkpointing rides the segmented device loop; --host has none"
        )


def _run(args) -> int:
    from gol_tpu.platform_env import enable_compile_cache
    from gol_tpu.resilience import faults

    if args.gens is not None:
        # --gens is the deep-time spelling of --gen-limit (the macro lane's
        # natural vocabulary); one value drives every lane either way.
        if args.gens < 0:
            raise ValueError(f"--gens must be >= 0, got {args.gens}")
        args.gen_limit = args.gens
    if args.macro_cas and args.engine not in ("macro", "auto"):
        # A silently-ignored persistence flag would misreport what ran.
        raise ValueError(
            "--macro-cas applies to the macro engine lane; add "
            "--engine macro (or auto)"
        )
    if args.engine == "shard" and not args.shard_across:
        raise ValueError(
            "--engine shard needs --shard-across ROUTER_URL (the job "
            "runs across a fleet, not in this process)"
        )
    if args.shard_across and args.engine != "shard":
        # Same loudness as --macro-cas: a sharding flag that silently
        # ran locally would misreport what executed where.
        raise ValueError("--shard-across applies to --engine shard")
    if args.engine == "shard" and args.pattern is None:
        raise ValueError(
            "--engine shard takes the --pattern lane (the universe "
            "travels as RLE; dense input files do not)"
        )
    enable_compile_cache(args.compile_cache)

    if args.fault_plan:
        faults.install(faults.FaultPlan.parse(args.fault_plan))
    else:
        # from_env() is None when GOL_FAULTS is unset, so a plan armed by a
        # previous in-process run (the crash-recovery harness) is cleared —
        # each run gets exactly the faults IT asked for.
        faults.install(faults.FaultPlan.from_env())
    variant = get_variant(args.variant)
    width, height = atoi(args.width), atoi(args.height)
    if variant.force_square:
        height = width  # src/game_mpi.c:504
    if width <= 0:
        width = DEFAULT_WIDTH
    if height <= 0:
        height = DEFAULT_HEIGHT

    if args.pattern is not None:
        # The geometry-first lane: the board is a pattern placed into a
        # declared universe — construction never materializes the canvas,
        # so the engine choice (sparse above the area threshold) happens
        # BEFORE any allocation the choice is supposed to avoid.
        return _run_pattern(args, variant)

    if args.input_file is None:
        # Simulation skipped entirely (src/game.c:238-241).
        if variant.final_finished:
            print("Finished")
        return 0

    config = GameConfig(
        gen_limit=args.gen_limit,
        check_similarity=not args.no_check_similarity,
        similarity_frequency=args.similarity_frequency,
        convention=variant.convention,
    )
    output_path = args.output or f"./{variant.output_file}"

    _validate_checkpoint_args(args)
    if args.resume_gen < 0:
        raise ValueError(f"--resume-gen must be >= 0, got {args.resume_gen}")
    if args.resume_gen > config.gen_limit:
        # A typo'd resume count would otherwise produce a no-op run with a
        # plausible-looking report above the limit.
        raise ValueError(
            f"--resume-gen {args.resume_gen} exceeds --gen-limit "
            f"{config.gen_limit}; nothing to resume"
        )

    # The zarr guards depend only on argv, so they run before every lane
    # (including --host, which would otherwise read_grid a .zarr directory).
    if args.snapshot_format == "zarr":
        if not args.packed_io:
            raise ValueError(
                "--snapshot-format zarr stores the bitpacked word state and "
                "needs the packed lane; add --packed-io"
            )
        from gol_tpu.io import ts_store

        if not ts_store.HAVE_TENSORSTORE:
            raise ValueError(
                "--snapshot-format zarr needs tensorstore, which is not "
                "installed; use --snapshot-format text"
            )
    if args.input_file and args.input_file.endswith(".zarr") and not args.packed_io:
        raise ValueError(
            "a .zarr input (TensorStore snapshot) holds packed word state; "
            "add --packed-io to resume from it"
        )

    if args.engine == "sparse":
        # Sparse engine over a dense input FILE (the A/B lane): reading the
        # file materializes the grid, so this only serves sizes the dense
        # guard admits — giant universes come in as --pattern instead.
        _validate_sparse_flags(args)
        return _run_sparse_file(args, variant, config, width, height)

    if args.engine == "macro":
        # Same A/B lane, macrocell engine: byte-gates the tree against the
        # dense/sparse answers from the CLI.
        _validate_macro_flags(args)
        return _run_macro_file(args, variant, config, width, height)

    if args.host:
        # lax is what the host oracle effectively is, so it stays accepted;
        # forcing an accelerator kernel alongside --host is a contradiction.
        if args.mesh or args.kernel not in ("auto", "lax") or args.packed_io:
            raise ValueError(
                "--mesh/--kernel/--packed-io do not apply with --host "
                "(oracle runs on the host CPU)"
            )
        if args.resume_gen:
            raise ValueError("--resume-gen is not supported with --host "
                             "(the oracle has no segmented loop)")
        return _run_host(args, variant, config, width, height, output_path)

    if variant.distributed:
        # MPI_Init analog: joins the pod cluster when GOL_MULTIHOST is set,
        # no-op otherwise (gol_tpu/parallel/bootstrap.py). Serial variants
        # never form a cluster, like the reference's non-MPI programs.
        from gol_tpu.parallel import bootstrap

        bootstrap.initialize()
    mesh = _parse_mesh_arg(args.mesh, variant.distributed, width, height)
    from gol_tpu.parallel.mesh import topology_for, validate_grid

    if mesh is not None and not topology_for(mesh).distributed:
        # A 1x1 mesh IS the single-device engine; dropping the mesh avoids
        # explicit-sharding annotations leaking into the unsharded kernels.
        mesh = None
    validate_grid(height, width, topology_for(mesh))

    if args.packed_io:
        if args.kernel not in ("auto", "packed"):
            raise ValueError(
                f"--packed-io always runs the packed kernel; --kernel "
                f"{args.kernel!r} contradicts it"
            )
        return _run_packed_io(args, variant, config, width, height, output_path, mesh)

    if mesh is None:
        # The dense-path scaling trap: an oversized request used to OOM
        # inside np.zeros/read_grid with a raw traceback. Fail it here,
        # clearly, naming the lane that CAN run it. (Sharded mesh reads
        # materialize per-shard, not the whole canvas — they keep their
        # own per-device warning below; the packed lane branched off
        # above and carries 32x smaller state.)
        from gol_tpu.sparse.board import dense_cells_guard

        dense_cells_guard(height, width)

    _warn_if_huge_byte_lane(width, height, mesh)

    t0 = time.perf_counter()
    with obs_trace.span("cli.read_phase", file=args.input_file):
        device_grid = _read_phase(variant, args.input_file, width, height, mesh)
    read_ms = (time.perf_counter() - t0) * 1000
    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")

    if _checkpointing(args):
        run_fn = _prepare_checkpointed(args, variant, config, mesh, device_grid,
                                       height, width, packed=False)
    elif args.snapshot_every:
        run_fn = _prepare_segmented(args, variant, config, mesh, device_grid, height, width)
    elif args.resume_gen:
        run_fn = _prepare_resumed(args, config, mesh, device_grid, height, width,
                                  packed=False, kernel=args.kernel)
    else:
        runner = engine.make_runner((height, width), config, mesh, args.kernel)
        compiled = engine.compile_runner(runner, device_grid)
        if args.warmup:
            # One discarded run: absorbs runtime/program-upload init that
            # would otherwise land in Execution time (remote-attached
            # accelerators pay it on the first call, not at compile()).
            _, g0 = compiled(device_grid)
            int(g0)

        def run_fn():
            final, gen = compiled(device_grid)
            return final, int(gen)  # int() blocks until the loop finishes

    with _profile_trace(args.profile):
        with obs_trace.span("cli.execution"):
            t0 = time.perf_counter()
            final, generations = run_fn()
            exec_ms = (time.perf_counter() - t0) * 1000

    return _report_and_write(
        variant,
        generations,
        exec_ms,
        lambda: _write_phase(variant, output_path, final),
    )


def _report_and_write(variant, generations, exec_ms, write_fn) -> int:
    """The reference's printed-output contract, shared by every lane
    (src/game.c:201-206, src/game_mpi_collective.c:367-450)."""
    if variant.serial_header:
        print("Finished.\n")
    print(f"Generations:\t{generations}")
    print(f"Execution time:\t{exec_ms:.2f} msecs")
    t0 = time.perf_counter()
    with obs_trace.span("cli.write_phase"):
        write_fn()
    write_ms = (time.perf_counter() - t0) * 1000
    if variant.io_timings:
        print(f"Writing file:\t{write_ms:.2f} msecs")
    if variant.final_finished:
        print("Finished")
    return 0


def _run_packed_io(args, variant, config, width, height, output_path, mesh) -> int:
    """The all-packed lane: file -> word state -> file, no uint8 grid ever.

    Timing lines keep the reference contract; the packed read/write go
    through the native codec (gol_tpu/native/codec.c)."""
    from gol_tpu.io import packed_io

    t0 = time.perf_counter()
    with obs_trace.span("cli.read_phase", file=args.input_file):
        if args.input_file.endswith(".zarr"):
            # A TensorStore snapshot (gen_NNNNNN.zarr) resumes directly on
            # the packed lane — the object-store counterpart of text resume.
            from gol_tpu.io import ts_store

            words = ts_store.read_words(args.input_file, width, height, mesh)
        else:
            words = packed_io.read_packed(args.input_file, width, height, mesh)
    read_ms = (time.perf_counter() - t0) * 1000
    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")

    if _checkpointing(args):
        run_fn = _prepare_checkpointed(args, variant, config, mesh, words,
                                       height, width, packed=True)
    elif args.snapshot_every:
        run_fn = _prepare_packed_segmented(args, config, mesh, words, height, width)
    elif args.resume_gen:
        run_fn = _prepare_resumed(args, config, mesh, words, height, width,
                                  packed=True)
    else:
        runner = engine.make_packed_runner((height, width), config, mesh)
        compiled = engine.compile_runner(runner, words)
        if args.warmup:
            _, g0 = compiled(words)
            int(g0)

        def run_fn():
            final, gen = compiled(words)
            return final, int(gen)

    with _profile_trace(args.profile):
        with obs_trace.span("cli.execution"):
            t0 = time.perf_counter()
            final, generations = run_fn()
            exec_ms = (time.perf_counter() - t0) * 1000

    return _report_and_write(
        variant,
        generations,
        exec_ms,
        lambda: packed_io.write_packed(output_path, final, width),
    )


def _prepare_packed_segmented(args, config, mesh, words, height, width):
    """Snapshotting loop over word state: every snapshot is written through
    the packed codec (text format — itself a valid packed-readable input
    file, the reference's resume property at packed-lane scale) or, with
    --snapshot-format zarr, through the sharded TensorStore lane (pod
    object stores with no shared POSIX mmap; io/ts_store.py)."""
    from gol_tpu.io import packed_io

    runner = engine.make_packed_segment_runner((height, width), config, mesh)
    if args.snapshot_format == "zarr":
        from gol_tpu.io import ts_store

        write = lambda path, state: ts_store.write_words(path, state, width)
        suffix = ".zarr"
    else:
        write = lambda path, state: packed_io.write_packed(path, state, width)
        suffix = ".out"
    return _snapshot_loop(
        args,
        config,
        runner,
        words,
        lambda state: engine.simulate_packed_segments(
            state, (height, width), config, mesh, args.snapshot_every,
            completed=args.resume_gen,
        ),
        write,
        suffix=suffix,
    )


def _prepare_resumed(args, config, mesh, state, height, width, *, packed, kernel="auto"):
    """Continue a run from a snapshot without writing further snapshots.

    The input file is the state after ``--resume-gen`` generations of a run
    that had not early-exited; the similarity phase is realigned from that
    count alone (engine.resume_scalars — no sidecar metadata exists or is
    needed), so exits and the reported total match the uninterrupted run.

    The zero-step warmup call below runs unconditionally (unlike the
    unsegmented lane, where warmup is opt-in via --warmup) for the same
    reason _snapshot_loop's does: compile + program upload happen outside
    the timer, so resumed Execution time is comparable to the unsegmented
    lane, which compiles before its timer too.
    """
    import jax.numpy as jnp

    runner = (
        engine.make_packed_segment_runner((height, width), config, mesh)
        if packed
        else engine.make_segment_runner((height, width), config, mesh, kernel)
    )
    gen0, counter0 = engine.resume_scalars(config, args.resume_gen)
    # Rebind, not discard: segment runners donate their state argument on
    # donating backends (engine jit_donating), so the zero-step call CONSUMES
    # `state` and hands back the identical carry in a fresh buffer.
    state, g, _, _ = runner(state, jnp.int32(gen0), jnp.int32(counter0),
                            jnp.int32(0))
    int(g)  # zero-step call: compile + program upload (the --warmup treatment)

    report = engine._REPORT[config.convention]

    def run_fn():
        final, gen, _counter, _stopped = runner(
            state, jnp.int32(gen0), jnp.int32(counter0), jnp.int32(config.gen_limit)
        )
        return final, report(int(gen))

    return run_fn


def _checkpoint_codec(args, variant, mesh, width, height):
    """Payload encoding for the checkpoint lane: the packed lane stores the
    bitpacked words (zarr when tensorstore is available — every host writes
    only its shards — else the packed text codec); the byte lane stores a
    text grid through the variant's own I/O strategy. All three are
    topology-independent, so checkpoints restore across mesh changes."""
    from gol_tpu.resilience.checkpoint import PayloadCodec

    if args.packed_io:
        from gol_tpu.io import packed_io, ts_store

        if ts_store.HAVE_TENSORSTORE:
            return PayloadCodec(
                format="zarr-words",
                suffix=".zarr",
                write=lambda path, state: ts_store.write_words(path, state, width),
                read=lambda path: ts_store.read_words(path, width, height, mesh),
                self_retrying=True,  # ts_store runs DEFAULT_IO_RETRY itself
            )
        return PayloadCodec(
            format="packed-text",
            suffix=".out",
            write=lambda path, state: packed_io.write_packed(path, state, width),
            read=lambda path: packed_io.read_packed(path, width, height, mesh),
        )
    return PayloadCodec(
        format="text-grid",
        suffix=".out",
        write=lambda path, state: _write_phase(variant, path, state),
        read=lambda path: _read_phase(variant, path, width, height, mesh),
    )


def _prepare_checkpointed(args, variant, config, mesh, state, height, width, *,
                          packed):
    """The crash-safe lane: --checkpoint-every writes an atomic checkpoint
    (fresh payload + manifest committed last; resilience/checkpoint.py) at
    every segment boundary, and --auto-resume restarts from the newest
    manifest every process can read — no --resume-gen arithmetic. Resumed
    runs are bit-exact with uninterrupted ones: the segmented loop carries
    the exact resume scalars (engine.resume_scalars), so the final output
    file and the reported Generations are byte-identical either way.
    """
    import jax.numpy as jnp

    from gol_tpu.resilience.checkpoint import CheckpointManager, run_fingerprint

    guard = None
    if getattr(args, "disk_reserve", 0):
        # The shed-checkpoints tier of the disk-pressure watchdog: ticked
        # at every save boundary, so a filling disk thins checkpoints
        # (loudly, counted) instead of killing the run with ENOSPC.
        from gol_tpu.resilience.diskguard import DiskGuard

        guard = DiskGuard(args.checkpoint_dir,
                          admission_bytes=args.disk_reserve)
    mgr = CheckpointManager(
        args.checkpoint_dir,
        height=height,
        width=width,
        codec=_checkpoint_codec(args, variant, mesh, width, height),
        keep=args.checkpoint_keep,
        guard=guard,
        # Fingerprinted on the INITIAL state (before any restore): a reused
        # checkpoint dir holding a different input's checkpoints must never
        # hand that run's state to this one.
        run_fingerprint=run_fingerprint(state, tag=config.convention),
    )
    completed = args.resume_gen
    if args.auto_resume:
        # Checkpoints past --gen-limit are skipped, mirroring the
        # --resume-gen validator: a rerun with a reduced limit resumes from
        # the newest checkpoint at or below it, or starts fresh.
        restored = mgr.restore(max_generation=config.gen_limit)
        if restored is not None:
            state, info = restored
            completed = info.generation

    runner = (
        engine.make_packed_segment_runner((height, width), config, mesh)
        if packed
        else engine.make_segment_runner((height, width), config, mesh, args.kernel)
    )
    gen0, counter0 = engine.resume_scalars(config, completed)
    # Rebind, not discard: segment runners donate their state argument on
    # donating backends, so this zero-step call CONSUMES `state` and returns
    # the identical carry in a fresh buffer (the donation-safe warm idiom).
    state, g, _, _ = runner(state, jnp.int32(gen0), jnp.int32(counter0),
                            jnp.int32(0))
    int(g)  # zero-step call: compile + program upload outside the timer

    segment = args.checkpoint_every or max(1, config.gen_limit)
    if packed:
        segments = lambda: engine.simulate_packed_segments(
            state, (height, width), config, mesh, segment, completed=completed
        )
    else:
        segments = lambda: engine.simulate_segments(
            state, config, mesh, args.kernel, segment, completed=completed
        )

    # The async writer (default): a boundary costs the device only the
    # device->host snapshot — payload write + fsync run on a background
    # thread while the next segment computes, and the manifest commits at
    # the NEXT boundary after draining that write (gol_tpu/pipeline/writer:
    # the iwrite/Wait-at-next-step discipline of src/game_mpi_async.c).
    # --sync-checkpoints keeps the fully synchronous path for A/B; both
    # produce bit-identical outputs and checkpoint payloads (test-pinned).
    use_async = bool(args.checkpoint_every) and not args.sync_checkpoints

    def run_fn():
        writer = None
        if use_async:
            from gol_tpu.pipeline.writer import AsyncCheckpointWriter

            writer = AsyncCheckpointWriter(mgr)
        try:
            final, generations = state, completed
            for generations, final, stopped in segments():
                if args.checkpoint_every and not stopped:
                    # Early-exited states are final output, not mid-run
                    # state — a checkpoint of one would replay as mid-run on
                    # resume and change the reported count (the --resume-gen
                    # caveat).
                    _, counter = engine.resume_scalars(config, generations)
                    if writer is not None:
                        writer.save(final, generations, counter)
                    else:
                        mgr.save(final, generations, counter)
            if writer is not None:
                # The final boundary's deferred wait: commit the last
                # pending checkpoint before the run reports success.
                writer.drain()
            return final, generations
        finally:
            if writer is not None:
                writer.close()  # join-on-exit, also on the error path

    return run_fn


def _profile_trace(profile_dir: str | None):
    """jax.profiler trace capture — the rich counterpart of the reference's
    three coarse phase timers (SURVEY.md §5 tracing).

    Rides obs.profiler.capture: start failures degrade to an unprofiled run
    (a run that exits on generation 0 — empty input — must not die because
    the profiler had nothing to capture), and a body that crashes
    mid-capture stops the profiler and sweeps the torn trace directory
    instead of leaving it looking like evidence."""
    from gol_tpu.obs import profiler

    return profiler.capture(profile_dir)


def _arm_observability(trace_dir: str | None):
    """``--trace DIR``: enable span tracing and the flight recorder.

    Returns an export thunk ``main`` calls when the lane ends (clean,
    error return, or crash unwind) — the Chrome trace JSON lands in DIR
    (open in Perfetto / chrome://tracing). A crash additionally gets the
    flight recorder's JSONL dump (same DIR, written at the injection/
    excepthook moment, so it exists even when the export can't run), and
    `gol trace-report` renders both artifact kinds."""
    if not trace_dir:
        return lambda: None
    from gol_tpu.obs import recorder, trace

    os.makedirs(trace_dir, exist_ok=True)
    trace.enable()
    recorder.install(trace_dir)

    def export():
        path = os.path.join(trace_dir, f"trace-{os.getpid()}.json")
        trace.export_chrome(path)
        print(f"trace -> {path}", file=sys.stderr)
        return path

    return export


def _snapshot_loop(args, config, runner, state0, segments, write_snapshot,
                   suffix=".out"):
    """Shared snapshotting driver: compile and init outside the timer.

    A zero-step segment call compiles the program and uploads it to the
    device (the --warmup treatment, done unconditionally here so segmented
    Execution time is comparable to the unsegmented lane, which compiles
    before its timer too). Each snapshot is a valid input file (the
    reference's only resume path, output-is-input, src/game.c:25-40 vs
    :154-165 — here it exists mid-run). Exec time covers the segmented loop
    including snapshot writes.
    """
    import os

    import jax.numpy as jnp

    gen0 = engine._GEN_START[config.convention]
    # Rebind, not discard: the runner donates its state argument on donating
    # backends (a zero-step call returns the carry unchanged, fresh buffer).
    state0, g, _, _ = runner(state0, jnp.int32(gen0), jnp.int32(0), jnp.int32(0))
    int(g)  # zero-step call: compile + program upload, no simulation

    outdir = args.snapshot_dir or "./snapshots"
    os.makedirs(outdir, exist_ok=True)

    def run_fn():
        final, generations = state0, 0
        for generations, final, _stopped in segments(state0):
            write_snapshot(
                os.path.join(outdir, f"gen_{generations:06d}{suffix}"), final
            )
        return final, generations

    return run_fn


def _prepare_segmented(args, variant, config, mesh, device_grid, height, width):
    runner = engine.make_segment_runner((height, width), config, mesh, args.kernel)
    return _snapshot_loop(
        args,
        config,
        runner,
        device_grid,
        lambda state: engine.simulate_segments(
            state, config, mesh, args.kernel, args.snapshot_every,
            completed=args.resume_gen,
        ),
        lambda path, state: _write_phase(variant, path, state),
    )


def _validate_lane_flags(args, lane: str) -> None:
    """Flags the pattern/sparse lanes cannot honor: both are single-device
    and snapshot-free, and a silently-ignored flag would misreport what
    ran. ``--kernel`` is deliberately NOT here — the dense pattern branch
    honors it; only the sparse engine rejects it (below)."""
    for flag, name in (
        (args.mesh, "--mesh"),
        (args.packed_io, "--packed-io"),
        (args.host, "--host"),
        (args.snapshot_every, "--snapshot-every"),
        (args.resume_gen, "--resume-gen"),
    ):
        if flag:
            raise ValueError(f"{name} does not apply to {lane}")
    if _checkpointing(args):
        raise ValueError(
            f"checkpointing is not supported on {lane}; the serve path "
            "replays sparse jobs from their journaled spec"
        )


def _validate_sparse_flags(args) -> None:
    _validate_lane_flags(args, "the sparse engine lane")
    if args.kernel != "auto":
        raise ValueError(
            "--kernel does not apply to the sparse engine lane (the tile "
            "step is its own kernel family)"
        )


def _validate_macro_flags(args) -> None:
    _validate_lane_flags(args, "the macro engine lane")
    if args.kernel != "auto":
        raise ValueError(
            "--kernel does not apply to the macro engine lane (leaf steps "
            "ride the sparse tile kernel family)"
        )


def _parse_universe(spec: str) -> tuple[int, int]:
    m = re.fullmatch(r"(\d+)x(\d+)", spec)
    if not m:
        raise ValueError(f"--universe must look like WxH, got {spec!r}")
    return int(m.group(1)), int(m.group(2))  # (width, height)


def _parse_place(spec: str) -> tuple[int, int]:
    m = re.fullmatch(r"(-?\d+),(-?\d+)", spec)
    if not m:
        raise ValueError(f"--place must look like X,Y, got {spec!r}")
    return int(m.group(1)), int(m.group(2))  # (x=column, y=row)


def _run_sparse(variant, config, board, read_ms, output_path) -> int:
    """Drive a sparse simulation and write the result as RLE (a giant
    universe's dense text grid must never be written), keeping the
    reference's printed contract."""
    from gol_tpu.sparse import TileMemo, simulate_sparse

    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")
    t0 = time.perf_counter()
    result = simulate_sparse(board, config, TileMemo())
    exec_ms = (time.perf_counter() - t0) * 1000
    comments = (
        f"generations {result.generations} exit {result.exit_reason}",
    )
    return _report_and_write(
        variant,
        result.generations,
        exec_ms,
        lambda: _write_text(output_path, result.board.to_rle(comments)),
    )


def _run_macro(args, variant, config, board, read_ms, output_path) -> int:
    """Drive a macrocell simulation (gol_tpu/macro) and write the result
    as RLE — same output contract as the sparse lane, because the result
    is byte-identical by construction; only the generation count scales
    differently (O(log gens) guarded jumps)."""
    from gol_tpu.macro import MacroMemo, NodeStore, simulate_macro

    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")
    memo = MacroMemo(NodeStore(board.tile), cas_dir=args.macro_cas)
    t0 = time.perf_counter()
    result = simulate_macro(board, config, memo)
    exec_ms = (time.perf_counter() - t0) * 1000
    comments = (
        f"generations {result.generations} exit {result.exit_reason}",
    )
    return _report_and_write(
        variant,
        result.generations,
        exec_ms,
        lambda: _write_text(output_path, result.board.to_rle(comments)),
    )


def _run_shard(args, variant, config, pattern, x, y, height, width, tile,
               read_ms) -> int:
    """``--engine shard``: submit the pattern as ONE sharded job to a
    fleet router (gol_tpu/shard) and poll it home. The printed contract
    and the written RLE are byte-identical to the sparse lane's — the
    sharded engine's core promise — only the execution spans N workers."""
    from gol_tpu.fleet import client as fleet_client
    from gol_tpu.io import rle as rle_codec
    from gol_tpu.sparse.board import SparseBoard

    router = args.shard_across.rstrip("/")
    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")
    body = {
        "shard": True,
        "rle": rle_codec.encode(pattern),
        "x": x, "y": y, "width": width, "height": height, "tile": tile,
        "convention": config.convention,
        "gen_limit": config.gen_limit,
        "check_similarity": config.check_similarity,
        "similarity_frequency": config.similarity_frequency,
    }
    t0 = time.perf_counter()
    status, payload = fleet_client.http_json(
        "POST", f"{router}/jobs", body, timeout=120)
    if status != 202:
        raise ValueError(
            f"shard submit rejected: HTTP {status} {payload}"
        )
    job_id = payload["id"]
    while True:
        status, job = fleet_client.http_json(
            "GET", f"{router}/jobs/{job_id}", timeout=30)
        if status != 200:
            raise ValueError(
                f"shard job poll failed: HTTP {status} {job}"
            )
        if job.get("state") in ("done", "failed"):
            break
        time.sleep(0.1)
    if job["state"] == "failed":
        raise ValueError(
            f"shard job failed: {job.get('error', 'unknown error')}"
        )
    status, result = fleet_client.http_json(
        "GET", f"{router}/result/{job_id}", timeout=300)
    if status != 200:
        raise ValueError(f"shard result fetch failed: HTTP {status}")
    exec_ms = (time.perf_counter() - t0) * 1000
    generations = int(result["generations"])
    comments = (
        f"generations {generations} exit {result['exit_reason']}",
    )
    # Round-trip through SparseBoard: validates the merged document and
    # re-emits it through the same encoder as the sparse lane, so the
    # written file is byte-identical to a single-worker run's.
    board = SparseBoard.from_rle(result["rle"], height=height,
                                 width=width, tile=tile)
    output_path = args.output or "./sparse_output.rle"
    return _report_and_write(
        variant,
        generations,
        exec_ms,
        lambda: _write_text(output_path, board.to_rle(comments)),
    )


def _write_text(path: str, text: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(text)


def _run_pattern(args, variant) -> int:
    """``--pattern FILE [--place X,Y] [--universe WxH]``: the RLE input
    lane. Board construction is geometry-first — only the tiles the
    pattern touches are allocated — so the engine choice (``--engine``,
    default auto: sparse above the area threshold) happens before any
    canvas could exist."""
    from gol_tpu.io import rle as rle_codec
    from gol_tpu.sparse.board import (
        DEFAULT_TILE,
        SparseBoard,
        dense_cells_guard,
    )

    if args.input_file is not None:
        raise ValueError("--pattern replaces the input file argument")
    _validate_lane_flags(args, "the --pattern lane")
    config = GameConfig(
        gen_limit=args.gen_limit,
        check_similarity=not args.no_check_similarity,
        similarity_frequency=args.similarity_frequency,
        convention=variant.convention,
    )
    t0 = time.perf_counter()
    with open(args.pattern, "r", encoding="utf-8") as f:
        pattern = rle_codec.parse(f.read())
    read_ms = (time.perf_counter() - t0) * 1000
    ph, pw = pattern.shape
    if args.universe:
        width, height = _parse_universe(args.universe)
    else:
        width, height = pw, ph
    x, y = _parse_place(args.place)
    tile = args.tile or DEFAULT_TILE
    engine_pick = args.engine
    if engine_pick == "auto":
        from gol_tpu.sparse.engine import auto_engine

        engine_pick = auto_engine(height, width, tile)
        if engine_pick == "sparse":
            # A sparse-routed auto run upgrades to the macrocell lane when
            # the generation count clears the crossover AND the placement
            # provably keeps the whole run off the torus seam (auto must
            # never pick an engine that can raise mid-run). Byte-identical
            # either way — this only changes how fast the answer arrives.
            from gol_tpu.macro import auto_macro

            if auto_macro(height, width, tile, config.gen_limit,
                          (y, x, y + ph - 1, x + pw - 1)):
                engine_pick = "macro"
    if engine_pick == "shard":
        if args.kernel != "auto":
            raise ValueError(
                "--kernel does not apply to the shard engine (the "
                "workers' tile step is its own kernel family)"
            )
        return _run_shard(args, variant, config, pattern, x, y,
                          height, width, tile, read_ms)
    if engine_pick in ("sparse", "macro"):
        if args.kernel != "auto":
            raise ValueError(
                "--kernel does not apply to the sparse engine (the tile "
                "step is its own kernel family); add --engine dense to "
                "force the dense lane"
            )
        board = SparseBoard.from_pattern(pattern, x, y, height, width, tile)
        output_path = args.output or "./sparse_output.rle"
        if engine_pick == "macro":
            return _run_macro(args, variant, config, board, read_ms,
                              output_path)
        return _run_sparse(variant, config, board, read_ms, output_path)
    # Dense engine on a pattern input: materialize (guarded), place, run
    # the classic device lane.
    dense_cells_guard(height, width, what="universe")
    if x < 0 or y < 0 or y + ph > height or x + pw > width:
        raise ValueError(
            f"pattern {ph}x{pw} at ({x},{y}) does not fit the "
            f"{height}x{width} universe"
        )
    grid = np.zeros((height, width), np.uint8)
    grid[y:y + ph, x:x + pw] = pattern
    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")
    device_grid = engine.put_grid(grid)
    runner = engine.make_runner((height, width), config, None, args.kernel)
    compiled = engine.compile_runner(runner, device_grid)
    t0 = time.perf_counter()
    final, gen = compiled(device_grid)
    generations = int(gen)
    exec_ms = (time.perf_counter() - t0) * 1000
    output_path = args.output or f"./{variant.output_file}"
    return _report_and_write(
        variant,
        generations,
        exec_ms,
        lambda: text_grid.write_grid(output_path,
                                     np.asarray(final, dtype=np.uint8)),
    )


def _run_sparse_file(args, variant, config, width, height) -> int:
    """``--engine sparse`` over a dense input file (the A/B lane: the same
    file the dense engine reads, simulated tile-wise — byte-gating the
    sparse lane against the dense one from the CLI)."""
    from gol_tpu.sparse.board import (
        DEFAULT_TILE,
        SparseBoard,
        dense_cells_guard,
    )

    dense_cells_guard(height, width, what="input file")
    t0 = time.perf_counter()
    grid = text_grid.read_grid(args.input_file, width, height)
    read_ms = (time.perf_counter() - t0) * 1000
    board = SparseBoard.from_dense(grid, args.tile or DEFAULT_TILE)
    output_path = args.output or "./sparse_output.rle"
    return _run_sparse(variant, config, board, read_ms, output_path)


def _run_macro_file(args, variant, config, width, height) -> int:
    """``--engine macro`` over a dense input file: the same A/B lane as
    ``_run_sparse_file``, driven through the macrocell tree."""
    from gol_tpu.sparse.board import (
        DEFAULT_TILE,
        SparseBoard,
        dense_cells_guard,
    )

    dense_cells_guard(height, width, what="input file")
    t0 = time.perf_counter()
    grid = text_grid.read_grid(args.input_file, width, height)
    read_ms = (time.perf_counter() - t0) * 1000
    board = SparseBoard.from_dense(grid, args.tile or DEFAULT_TILE)
    output_path = args.output or "./sparse_output.rle"
    return _run_macro(args, variant, config, board, read_ms, output_path)


def _run_host(args, variant, config, width, height, output_path) -> int:
    """--host: the NumPy oracle path, no accelerator involved.

    Prints exactly the lines the variant would print on device — including
    the Reading/Writing lines of io_timings variants
    (src/game_mpi_collective.c:200-203,447-450) — so host-vs-device output
    is line-for-line comparable."""
    from gol_tpu.sparse.board import dense_cells_guard

    dense_cells_guard(height, width)
    t0 = time.perf_counter()
    grid = text_grid.read_grid(args.input_file, width, height)
    read_ms = (time.perf_counter() - t0) * 1000
    if variant.io_timings:
        print(f"Reading file:\t{read_ms:.2f} msecs")
    t0 = time.perf_counter()
    result = oracle.run(grid, config)
    exec_ms = (time.perf_counter() - t0) * 1000
    return _report_and_write(
        variant,
        result.generations,
        exec_ms,
        lambda: text_grid.write_grid(output_path, result.grid),
    )


def _show(args) -> int:
    """Render a grid file with the reference's VT100 codes (src/game.c:42-58);
    --animate evolves it live on the host oracle."""
    from gol_tpu import render

    width, height = atoi(args.width), atoi(args.height)
    if width <= 0:
        width = DEFAULT_WIDTH
    if height <= 0:
        height = DEFAULT_HEIGHT
    grid = text_grid.read_grid(args.input_file, width, height)
    if args.animate:
        render.animate(grid, args.animate, fps=args.fps)
    else:
        render.show(grid)
    return 0


def _serve(args) -> int:
    """``gol serve``: the batched multi-tenant simulation service.

    Boots the HTTP API (gol_tpu/serve/server.py) over the journaled
    scheduler. SIGTERM/SIGINT drain gracefully: admission stops, queued
    buckets flush, in-flight batches finish, then the process exits — no
    accepted job is lost (the journal replays any that were cut off).

    ``--compile-cache`` persists XLA/Mosaic compiles across restarts;
    ``--warm-plans`` pre-compiles the bucket programs of every shape the
    offline tuner (`gol tune`) recorded, so tuned fleets pay neither
    compile on the first request after a restart."""
    import signal

    from gol_tpu.platform_env import enable_compile_cache
    from gol_tpu.resilience import faults

    enable_compile_cache(args.compile_cache)

    # The subprocess fault harness (GOL_FAULTS crosses the exec boundary,
    # flags don't): the storage chaos matrix drives a REAL serve process
    # into ENOSPC/SIGKILL-mid-compaction this way. Unset, this clears any
    # plan a previous in-process run armed — same contract as `gol run`.
    faults.install(faults.FaultPlan.from_env())

    from gol_tpu.serve.server import GolServer

    if args.flush_age < 0:
        raise ValueError(f"--flush-age must be >= 0, got {args.flush_age}")
    if args.warm_plans:
        _warm_plans()
    if args.slo_latency_p99 <= 0:
        raise ValueError(
            f"--slo-latency-p99 must be > 0, got {args.slo_latency_p99}"
        )
    if args.cache_entries < 1:
        raise ValueError(
            f"--cache-entries must be >= 1, got {args.cache_entries}"
        )
    if args.cache_disk_bytes is not None and args.cache_disk_bytes < 1:
        raise ValueError(
            f"--cache-disk-bytes must be >= 1, got {args.cache_disk_bytes}"
        )
    if args.journal_segment_bytes is not None \
            and args.journal_segment_bytes < 0:
        raise ValueError(
            f"--journal-segment-bytes must be >= 0, got "
            f"{args.journal_segment_bytes}"
        )
    if args.journal_retain is not None and args.journal_retain < 1:
        raise ValueError(
            f"--journal-retain must be >= 1, got {args.journal_retain}"
        )
    if args.disk_reserve < 0:
        raise ValueError(
            f"--disk-reserve must be >= 0, got {args.disk_reserve}"
        )
    if args.disk_reserve and not args.journal_dir:
        raise ValueError(
            "--disk-reserve watches the journal partition; pass "
            "--journal-dir (a journal-less server has no durable state "
            "to protect)"
        )
    # --result-cache with a journal but no explicit --cache-dir puts the
    # CAS tier beside the journal: restarts (and fleet worker partitions,
    # which forward --result-cache verbatim) keep their durable tier with
    # zero extra flags. No journal and no --cache-dir = memory-only.
    cache_dir = args.cache_dir
    if args.result_cache and cache_dir is None and args.journal_dir:
        cache_dir = os.path.join(args.journal_dir, "cache")
    # --metrics-history with no DIR rides the journal partition (the fleet
    # lane: every worker's history lands beside its journal with zero
    # extra flags); bare --metrics-history without a journal needs an
    # explicit DIR — there is nowhere durable to default to.
    history_dir = args.metrics_history
    if history_dir == "auto":
        if not args.journal_dir:
            raise ValueError(
                "--metrics-history needs a DIR (or --journal-dir, whose "
                "partition hosts the default <journal-dir>/history)"
            )
        history_dir = os.path.join(args.journal_dir, "history")
    if history_dir and args.sample_interval <= 0:
        # The history ring is fed by the sampler thread; with the sampler
        # disabled the ring would mount and then silently stay empty —
        # exactly the record an incident review would reach for and not
        # find. Refuse the combination instead.
        raise ValueError(
            "--metrics-history is fed by the background sampler; "
            f"--sample-interval must be > 0 (got {args.sample_interval})"
        )
    if args.history_bytes is not None and args.history_bytes < 4096:
        raise ValueError(
            f"--history-bytes must be >= 4096, got {args.history_bytes}"
        )
    if args.retry_budget < 0:
        raise ValueError(
            f"--retry-budget must be >= 0, got {args.retry_budget}"
        )
    scheduler_kwargs = {}
    if args.retry_budget:
        # The dispatch-retry token bucket (resilience/retry.RetryBudget):
        # N tokens of capacity, refilled over a minute — under a brownout
        # the scheduler degrades to first-attempt-only dispatch instead
        # of amplifying the overload with retry traffic. 0 (default) =
        # unlimited, the pre-budget behavior.
        from gol_tpu.resilience.retry import RetryBudget

        scheduler_kwargs["retry_budget"] = RetryBudget(
            capacity=args.retry_budget,
            refill_per_s=args.retry_budget / 60.0,
        )
    server = GolServer(
        host=args.host,
        port=args.port,
        journal_dir=args.journal_dir,
        max_queue_depth=args.max_queue_depth,
        max_batch=args.max_batch,
        flush_age=args.flush_age,
        max_inflight=args.max_inflight,
        pipeline_depth=args.pipeline_depth,
        resident_ring=args.resident_ring,
        slo_shed=args.slo_shed,
        slo_latency_target=args.slo_latency_p99,
        sample_interval=args.sample_interval,
        result_cache=args.result_cache,
        cache_dir=cache_dir,
        cache_entries=args.cache_entries,
        cache_payload=args.cache_payload,
        cache_disk_bytes=args.cache_disk_bytes,
        journal_segment_bytes=args.journal_segment_bytes,
        journal_retain=args.journal_retain,
        disk_reserve=args.disk_reserve,
        history_dir=history_dir,
        history_bytes=args.history_bytes,
        **scheduler_kwargs,
    )
    stop = {"signaled": False}

    def _on_signal(signum, frame):
        # Second signal: exit hard (the journal still replays on restart).
        if stop["signaled"]:
            raise SystemExit(1)
        stop["signaled"] = True
        import threading

        threading.Thread(
            target=lambda: (server.shutdown(drain=True)), daemon=True
        ).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(f"serving on {server.url}", flush=True)
    if server.replayed:
        print(f"replayed {server.replayed} unfinished job(s) from the journal",
              flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    # A second signal raises SystemExit(1) in the main thread (the hard-exit
    # path) — it must PROPAGATE so supervisors see a non-zero status for an
    # aborted drain, not a clean 0.
    return 0


def _journal_partitions(directory: str) -> list[str]:
    """Journal directories under ``directory``: itself when it IS one, else
    every immediate subdirectory holding journal state — the fleet-dir
    shape, where each worker partition compacts independently."""
    from gol_tpu.serve import compaction

    def is_partition(d):
        return (
            os.path.exists(os.path.join(d, compaction.ACTIVE_FILENAME))
            or os.path.exists(compaction.snapshot_path(d))
            or bool(compaction.sealed_segments(d))
        )

    if is_partition(directory):
        return [directory]
    try:
        subdirs = sorted(
            os.path.join(directory, name) for name in os.listdir(directory)
        )
    except OSError as err:
        raise ValueError(f"cannot read {directory}: {err}") from None
    return [d for d in subdirs if os.path.isdir(d) and is_partition(d)]


def _compact_cmd(args) -> int:
    """``gol compact``: offline journal compaction — fold sealed segments
    into the CRC-stamped snapshot and retire them (the same pass a serving
    worker runs on idle sampler ticks). Accepts a journal directory OR a
    fleet directory, whose partitions compact independently."""
    from gol_tpu.serve import compaction

    partitions = _journal_partitions(args.dir)
    if not partitions:
        raise ValueError(f"no journal state under {args.dir}")
    for directory in partitions:
        report = compaction.compact(directory, retain_results=args.retain)
        print(
            f"{directory}: "
            + (f"compacted {report.segments_retired} segment(s) -> "
               f"snapshot ({report.records_kept} records"
               + (f", {report.terminal_dropped} old result(s) dropped"
                  if report.terminal_dropped else "")
               + f"), {report.bytes_before} -> {report.bytes_after} bytes"
               if report.compacted else
               f"nothing to compact ({report.bytes_after} bytes"
               + (f"; swept {report.segments_retired} stale segment(s)"
                  if report.segments_retired else "") + ")")
        )
    return 0


def _gc_cmd(args) -> int:
    """``gol gc``: CAS garbage collection — sweep orphans and evict
    least-recently-used entries to a byte budget. DRY-RUN by default
    (prints what would happen); --apply deletes. Eviction is always safe:
    the CAS is a cache, the journal stays the source of truth."""
    from gol_tpu.cache import gc as cas_gc

    if not os.path.isdir(args.dir):
        raise ValueError(f"no such cache directory: {args.dir}")
    if args.budget is not None and args.budget < 0:
        raise ValueError(f"--budget must be >= 0, got {args.budget}")
    report = cas_gc.collect(args.dir, args.budget, apply=args.apply)
    verb = "removed" if args.apply else "would remove"
    print(f"{args.dir}: {report.entries} entr(ies), "
          f"{report.bytes_total} bytes"
          + (f" (budget {report.budget})" if report.budget is not None
             else ""))
    print(f"  {verb} {len(report.orphans)} orphan(s) "
          f"({report.orphan_bytes} bytes)")
    for path in report.orphans:
        print(f"    {path}")
    verb = "evicted" if args.apply else "would evict"
    print(f"  {verb} {len(report.evicted)} entr(ies) "
          f"({report.evicted_bytes} bytes, LRU first)")
    for fp in report.evicted:
        print(f"    {fp}")
    print(f"  after: {report.bytes_after} bytes"
          + ("" if args.apply else " (dry run; pass --apply to delete)"))
    return 0


def _fleet(args) -> int:
    """``gol fleet``: the sharded serving fleet — router + N workers.

    Spawns ``--workers`` local ``gol serve`` subprocesses (each on its own
    journal partition under ``--fleet-dir``) and/or attaches externally
    managed workers by ``--attach URL`` (the multi-host lane: boot workers
    wherever ``parallel/bootstrap.py`` put the devices, hand the router
    their URLs), then serves the single-server HTTP job API unchanged
    behind bucket-consistent routing (gol_tpu/fleet/).

    Restart story: started on a ``--fleet-dir`` holding a manifest, the
    router reattaches workers that are still alive and respawns dead local
    partitions, whose journals replay to exactly-once — killing the router
    loses nothing. SIGTERM/SIGINT cascade a fleet-wide graceful drain:
    admission stops at the router, every worker drains, local workers get
    SIGTERM, then the router exits."""
    import signal
    import subprocess

    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet, core_slice_prefix

    if args.workers < 0:
        raise ValueError(f"--workers must be >= 0, got {args.workers}")
    if args.flush_age < 0:
        raise ValueError(f"--flush-age must be >= 0, got {args.flush_age}")
    if args.health_interval <= 0:
        raise ValueError(
            f"--health-interval must be > 0, got {args.health_interval}"
        )
    # The worker-side --metrics-history/--history-bytes rules, enforced
    # BEFORE any worker spawns: forwarding a value every worker will
    # reject at its own argv parse would boot-crash the whole fleet and
    # surface as a raw _await_ready RuntimeError instead of the CLI's
    # `gol: <error>` contract.
    if args.metrics_history and args.sample_interval <= 0:
        raise ValueError(
            "--metrics-history is fed by each worker's background "
            f"sampler; --sample-interval must be > 0 "
            f"(got {args.sample_interval})"
        )
    if args.history_bytes is not None and args.history_bytes < 4096:
        raise ValueError(
            f"--history-bytes must be >= 4096, got {args.history_bytes}"
        )
    if args.cores_per_worker < 0:
        raise ValueError(
            f"--cores-per-worker must be >= 0, got {args.cores_per_worker}"
        )
    if args.cores_per_worker > (os.cpu_count() or args.cores_per_worker):
        # Validated BEFORE any worker spawns (the history-flags contract):
        # taskset fails outright on a range naming CPUs the host lacks,
        # and every worker would boot-crash with a raw log tail instead
        # of a `gol:` error.
        raise ValueError(
            f"--cores-per-worker {args.cores_per_worker} exceeds the "
            f"host's {os.cpu_count()} cores"
        )
    if args.breaker_cooldown < 0:
        raise ValueError(
            f"--breaker-cooldown must be >= 0, got {args.breaker_cooldown}"
        )
    if args.routers < 1:
        raise ValueError(f"--routers must be >= 1, got {args.routers}")
    if args.retry_budget < 0:
        # Validated BEFORE any worker spawns (the history-flags contract):
        # forwarded verbatim, a negative budget boot-crashes every worker
        # long after launch instead of erroring here.
        raise ValueError(
            f"--retry-budget must be >= 0, got {args.retry_budget}"
        )
    # Storage-lifecycle flags: same validated-before-spawn contract.
    if args.cache_disk_bytes is not None and args.cache_disk_bytes < 1:
        raise ValueError(
            f"--cache-disk-bytes must be >= 1, got {args.cache_disk_bytes}"
        )
    if args.journal_segment_bytes is not None \
            and args.journal_segment_bytes < 0:
        raise ValueError(
            f"--journal-segment-bytes must be >= 0, got "
            f"{args.journal_segment_bytes}"
        )
    if args.journal_retain is not None and args.journal_retain < 1:
        raise ValueError(
            f"--journal-retain must be >= 1, got {args.journal_retain}"
        )
    if args.disk_reserve < 0:
        raise ValueError(
            f"--disk-reserve must be >= 0, got {args.disk_reserve}"
        )
    if args.chaos:
        # Parsed up front so a typo'd plan is a `gol: <error>` before any
        # worker spawns — and so the boot banner can echo the armed plan.
        from gol_tpu.chaos import ChaosPlan

        ChaosPlan.parse(args.chaos)
    # Autoscaler bounds resolve against --workers; AutoscaleConfig's own
    # validation (min >= 1, max >= min, threshold ordering) runs HERE,
    # before any worker spawns — same contract as the history flags.
    autoscale_cfg = None
    if args.autoscale:
        from gol_tpu.fleet.autoscale import AutoscaleConfig

        min_workers = (args.min_workers if args.min_workers is not None
                       else max(1, args.workers))
        max_workers = (args.max_workers if args.max_workers is not None
                       else max(4, args.workers))
        autoscale_cfg = AutoscaleConfig(
            min_workers=min_workers,
            max_workers=max_workers,
            up_saturation=args.scale_up_saturation,
            up_sustain=args.scale_up_sustain,
            down_occupancy=args.scale_down_occupancy,
            down_sustain=args.scale_down_sustain,
            cooldown_s=args.scale_cooldown,
        )
    elif args.min_workers is not None or args.max_workers is not None:
        raise ValueError("--min-workers/--max-workers need --autoscale")
    # Worker flags forwarded verbatim to every spawned `gol serve` —
    # including --warm-plans, so a tuned fleet pre-compiles each worker's
    # bucket programs (and the plan cache is shared via GOL_PLAN_CACHE /
    # the default cache path, exactly as for a single server).
    serve_args = [
        "--max-queue-depth", str(args.max_queue_depth),
        "--max-batch", str(args.max_batch),
        "--flush-age", str(args.flush_age),
        "--pipeline-depth", str(args.pipeline_depth),
        "--slo-latency-p99", str(args.slo_latency_p99),
        "--sample-interval", str(args.sample_interval),
    ]
    if args.retry_budget:
        serve_args += ["--retry-budget", str(args.retry_budget)]
    if args.resident_ring:
        serve_args += ["--resident-ring", str(args.resident_ring)]
    if args.warm_plans:
        serve_args += ["--warm-plans"]
    if args.compile_cache:
        serve_args += ["--compile-cache", args.compile_cache]
    if args.slo_shed:
        serve_args += ["--slo-shed"]
    if args.result_cache:
        # Each worker's CAS tier lands on its own journal partition
        # (--result-cache + --journal-dir defaults --cache-dir to
        # <partition>/cache): with --cache-route, a fingerprint's HRW owner
        # IS the worker whose partition holds its cache shard.
        serve_args += ["--result-cache"]
    if args.trace:
        # Every worker arms its own tracer on the SHARED directory
        # (exports/flight dumps are pid-qualified, so processes never
        # collide); the router's own arming rides main()'s --trace hook.
        serve_args += ["--trace", args.trace]
    if args.metrics_history:
        # Bare --metrics-history on a worker resolves to its journal
        # partition (<partition>/history) — per-process rings, exactly
        # like the journal and the CAS tier.
        serve_args += ["--metrics-history"]
        if args.history_bytes is not None:
            serve_args += ["--history-bytes", str(args.history_bytes)]
    # Storage-lifecycle flags, forwarded verbatim: every partition rotates,
    # compacts, budgets its CAS, and watches its own free bytes
    # INDEPENDENTLY — one full-disk partition 507s alone while the rest of
    # the fleet serves.
    if args.cache_disk_bytes is not None:
        serve_args += ["--cache-disk-bytes", str(args.cache_disk_bytes)]
    if args.journal_segment_bytes is not None:
        serve_args += ["--journal-segment-bytes",
                       str(args.journal_segment_bytes)]
    if args.journal_retain is not None:
        serve_args += ["--journal-retain", str(args.journal_retain)]
    if args.disk_reserve:
        serve_args += ["--disk-reserve", str(args.disk_reserve)]

    # --cores-per-worker: pin worker k to its own equal `taskset` slice
    # (the fixed per-worker budget of a one-worker-per-device deployment,
    # on a shared host) and weight it for --affinity placement. Autoscaled
    # spawns ride the same hook, so new workers land on distinct slices.
    spawn_prefix = None
    spawn_weight = None
    if args.cores_per_worker:
        spawn_prefix = core_slice_prefix(args.cores_per_worker)
        spawn_weight = float(args.cores_per_worker)

    from gol_tpu.fleet import replicate

    fleet = Fleet(args.fleet_dir, serve_args=serve_args,
                  spawn_prefix=spawn_prefix, spawn_weight=spawn_weight)
    recovered = fleet.load()
    if recovered:
        print(f"reattached {recovered} worker partition(s) from "
              f"{fleet.manifest_path}", flush=True)
    # This invocation's flags become the manifest's `config` block — the
    # single source of truth a `gol router` replica boots from (set AFTER
    # load(), so the operator's current flags supersede a stale block).
    fleet.manifest_config = {
        "serve_args": serve_args,
        "health_interval": args.health_interval,
        "big_edge": args.big_edge,
        "cache_route": bool(args.cache_route),
        "affinity": bool(args.affinity),
        "breakers": not args.no_breakers,
        "breaker_cooldown": args.breaker_cooldown,
        "breaker_slow": args.breaker_slow,
        "max_queue_depth": args.max_queue_depth,
        "cores_per_worker": args.cores_per_worker,
        "autoscale": (dataclasses.asdict(autoscale_cfg)
                      if autoscale_cfg is not None else None),
    }
    # Arm the leader lease BEFORE spawning: normally this primary wins
    # immediately, but if a surviving replica of a previous incarnation
    # still holds the lock, the restarted primary joins as a follower for
    # the single-writer ticks (it still performs this boot's operator-
    # initiated spawns — the flock serializes the manifest writes).
    fleet.enable_leader_election(label="r0")
    for url in args.attach or []:
        fleet.attach(url)
    fleet.spawn_fleet(args.workers, big_lane=args.big_lane)
    if not fleet.workers():
        raise ValueError(
            "fleet has no workers: pass --workers N and/or --attach URL"
        )
    fleet.write_manifest()  # persist the config block even when nothing spawned
    fleet.start_health(args.health_interval)
    # The chaos-hardened data path (PR 14): breakers default ON for the
    # CLI fleet (the library RouterServer default stays off/byte-identical
    # for embedders and old tests); --chaos mounts the fault-injecting
    # proxy pool on the router->worker data path. Breaker transitions land
    # in a durable ring beside the autoscaler's decisions.
    chaos_pool = None
    if args.chaos:
        from gol_tpu.chaos import ChaosPlan, ProxyPool

        chaos_pool = ProxyPool(ChaosPlan.parse(args.chaos))
        # Respawns move workers to fresh ports; every health tick drops
        # the proxies (listener socket + accept thread each) still
        # fronting the dead ones.
        fleet.add_tick_hook(
            lambda: chaos_pool.prune(w.url for w in fleet.workers())
        )
        print(f"chaos: fault injection ARMED on the router->worker data "
              f"path ({args.chaos})", flush=True)
    breaker_kwargs = {}
    if not args.no_breakers:
        from gol_tpu.fleet.breaker import BreakerConfig
        from gol_tpu.obs.history import HistoryWriter as _BreakerRing

        breaker_kwargs = {
            "breakers": True,
            "breaker_config": BreakerConfig(
                cooldown_s=args.breaker_cooldown,
                slow_s=args.breaker_slow if args.breaker_slow > 0 else None,
            ),
            # Per-ROUTER ring (PR 16): each replica is the single writer
            # of its own `<fleet-dir>/routers/<id>/breaker-history`, and
            # warm-start merges across all of them.
            "breaker_history": _BreakerRing(
                os.path.join(replicate.state_dir(args.fleet_dir, "r0"),
                             replicate.BREAKER_RING),
                source="breaker",
            ),
        }
    router = RouterServer(fleet, host=args.host, port=args.port,
                          big_edge=args.big_edge,
                          cache_route=args.cache_route,
                          affinity_route=args.affinity,
                          chaos=chaos_pool,
                          router_id="r0",
                          state_dir=replicate.state_dir(args.fleet_dir, "r0"),
                          **breaker_kwargs)
    if not args.no_breakers:
        # Same cadence as the chaos-proxy prune: a retired worker's
        # breaker (and its state gauge) leaves with its membership row.
        fleet.add_tick_hook(router.prune_breakers)
    if autoscale_cfg is not None:
        from gol_tpu.fleet.autoscale import Autoscaler
        from gol_tpu.obs.history import HistoryWriter

        # Every decision lands in a PR-10 durable ring beside the router's
        # — `gol history-report` and the bench suite replay why the fleet
        # grew. The tick rides the health loop: one cadence, and the /slo
        # payloads the loop fetched this tick ARE the burn signal.
        autoscaler = Autoscaler(
            fleet, router, autoscale_cfg,
            queue_capacity=args.max_queue_depth,
            history=HistoryWriter(
                os.path.join(args.fleet_dir, "autoscaler-history"),
                source="autoscaler",
            ),
        )
        router.autoscaler = autoscaler
        fleet.add_tick_hook(autoscaler.tick)
        print(f"autoscaler: {autoscale_cfg.min_workers}"
              f"..{autoscale_cfg.max_workers} workers "
              f"(up at {autoscale_cfg.up_saturation:.2f} saturation or "
              f"SLO-critical burn, down below "
              f"{autoscale_cfg.down_occupancy:.2f} occupancy, "
              f"{autoscale_cfg.cooldown_s:.0f}s cooldown)", flush=True)
    if args.metrics_history:
        # The router's durable record is the fleet-MERGED snapshot, floored
        # by MonotonicCounters — the series an incident review replays stay
        # monotonic through every worker respawn in the window.
        router.start_history(
            os.path.join(args.fleet_dir, "router-history"),
            interval=args.sample_interval,  # validated > 0 above
            total_bytes=args.history_bytes,
        )
    # --routers N: N-1 extra `gol router` replica subprocesses over the
    # same --fleet-dir. Replicas are the horizontal CONTROL plane: each
    # serves the full job API from the shared manifest, contests the
    # leader lease, and inherits the durable floors/breaker state — so no
    # single router process is a SPOF. They are deliberately NOT
    # supervised (no respawn-the-router loop: the operator's init system
    # owns router lifetimes; the fleet only guarantees any survivor can
    # carry the whole control plane).
    replicas: list = []
    for k in range(1, args.routers):
        rid = f"r{k}"
        rdir = replicate.state_dir(args.fleet_dir, rid)
        os.makedirs(rdir, exist_ok=True)
        log_path = os.path.join(rdir, "log")
        log_f = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "gol_tpu", "router",
                 "--fleet-dir", args.fleet_dir,
                 "--router-id", rid, "--port", "0"],
                stdout=log_f, stderr=subprocess.STDOUT,
                env={**os.environ, "PYTHONUNBUFFERED": "1"},
            )
        finally:
            log_f.close()
        replicas.append(proc)
        print(f"router replica {rid} pid={proc.pid} (log: {log_path})",
              flush=True)

    stop = {"signaled": False}

    def _on_signal(signum, frame):
        # Second signal: exit hard (workers' journals replay on restart).
        if stop["signaled"]:
            raise SystemExit(1)
        stop["signaled"] = True
        import threading

        def _cascade():
            # Replicas go FIRST: they hold no worker processes, and
            # stopping them before the workers drain means no replica
            # wins the lease mid-cascade and starts "supervising" the
            # teardown it cannot see.
            for proc in replicas:
                if proc.poll() is None:
                    proc.terminate()
            router.shutdown(cascade=True)
            for proc in replicas:
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()

        threading.Thread(target=_cascade, daemon=True).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    roster = ", ".join(f"{w.id}={w.url}" for w in fleet.workers())
    print(f"fleet router on {router.url} "
          f"({len(fleet.workers())} workers: {roster})", flush=True)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _router(args) -> int:
    """``gol router``: one attachable router replica over a running fleet.

    Boots from the shared manifest alone (`--fleet-dir` is the only
    coordination channel): adopts the membership and the `config` block
    the primary recorded, inherits the durable counter floors and breaker
    evidence under ``<fleet-dir>/routers/``, and contests the leader
    lease. While following it routes, forwards, and serves lookups like
    any replica (active-active data plane); if the leader dies, the
    kernel drops the flock and the next health tick here picks up the
    single-writer ticks (respawn supervision, scale decisions).

    SIGTERM/SIGINT stop THIS replica only (``cascade=False``): workers
    belong to the fleet, not to any one router."""
    import signal

    from gol_tpu.fleet import replicate
    from gol_tpu.fleet.router import RouterServer
    from gol_tpu.fleet.workers import Fleet, core_slice_prefix

    if not re.match(r"^[A-Za-z0-9][A-Za-z0-9._-]*$", args.router_id):
        raise ValueError(
            f"--router-id must be alphanumeric/._- (got {args.router_id!r})"
        )
    manifest = os.path.join(args.fleet_dir, "manifest.json")
    if not os.path.exists(manifest):
        raise ValueError(
            f"no fleet manifest at {manifest}: start "
            f"`gol fleet --fleet-dir {args.fleet_dir}` first"
        )
    fleet = Fleet(args.fleet_dir, replica=True)
    recovered = fleet.load()
    cfg = fleet.manifest_config or {}
    # A replica spawns nothing at boot, but a replica-turned-leader
    # respawns dead partitions and scales — with the primary's recorded
    # spawn recipe, not a divergent one.
    fleet.serve_args = list(cfg.get("serve_args") or [])
    cores = int(cfg.get("cores_per_worker") or 0)
    if cores:
        fleet._spawn_prefix = core_slice_prefix(cores)
        fleet._spawn_weight = float(cores)
    leading = fleet.enable_leader_election(label=args.router_id)
    breaker_kwargs = {}
    if cfg.get("breakers", True):
        from gol_tpu.fleet.breaker import BreakerConfig
        from gol_tpu.obs.history import HistoryWriter as _BreakerRing

        cooldown = float(cfg.get("breaker_cooldown", 5.0))
        slow = float(cfg.get("breaker_slow", 1.0))
        breaker_kwargs = {
            "breakers": True,
            "breaker_config": BreakerConfig(
                cooldown_s=cooldown, slow_s=slow if slow > 0 else None,
            ),
            "breaker_history": _BreakerRing(
                os.path.join(
                    replicate.state_dir(args.fleet_dir, args.router_id),
                    replicate.BREAKER_RING),
                source="breaker",
            ),
        }
    router = RouterServer(
        fleet, host=args.host, port=args.port,
        big_edge=int(cfg.get("big_edge", 1024)),
        cache_route=bool(cfg.get("cache_route")),
        affinity_route=bool(cfg.get("affinity")),
        router_id=args.router_id,
        state_dir=replicate.state_dir(args.fleet_dir, args.router_id),
        **breaker_kwargs)
    if breaker_kwargs:
        fleet.add_tick_hook(router.prune_breakers)
    if isinstance(cfg.get("autoscale"), dict):
        # Armed but leader-gated: the tick no-ops until THIS replica holds
        # the lease, then scale decisions continue where the dead leader's
        # stopped. Its decision ring lives in this replica's own state dir
        # (single writer per directory), not the primary's legacy path.
        from gol_tpu.fleet.autoscale import AutoscaleConfig, Autoscaler
        from gol_tpu.obs.history import HistoryWriter

        try:
            autoscale_cfg = AutoscaleConfig(**cfg["autoscale"])
        except (TypeError, ValueError) as err:
            raise ValueError(
                f"manifest autoscale config is invalid: {err}") from err
        autoscaler = Autoscaler(
            fleet, router, autoscale_cfg,
            queue_capacity=int(cfg.get("max_queue_depth", 1024)),
            history=HistoryWriter(
                os.path.join(
                    replicate.state_dir(args.fleet_dir, args.router_id),
                    "autoscaler-history"),
                source="autoscaler",
            ),
        )
        router.autoscaler = autoscaler
        fleet.add_tick_hook(autoscaler.tick)
    fleet.start_health(float(cfg.get("health_interval", 1.0)))
    stop = {"signaled": False}

    def _on_signal(signum, frame):
        if stop["signaled"]:
            raise SystemExit(1)
        stop["signaled"] = True
        import threading

        threading.Thread(
            target=lambda: router.shutdown(cascade=False), daemon=True
        ).start()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(f"fleet router on {router.url} "
          f"(replica {args.router_id} over {args.fleet_dir}, "
          f"{recovered} partition(s) adopted, "
          f"{'leading' if leading else 'following'})", flush=True)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def _warm_plans() -> None:
    """Pre-compile the bucket programs of every tuner-recorded serve shape
    (plus the tuned quantum/ladder geometry, consulted implicitly by
    ``bucket_for``). EVERY ladder rung compiles, not just the full batch:
    real flushes dispatch at whatever rung the flushed count rounds to, and
    each rung is a distinct compiled program — warming only the top rung
    would leave the common partial-flush sizes paying compile on their
    first request. Warm failures are loud but non-fatal: a server that
    compiles on first dispatch still serves."""
    from gol_tpu.serve import batcher
    from gol_tpu.serve.jobs import new_job
    from gol_tpu.tune import select

    entries = select.warm_entries()
    if not entries:
        print("no tuned serve shapes to warm (run `gol tune --serve-board` "
              "first)", file=sys.stderr)
        return
    rungs = batcher._plan().batch_ladder
    for entry in entries:
        t0 = time.perf_counter()
        # The whole per-entry path sits inside the guard: warm entries are
        # cache-file content, and a stale or hand-edited entry (bad
        # convention, non-numeric extent) must degrade like every other
        # cache problem — loudly, to compiling on first dispatch — never
        # abort server boot.
        try:
            height, width = int(entry["height"]), int(entry["width"])
            convention = str(entry.get("convention", "c"))
            board = np.zeros((height, width), dtype=np.uint8)
            key = batcher.bucket_for(
                new_job(width, height, board, convention=convention)
            )
            for rung in rungs:
                batcher.warm(key, batch=rung)
        except Exception as err:  # noqa: BLE001 - warmup must not kill boot
            print(f"warm entry {entry} failed ({type(err).__name__}: {err})",
                  file=sys.stderr)
            continue
        print(f"warmed bucket {key.label()} ({len(rungs)} batch rungs) in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)


def _tune(args) -> int:
    """``gol tune``: the offline measured search.

    Searches the declarative space (gol_tpu/tune/space.py) for each
    requested shape x convention, byte-gating every candidate against the
    default engine (oracle-checked where affordable), and commits the
    winners to the persistent plan cache — after which `gol run`/`gol
    serve` on the same machine pick them up automatically. A human-readable
    report goes to --report (or stderr)."""
    from gol_tpu.platform_env import enable_compile_cache

    enable_compile_cache(args.compile_cache)

    from gol_tpu.parallel.mesh import topology_for
    from gol_tpu.tune import measure, plans, select

    shapes = []
    for spec in args.shape or ["256x256"]:
        m = re.fullmatch(r"(\d+)x(\d+)", spec)
        if not m:
            raise ValueError(f"--shape must look like HxW, got {spec!r}")
        shapes.append((int(m.group(1)), int(m.group(2))))
    conventions = (
        ["c", "cuda"] if args.convention == "both" else [args.convention]
    )
    mesh = _parse_mesh_arg(args.mesh, bool(args.mesh))
    store = plans.PlanStore(args.plan_cache)
    results = []
    families = [False]
    if args.packed:
        # The packed-state lane (--packed-io runs) consults its own
        # family's fingerprints — tune it explicitly or it stays on the
        # built-in ladder.
        bad = [f"{h}x{w}" for h, w in shapes if w % 32 != 0]
        if bad:
            raise ValueError(
                f"--packed needs widths divisible by 32 (the packed word), "
                f"got {bad}"
            )
        families.append(True)
    for height, width in shapes:
        for convention in conventions:
            for packed_state in families:
                config = GameConfig(gen_limit=args.gen_limit,
                                    convention=convention)
                family = "packed" if packed_state else "byte"
                print(f"tune engine: {height}x{width}/{convention}/{family} "
                      f"(gen_limit={args.gen_limit}, iters={args.iters})",
                      file=sys.stderr)
                result = measure.run_engine_search(
                    height, width, config, mesh, packed_state=packed_state,
                    iters=args.iters, quick=args.quick,
                )
                results.append(result)
                store.put(
                    select.engine_fingerprint((height, width), config, mesh,
                                              packed_state=packed_state),
                    result.winner.to_dict(),
                    measured=result.to_dict() if args.provenance else {
                        "tuned_vs_default": round(result.speedup, 4),
                        "default": result.default_label,
                    },
                )
                print(f"  winner {result.winner.label()} at "
                      f"{result.speedup:.3f}x the default ladder",
                      file=sys.stderr)

    if args.serve_board:
        m = re.fullmatch(r"(\d+)x(\d+)", args.serve_board)
        if not m:
            raise ValueError(
                f"--serve-board must look like HxW, got {args.serve_board!r}"
            )
        height, width = int(m.group(1)), int(m.group(2))
        if mesh is not None and topology_for(mesh).distributed:
            raise ValueError("--serve-board tunes the single-device serving "
                             "lane; drop --mesh")
        print(f"tune serve: {height}x{width} boards", file=sys.stderr)
        result = measure.run_serve_search(
            height, width, conventions[0],
            gen_limit=min(args.gen_limit, 8), iters=args.iters,
        )
        results.append(result)
        plan_dict = result.winner.to_dict()
        plan_dict["warm"] = [
            {"height": height, "width": width, "convention": convention}
            for convention in conventions
        ]
        if result.marginal:
            # The winner's marginal kernel rate rides with the plan: the
            # serving dispatch-gap monitor reads it back as its roofline
            # (select.marginal_rates).
            plan_dict["marginal"] = result.marginal
        store.put(
            select.serve_fingerprint(), plan_dict,
            measured={"tuned_vs_default": round(result.speedup, 4)},
        )
        print(f"  winner {result.winner.label()} at "
              f"{result.speedup:.3f}x the default geometry", file=sys.stderr)

    if args.sparse_crossover:
        # The `--engine auto` dense/sparse threshold, measured on THIS
        # host instead of hard-coded: fit dense cost (linear in area)
        # against the sparse engine's flat cost and persist the solved
        # crossover (tune.select.sparse_auto_area consults it).
        print("tune sparse-crossover: dense-vs-sparse per-generation cost",
              file=sys.stderr)
        crossover = measure.run_sparse_crossover_search(
            iters=args.iters, quick=args.quick,
        )
        store.put(
            select.sparse_fingerprint(),
            {"auto_area": crossover.auto_area},
            measured=crossover.to_dict(),
        )
        print(f"  dense overtakes sparse at ~{crossover.auto_area} cells "
              f"(~{int(crossover.auto_area ** 0.5)}^2); persisted as the "
              "--engine auto threshold", file=sys.stderr)

    report = measure.render_report(results)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            f.write(report)
        print(f"report -> {args.report}", file=sys.stderr)
    else:
        print(report, file=sys.stderr)
    print(f"plans -> {store.path}", file=sys.stderr)
    # A same-process serve (tests, tune-then-serve scripts) must see the
    # fresh plans: drop the consult caches.
    select.reset()
    from gol_tpu.serve import batcher

    batcher._reset_plan()
    return 0


def _http_json(method: str, url: str, body: dict | None = None, timeout=30,
               raw: bytes | None = None, content_type: str | None = None,
               headers: dict | None = None):
    """The ONE stdlib JSON client (``gol_tpu/fleet/client.py`` — jax-free,
    shared with the router/health loops): HTTP errors come back as
    (status, payload), connection trouble raises for the callers'
    retry/timeout logic. ``raw``/``content_type`` send a pre-encoded
    body (the packed wire submit); ``headers`` adds request headers (the
    submit deadline stamp, obs/propagate.py)."""
    from gol_tpu.fleet import client as fleet_client

    return fleet_client.http_json(method, url, body, timeout=timeout,
                                  raw=raw, content_type=content_type,
                                  headers=headers)


def _http_exchange(method: str, url: str, timeout=30, accept=None):
    """Byte-level GET for the packed result fetch: (status, content type,
    body bytes) — the caller parses by the RESPONSE type, so an old
    server answering JSON degrades transparently."""
    from gol_tpu.fleet import client as fleet_client

    headers = {"Accept": accept} if accept else None
    return fleet_client.http_exchange(method, url, timeout=timeout,
                                      headers=headers)


class _WireDowngrade(Exception):
    """A packed submit answered 400/415: resend as text (retryable)."""


class _WireCRCResend(Exception):
    """A packed submit answered a CRC-mismatch 400: the frame was
    corrupted in transit, not rejected — resend PACKED (bounded)."""


def _connection_trouble(err: BaseException) -> bool:
    """Connection-level trouble worth an in-call retry: refused, reset,
    timed out, torn HTTP — anything the transport raised. HTTP statuses
    never reach here (they return as values), so semantics stay with the
    call sites."""
    import urllib.error

    return isinstance(err, (urllib.error.URLError, ConnectionError, OSError))


def _submit_retry():
    """The ONE retry stance for ``gol submit`` — a jittered exponential
    policy over a shared token-bucket budget, replacing the three ad-hoc
    loops that had grown here (the status poll, the result collect, and
    the packed->text wire downgrade). The shared budget bounds the
    client's total retry amplification: against a browned-out fleet the
    bucket drains and every site degrades to one attempt per sweep,
    surfacing the original errors instead of piling on. The per-target
    no-contact cutoff in ``_collect_results`` is UNCHANGED — the policy
    retries inside a sweep; the cutoff still decides when a target is
    dead."""
    from gol_tpu.resilience.retry import RetryBudget, RetryPolicy

    policy = RetryPolicy(attempts=3, base_delay=0.1, multiplier=2.0,
                         max_delay=1.0, jitter=0.25)
    budget = RetryBudget(capacity=16.0, refill_per_s=1.0)
    return policy, budget


class _ServerRing:
    """The ``--servers A,B,C`` failover ring: every base is a router
    REPLICA over one fleet (shared manifest — any replica can place,
    forward, or look up any job), so idempotent GETs rotate freely on
    connection trouble, while the job-creating POST rotates ONLY on
    delivery-impossible failures (refused/DNS/unreachable: no byte
    reached any queue). An ambiguous failure — reset or timeout AFTER
    the bytes went out — never rotates: the first router may have
    accepted and journaled the job, and a blind resubmit to a sibling
    double-runs the board under two ids (the ambiguous-504 contract,
    now applied across replicas). A plain ``--server`` invocation gets a
    one-element ring, so every single-server path is pinned unchanged."""

    def __init__(self, spec):
        if isinstance(spec, str):
            bases = [s.strip().rstrip("/") for s in spec.split(",")]
        else:
            bases = [s.rstrip("/") for s in spec]
        self.bases = [b for b in bases if b]
        if not self.bases:
            raise ValueError("--servers needs at least one URL")
        self._i = 0  # the preferred base: last one that answered

    @property
    def current(self) -> str:
        return self.bases[self._i]

    def prefer(self, base: str) -> None:
        if base in self.bases:
            self._i = self.bases.index(base)

    def rotation(self) -> list:
        """Every base, preferred first — the probe order for idempotent
        reads."""
        return self.bases[self._i:] + self.bases[:self._i]

    def others(self, base: str) -> list:
        """Failover candidates for a dead ``base``, in ring order after
        it (empty for a one-element ring)."""
        if len(self.bases) < 2:
            return []
        try:
            i = self.bases.index(base)
        except ValueError:
            return list(self.bases)
        return self.bases[i + 1:] + self.bases[:i]


def _submit(args) -> int:
    """``gol submit``: client for a running ``gol serve`` instance.

    Submits each input file as one job, then (by default) polls until every
    job is terminal and writes each result next to its input
    (``<input>.out`` or into --output-dir), printing the per-board
    ``Generations:`` accounting the solo CLI prints."""
    from gol_tpu.variants import get_variant

    variant = get_variant(args.variant)
    width, height = atoi(args.width), atoi(args.height)
    if width <= 0:
        width = DEFAULT_WIDTH
    if height <= 0:
        height = DEFAULT_HEIGHT
    ring = _ServerRing(getattr(args, "servers", None) or args.server)
    base = ring.current
    # --shard-across: against a fleet router, fan the multi-board submit
    # round-robin over the fleet's workers directly (GET /fleet lists
    # them); against a single `gol serve` — no /fleet endpoint — the flag
    # is a no-op and every job goes to --server as always. Membership is
    # re-fetched on an interval (and on a 429) rather than snapshotted
    # once: against an autoscaled fleet, workers appear mid-submission —
    # exactly because of the load this loop is applying — and a one-shot
    # snapshot would never send them a job.
    targets = _ShardTargets(
        base, args.shard_across,
        refresh_s=getattr(args, "shard_refresh", 5.0),
        fetch=_fetch_json,
    )
    targets.refresh(force=True)
    if args.shard_across and len(targets.targets) > 1:
        print(f"gol submit: sharding {len(args.input_files)} board(s) "
              f"across {len(targets.targets)} fleet worker(s)",
              file=sys.stderr)
    # --wire packed: boards travel as binary wire frames (io/wire.py, ~8x
    # fewer bytes). Degradation is PER TARGET: a server that answers 415
    # (or 400 — an old server's JSON parser rejecting the frame) gets ONE
    # logged resend as text and every later submit to it goes text too —
    # bounded per target by construction, so it bypasses the retry budget
    # (format negotiation is free; brownout amplification is what the
    # budget caps).
    wire_default = getattr(args, "wire", "text")
    wire_mode = {}  # per target; new targets default to the flag's mode
    from gol_tpu.obs import propagate as obs_propagate

    policy, budget = _submit_retry()
    ids = {}  # job id -> (input path, server base the job lives on)
    for path in args.input_files:
        target = targets.next()
        wire_mode.setdefault(target, wire_default)
        grid = text_grid.read_grid(path, width, height)
        meta = {
            "convention": variant.convention,
            "gen_limit": args.gen_limit,
            "priority": args.priority,
        }
        if args.deadline is not None:
            meta["deadline_s"] = args.deadline
        if args.no_cache:
            # Per-job result-cache opt-out (Job.no_cache); servers without
            # a cache ignore the field after type validation.
            meta["no_cache"] = True
        job_t0 = time.perf_counter()

        def deadline_headers():
            # --timeout: stamp the REMAINING X-Gol-Deadline budget at send
            # time — a resend after backoff carries less than the first
            # attempt did, exactly like a router hop. Old servers ignore
            # the header; no --timeout sends no header (pinned).
            if args.timeout is None:
                return None
            remaining = args.timeout - (time.perf_counter() - job_t0)
            return {obs_propagate.DEADLINE_HEADER:
                    obs_propagate.encode_deadline(remaining)}

        crc_resends = {"n": 0}  # per board: transit-corrupted frames

        def post_once(target):
            if wire_mode[target] == "packed":
                from gol_tpu.io import wire

                status, payload = _http_json(
                    "POST", f"{target}/jobs",
                    raw=wire.encode_frame(meta, grid=grid),
                    content_type=wire.CONTENT_TYPE,
                    headers=deadline_headers(),
                )
                if status not in (400, 415):
                    return status, payload
                if status == 400 and wire.is_crc_error(payload):
                    # The server's CRC gate caught a frame corrupted in
                    # transit (a 400 created no job: resending is
                    # unconditionally safe) — that is the wire format
                    # WORKING, not the server rejecting it. Downgrading
                    # here would swap detected corruption for the text
                    # lane's undetectable kind, on exactly the link that
                    # corrupts. Resend packed, twice at most; a hop
                    # corrupting every frame surfaces the 400 loudly.
                    if crc_resends["n"] < 2:
                        crc_resends["n"] += 1
                        print(
                            f"gol submit: {target} reports a frame CRC "
                            "mismatch (corrupted in transit); resending "
                            f"packed ({crc_resends['n']}/2)",
                            file=sys.stderr,
                        )
                        raise _WireCRCResend(status)
                    return status, payload
                print(
                    f"gol submit: {target} does not accept the packed "
                    f"wire format (HTTP {status}); retrying as text",
                    file=sys.stderr,
                )
                wire_mode[target] = "text"
                raise _WireDowngrade(status)
            body = {"width": width, "height": height,
                    "cells": text_grid.encode(grid).decode("ascii"),
                    **meta}
            return _http_json("POST", f"{target}/jobs", body,
                              headers=deadline_headers())

        def submit_to(target):
            # The job-creating POST is NOT idempotent: only failures that
            # guarantee nothing reached the server (refused, DNS,
            # unreachable — the router's spill-safety classification) are
            # auto-retried. Anything ambiguous — a reset or timeout after
            # the bytes went out — surfaces instead of re-POSTing, because
            # the server may have accepted and journaled the job and a
            # blind resend would run the board twice under two ids.
            from gol_tpu.resilience.retry import delivery_impossible

            while True:
                try:
                    return policy.call(
                        lambda: post_once(target),
                        retryable=delivery_impossible,
                        budget=budget,
                    )
                except _WireDowngrade:
                    # Format negotiation, not a transient: post_once
                    # already flipped this target to text, so the resend
                    # is deterministic and happens AT MOST ONCE per
                    # target — it spends no retry-budget tokens (a fleet
                    # of old servers must not eat the brownout budget,
                    # and an empty bucket must not strand the downgrade).
                    continue
                except _WireCRCResend:
                    # A transit-corrupted frame, bounded at 2 per board
                    # inside post_once; same budget exemption (nothing
                    # reached the queue — a 400 created no job).
                    continue

        def submit_failover(target):
            # --servers: a dead ROUTER rotates the POST to the next
            # replica — but only on delivery-impossible failures, where
            # no byte reached any queue (see _ServerRing). The rotation
            # applies to ring bases only: a --shard-across WORKER target
            # failing surfaces as before (the job's placement is the
            # router's business, not a reason to re-pick routers).
            from gol_tpu.resilience.retry import delivery_impossible

            tried = {target}
            while True:
                try:
                    return target, submit_to(target)
                except OSError as err:
                    if target not in ring.bases \
                            or not delivery_impossible(err):
                        raise
                    nxt = next((b for b in ring.others(target)
                                if b not in tried), None)
                    if nxt is None:
                        raise
                    print(f"gol submit: router {target} unreachable "
                          f"({type(err).__name__}); failing over to {nxt}",
                          file=sys.stderr)
                    tried.add(nxt)
                    wire_mode.setdefault(nxt, wire_default)
                    target = nxt
                    ring.prefer(nxt)

        try:
            target, (status, payload) = submit_failover(target)
            if status == 429:
                # A shed burst: the membership that 429'd may already be
                # stale — an autoscaled fleet is likely scaling up RIGHT
                # NOW because of this very load. Re-fetch and retry ONCE
                # against the next (possibly brand-new) target before
                # giving up.
                targets.on_429()
                retry = targets.next()
                wire_mode.setdefault(retry, wire_default)
                print(f"gol submit: {target} shed the job (HTTP 429); "
                      f"refreshed membership, retrying on {retry}",
                      file=sys.stderr)
                target = retry
                target, (status, payload) = submit_failover(target)
        except OSError as err:
            # Exchange trouble the policy refused to retry: either
            # no-contact retries ran out, or — the case that matters —
            # the failure was ambiguous and a resend could double-run
            # the board. Name which, so the operator knows whether a
            # resubmit is safe.
            from gol_tpu.resilience.retry import delivery_impossible

            fate = ("never delivered — safe to resubmit"
                    if delivery_impossible(err)
                    else "outcome unknown — the job may have been "
                         "accepted there; audit before resubmitting")
            print(f"gol submit: {path}: {target} exchange failed "
                  f"({type(err).__name__}: {err}); {fate}",
                  file=sys.stderr)
            return 1
        if status != 202:
            # A router's ambiguous 504 names the worker whose outcome is
            # unknown (and its breaker state): surface both, so the
            # operator knows WHICH partition to audit before resubmitting.
            note = ""
            if isinstance(payload, dict) and payload.get("worker"):
                breaker = payload.get("breaker")
                note = (f" [outcome unknown at worker {payload['worker']}"
                        + (f", breaker {breaker}" if breaker else "") + "]")
            detail = (payload.get("error", payload)
                      if isinstance(payload, dict) else payload)
            print(f"gol submit: {path}: HTTP {status}: {detail}{note}",
                  file=sys.stderr)
            return 1
        if not isinstance(payload, dict) or "id" not in payload:
            # A 202 whose ack BODY was corrupted in transit (bit-flipped
            # hop garbling the JSON): the job WAS accepted — the status
            # line survived — but there is no id to poll, and a resend
            # would run the board twice. Same loud-abandon contract as
            # the ambiguous 504.
            print(
                f"gol submit: {path}: {target} accepted the job but the "
                "ack body arrived corrupted; cannot track it — audit the "
                "server's journal before resubmitting",
                file=sys.stderr,
            )
            return 1
        ids[payload["id"]] = (path, target)
        print(f"{path}\t{payload['id']}")
    if not args.wait:
        return 0

    outdir = args.output_dir
    if outdir:
        os.makedirs(outdir, exist_ok=True)
    return _collect_results(dict(ids), args, outdir,
                            retry=(policy, budget), ring=ring)


class _ShardTargets:
    """The --shard-across target set, kept fresh through the submission.

    ``gol submit`` used to snapshot GET /fleet once at startup, so a long
    submission never saw workers an autoscaler added mid-run — the fleet
    would scale up under the load and the client would keep hammering the
    original N workers. This object re-fetches membership every
    ``refresh_s`` seconds of submission (and immediately on a 429 burst,
    via ``on_429``) and rotates round-robin over the CURRENT healthy
    non-big workers. Disabled (``--shard-across`` absent) or against a
    single ``gol serve`` (no /fleet endpoint, fetch returns {}), the
    target list stays ``[base]`` — the pinned no-op behavior.

    Clock: ``time.perf_counter`` (interval arithmetic only)."""

    def __init__(self, base: str, enabled: bool, refresh_s: float = 5.0,
                 fetch=None, clock=time.perf_counter):
        self.base = base
        self.enabled = enabled
        self.refresh_s = refresh_s
        self._fetch = fetch if fetch is not None else _fetch_json
        self._clock = clock
        self.targets = [base]
        self._i = 0
        self._fetched_at: float | None = None

    def refresh(self, force: bool = False) -> None:
        if not self.enabled:
            return
        now = self._clock()
        if (not force and self._fetched_at is not None
                and now - self._fetched_at < self.refresh_s):
            return
        self._fetched_at = now
        membership = self._fetch(f"{self.base}/fleet")
        urls = [
            str(w["url"]).rstrip("/")
            for w in (membership.get("workers") or [])
            if w.get("url") and w.get("healthy", True) and not w.get("big")
            and not w.get("retiring")
        ]
        if not urls:
            return  # single server / unreachable: keep what we have
        if urls != self.targets:
            print(f"gol submit: fleet membership now {len(urls)} "
                  f"worker(s)", file=sys.stderr)
        self.targets = urls

    def next(self) -> str:
        """The next round-robin target, after an interval-gated refresh."""
        self.refresh()
        target = self.targets[self._i % len(self.targets)]
        self._i += 1
        return target

    def on_429(self) -> None:
        """A shed answer: whatever membership produced it is suspect —
        re-fetch NOW regardless of the interval."""
        self.refresh(force=True)


def _collect_results(pending: dict, args, outdir, retry=None,
                     ring=None) -> int:
    """Poll every submitted job to a terminal state and write its result.

    ``pending`` maps job id -> (input path, server base URL) — with
    ``--shard-across`` the bases differ per job, so contact tracking is
    PER TARGET: one dead worker (e.g. respawned by its fleet on a new
    port, unreachable at the URL this client recorded) abandons only ITS
    jobs after ``--server-timeout`` of no contact; jobs on healthy
    targets keep completing. Connection errors and 5xx answers are both
    transient-with-timeout — the server-restart/worker-respawn windows
    the journal-replay story is built for.

    ``retry`` is the submit loop's shared (RetryPolicy, RetryBudget) pair
    (``_submit_retry``): transient connection trouble retries INSIDE a
    sweep under the budget before it counts against the per-target
    no-contact cutoff — whose semantics are deliberately unchanged."""
    import time as _time
    import urllib.error

    policy, budget = retry if retry is not None else _submit_retry()
    rc = 0
    now = time.perf_counter()
    last_contact = {base: now for _, base in pending.values()}
    bad_body: dict = {}  # job_id -> sweeps whose 200 body was unusable
    while pending:
        _time.sleep(args.poll_interval)
        stale_this_sweep = set()  # targets already found down this sweep
        for job_id in list(pending):
            entry = pending.get(job_id)
            if entry is None:
                continue  # removed mid-sweep by target_down on its base
            path, job_base = entry
            if job_base in stale_this_sweep:
                continue

            def target_down(detail):
                stale_this_sweep.add(job_base)
                if (time.perf_counter() - last_contact[job_base]
                        <= args.server_timeout):
                    return False  # transient so far; retry next sweep
                victims = [j for j, (_, b) in pending.items()
                           if b == job_base]
                print(
                    f"gol submit: no contact with {job_base} for "
                    f"{args.server_timeout:.0f}s ({detail}); giving up on "
                    f"{len(victims)} job(s) there",
                    file=sys.stderr,
                )
                for j in victims:
                    del pending[j]
                return True

            def bad_body_strike(detail):
                """Bounded tolerance for answers whose BODY is unusable —
                a bit-flipped hop garbling status JSON, a result grid, or
                a packed frame's CRC. Transit corruption heals on the next
                sweep's refetch; a hop corrupting EVERY exchange must not
                poll forever (the answers keep coming, so the no-contact
                cutoff above never fires for this job). True once the
                3-strike bound is hit: the job is abandoned loudly."""
                bad_body[job_id] = bad_body.get(job_id, 0) + 1
                if bad_body[job_id] < 3:
                    return False
                print(
                    f"gol submit: {path}: unusable response body across "
                    f"{bad_body[job_id]} sweeps ({detail}); giving up on "
                    f"job {job_id}", file=sys.stderr,
                )
                pending.pop(job_id, None)
                return True

            try:
                status, payload = policy.call(
                    lambda: _http_json("GET", f"{job_base}/jobs/{job_id}"),
                    retryable=_connection_trouble, budget=budget,
                )
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # --servers: a status GET is idempotent, and any replica
                # router can look up any job — re-home this job to the
                # next ring base that is not itself past the no-contact
                # cutoff. Only ring bases re-home (a --shard-across
                # WORKER base has no siblings with its journal); with
                # every router dead, each base ages past the cutoff and
                # the per-target give-up below fires exactly as before.
                moved = None
                if ring is not None and job_base in ring.bases:
                    now2 = time.perf_counter()
                    for cand in ring.others(job_base):
                        last_contact.setdefault(cand, now2)
                        if now2 - last_contact[cand] <= args.server_timeout:
                            moved = cand
                            break
                if moved is not None:
                    print(f"gol submit: router {job_base} unreachable "
                          f"({type(e).__name__}); polling job {job_id} "
                          f"via {moved}", file=sys.stderr)
                    pending[job_id] = (path, moved)
                    continue
                if target_down(e):
                    rc = 1
                continue
            if status >= 500:
                # A fleet router whose worker is mid-respawn answers 503
                # while the partition replays; same treatment as a
                # connection error. (Contact is only refreshed by real
                # answers, so a permanently-5xxing target times out.)
                if target_down(f"HTTP {status}"):
                    rc = 1
                continue
            last_contact[job_base] = time.perf_counter()
            if status != 200:
                print(f"gol submit: lost job {job_id}: HTTP {status}",
                      file=sys.stderr)
                del pending[job_id]
                rc = 1
                continue
            state = (payload.get("state")
                     if isinstance(payload, dict) else None)
            if state is None:
                # Parsed, but not as a job answer (a flip that left valid
                # JSON): same bounded-refetch treatment as a parse error.
                if bad_body_strike("no job state in the answer"):
                    rc = 1
                continue
            if state in ("queued", "scheduled", "running"):
                # A usable answer clears the strikes: the bound is on
                # CONSECUTIVE corrupt sweeps, not lifetime total — a long
                # job under intermittent, self-healing transit flips must
                # never strike out. (A done job's result-fetch strikes
                # stay consecutive by construction: any good fetch
                # completes the job.)
                bad_body.pop(job_id, None)
                continue
            del pending[job_id]
            if state != "done":
                print(f"gol submit: {path}: job {state}: "
                      f"{payload.get('error', '')}", file=sys.stderr)
                rc = 1
                continue
            try:
                # Body corruption (ValueError: a packed frame's CRC gate
                # — WireError subclasses it — or garbled JSON/grid text)
                # is retryable HERE and nowhere else: the result on the
                # worker is intact, so a refetch is the fix (the PR-11
                # gate turning a flipped bit into a retry instead of a
                # wrong board).
                status, result, grid = policy.call(
                    lambda: _fetch_result(
                        job_base, job_id, getattr(args, "wire", "text")
                    ),
                    retryable=lambda e: (_connection_trouble(e)
                                         or isinstance(e, ValueError)),
                    budget=budget,
                )
            except (urllib.error.URLError, ConnectionError, OSError,
                    ValueError, KeyError) as e:
                if isinstance(e, (ValueError, KeyError)):
                    if bad_body_strike(repr(e)):
                        rc = 1
                        continue
                pending[job_id] = (path, job_base)  # refetch next sweep
                continue
            if status >= 500:
                pending[job_id] = (path, job_base)  # refetch next sweep
                continue
            if status != 200:
                print(f"gol submit: {path}: result fetch HTTP {status}",
                      file=sys.stderr)
                rc = 1
                continue
            if (not isinstance(result, dict) or "generations" not in result
                    or "exit_reason" not in result):
                # Valid JSON and a decodable grid, but a flip ate a meta
                # key: don't trust the body enough to write it out — the
                # same bounded refetch as any other unusable answer
                # (previously an uncaught KeyError at the print below
                # abandoned every pending job).
                if bad_body_strike("result meta incomplete"):
                    rc = 1
                    continue
                pending[job_id] = (path, job_base)
                continue
            out_path = (
                os.path.join(outdir, os.path.basename(path) + ".out")
                if outdir
                else path + ".out"
            )
            text_grid.write_grid(out_path, grid)
            # The cache marker: present only when the server answered from
            # its result cache (or coalesced the run) — old servers' result
            # payloads lack the key and the line degrades to nothing,
            # exactly like the timeline columns after it.
            cached = result.get("cached")
            marker = f"\tcached:{cached}" if cached else ""
            print(f"{path}\tGenerations:\t{result['generations']}\t"
                  f"{result['exit_reason']}\t-> {out_path}{marker}"
                  f"{_submit_latency_note(job_base, job_id)}")
    return rc


def _fetch_result(base: str, job_id: str, wire_pref: str):
    """GET /result/<id> -> (status, result meta dict, grid or None).

    With ``wire_pref == "packed"`` the fetch sends ``Accept:
    application/x-gol-packed`` and parses by the RESPONSE content type —
    a new server answers a binary frame (~8x fewer bytes on the wire), an
    old server ignores the header and answers JSON, byte-identical
    either way (the decoded grid is the same board; test-pinned)."""
    if wire_pref == "packed":
        from gol_tpu.io import wire

        status, ctype, body = _http_exchange(
            "GET", f"{base}/result/{job_id}", accept=wire.CONTENT_TYPE
        )
        if status == 200 and wire.is_packed(ctype):
            frame = wire.decode_frame(body)
            return status, dict(frame.meta), frame.grid()
        try:
            result = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            result = {"error": body[:200].decode("utf-8", "replace")}
    else:
        status, result = _http_json("GET", f"{base}/result/{job_id}")
    grid = None
    if status == 200:
        grid = text_grid.decode(
            result["grid"].encode("ascii"), result["width"], result["height"]
        )
    return status, result, grid


def _submit_latency_note(base: str, job_id: str) -> str:
    """Where the client's time went, from the job's timeline (the server's
    per-job milestone decomposition) — appended to the per-board result
    line so the answer arrives without anyone curling a debug endpoint.
    Empty when the server predates timelines or the fetch fails: the
    result line must never fail because the ops surface did."""
    import urllib.error

    try:
        status, tl = _http_json("GET", f"{base}/jobs/{job_id}/timeline",
                                timeout=5)
    except (urllib.error.URLError, ConnectionError, OSError):
        return ""
    if status != 200 or tl.get("total_seconds") is None:
        return ""
    queue_ms = (tl.get("segments") or {}).get("queue_wait", 0.0) * 1e3
    return (f"\tqueue {queue_ms:.1f} ms"
            f"\ttotal {tl['total_seconds'] * 1e3:.1f} ms")


def _batch(args) -> int:
    """``gol batch``: the offline batched lane — N input files, one process.

    The headline throughput path even without the HTTP layer: jobs are
    bucketed exactly as the server would (gol_tpu/serve/batcher.py), each
    bucket dispatches as few compiled programs as the batch-size ladder
    allows, and per-board results are bit-identical to solo ``gol`` runs."""
    from gol_tpu.serve import batcher
    from gol_tpu.serve.jobs import new_job
    from gol_tpu.variants import get_variant

    variant = get_variant(args.variant)
    width, height = atoi(args.width), atoi(args.height)
    if width <= 0:
        width = DEFAULT_WIDTH
    if height <= 0:
        height = DEFAULT_HEIGHT
    if not 1 <= args.max_batch <= batcher.MAX_BATCH:
        raise ValueError(
            f"--max-batch must be in [1, {batcher.MAX_BATCH}], "
            f"got {args.max_batch}"
        )
    outdir = args.output_dir
    if outdir:
        os.makedirs(outdir, exist_ok=True)

    jobs = []
    for path in args.input_files:
        grid = text_grid.read_grid(path, width, height)
        job = new_job(
            width, height, grid,
            convention=variant.convention,
            gen_limit=args.gen_limit,
        )
        jobs.append((path, job))

    buckets: dict = {}
    for path, job in jobs:
        buckets.setdefault(batcher.bucket_for(job), []).append((path, job))

    t0 = time.perf_counter()
    batches = 0
    occupancy = []
    outputs = []
    for key, members in buckets.items():
        for i in range(0, len(members), args.max_batch):
            chunk = members[i : i + args.max_batch]
            results = batcher.run_batch(key, [job for _, job in chunk])
            batches += 1
            occupancy.append(len(chunk) / batcher.pad_batch(len(chunk)))
            for (path, _job), result in zip(chunk, results):
                out_path = (
                    os.path.join(outdir, os.path.basename(path) + ".out")
                    if outdir
                    else path + ".out"
                )
                text_grid.write_grid(out_path, result.grid)
                outputs.append(
                    (path, result.generations, result.exit_reason, out_path)
                )
    exec_s = time.perf_counter() - t0
    for path, gens, reason, out_path in outputs:
        print(f"{path}\tGenerations:\t{gens}\t{reason}\t-> {out_path}")
    mean_occ = sum(occupancy) / len(occupancy) if occupancy else 0.0
    print(
        f"Batch:\t{len(jobs)} boards, {len(buckets)} bucket(s), "
        f"{batches} dispatch(es), occupancy {mean_occ:.2f}, "
        f"{len(jobs) / max(exec_s, 1e-9):.1f} boards/sec, "
        f"{exec_s * 1000:.2f} msecs",
        file=sys.stderr,
    )
    return 0


def _fetch_json(url: str, timeout: float = 5.0):
    """GET url -> payload dict via the one stdlib client (``_http_json``),
    or {} on any connection/HTTP trouble — the ops surfaces below must
    outlive a flapping server; that is their point."""
    import urllib.error

    try:
        status, payload = _http_json("GET", url, timeout=timeout)
    except (urllib.error.URLError, ConnectionError, OSError, ValueError):
        return {}
    return payload if status == 200 and isinstance(payload, dict) else {}


def _top(args) -> int:
    """``gol top``: live terminal dashboard over /metrics + /slo.

    Polls the two JSON endpoints every --interval seconds and redraws one
    ANSI frame in place (gol_tpu/obs/top.py renders; this loop only owns
    HTTP and the terminal). --iterations N exits after N frames (0 = run
    until interrupted) — the scriptable/test lane."""
    from gol_tpu.obs import top as obs_top

    ring = _ServerRing(getattr(args, "servers", None) or args.server)
    if args.interval <= 0:
        raise ValueError(f"--interval must be > 0, got {args.interval}")
    ansi = sys.stdout.isatty() and not args.no_ansi
    frames = 0
    try:
        while True:
            # --servers: probe the ring preferred-first; the dashboard
            # follows whichever replica answers (the title names it, so
            # the operator always knows WHICH router's view this is).
            # One base — the plain --server invocation — is pinned:
            # same fetches, same title.
            metrics, answered = {}, None
            for cand in ring.rotation():
                metrics = _fetch_json(f"{cand}/metrics?format=json")
                if metrics:
                    answered = cand
                    ring.prefer(cand)
                    break
            base = answered or ring.current
            slo = _fetch_json(f"{base}/slo")
            title = f"gol top — {base}"
            if len(ring.bases) > 1:
                title += (f" [answered by {base}]" if answered
                          else f" [all {len(ring.bases)} routers "
                               "unreachable]")
            frame = obs_top.render_frame(
                metrics, slo or None, ansi=ansi,
                title=title,
            )
            if ansi:
                sys.stdout.write(obs_top.CLEAR)
            sys.stdout.write(frame)
            sys.stdout.flush()
            frames += 1
            if args.iterations and frames >= args.iterations:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def _slo_report(args) -> int:
    """``gol slo-report``: summarize SLO state from a live server or a
    flight-recorder dump (the ``slo`` state record a crash leaves behind)."""
    from gol_tpu.obs import recorder, slo as obs_slo

    target = args.target
    if target.startswith(("http://", "https://")):
        status = _fetch_json(f"{target.rstrip('/')}/slo", timeout=10)
        if not status:
            raise ValueError(f"no SLO status from {target} (is the server "
                             "up, and does it have /slo?)")
        sys.stdout.write(obs_slo.render_status(status))
        return 0
    # A flight dump: find the slo state record.
    state = None
    for rec in recorder.read_dump(target):
        if rec.get("record") == "state" and rec.get("name") == obs_slo.STATE_PROVIDER:
            state = {k: v for k, v in rec.items()
                     if k not in ("record", "name")}
    if state is None:
        raise ValueError(
            f"{target} holds no SLO state record (was the dumping process "
            "a server? pre-SLO dumps have none)"
        )
    sys.stdout.write(obs_slo.render_status(state))
    return 0


def _trace_report(args) -> int:
    """``gol trace-report``: render the summary of a trace artifact.

    Accepts both formats the obs subsystem writes — the Chrome trace JSON a
    ``--trace DIR`` run exports, and the flight-recorder JSONL a crash (or
    SIGUSR1) dumps — so the same command answers "where did the time go"
    and "what was it doing when it died"."""
    from gol_tpu.obs import report

    sys.stdout.write(report.render(args.trace_file))
    return 0


def _fleet_trace(args) -> int:
    """``gol fleet-trace``: one stitched Perfetto timeline for the fleet.

    Collects ``GET /debug/trace`` from the router and every worker its
    ``GET /fleet`` lists (concurrently; a single ``gol serve`` — no /fleet
    — is traced alone), normalizes each process's monotonic clock against
    its wall anchor, and writes ONE Chrome trace JSON: a pid lane per
    process, cross-process flow arrows router→worker per job. Unreachable
    workers are skipped with a note — tracing the survivors during the
    incident that killed a worker is the point."""
    import urllib.error

    from gol_tpu.obs import fleettrace

    ring = _ServerRing(getattr(args, "servers", None) or args.server)
    doc = None
    last_err = None
    for cand in ring.rotation():
        # --servers: the stitched export reads idempotent debug
        # endpoints, so trying the next replica router is always safe.
        try:
            doc = fleettrace.export(cand, args.output)
            if len(ring.bases) > 1:
                print(f"fleet-trace: exported via router {cand}",
                      file=sys.stderr)
            break
        except (urllib.error.URLError, ConnectionError, OSError) as err:
            last_err = err
            if len(ring.bases) > 1:
                print(f"fleet-trace: router {cand} unreachable "
                      f"({type(err).__name__}); trying the next replica",
                      file=sys.stderr)
    if doc is None:
        raise ValueError(
            f"no router in {', '.join(ring.bases)} answered: {last_err}")
    other = doc.get("otherData", {})
    processes = other.get("processes", {})
    events = doc.get("traceEvents", [])
    flows = sum(1 for e in events if e.get("ph") in ("s", "t", "f"))
    spans = sum(1 for e in events if e.get("ph") == "X")
    print(f"fleet-trace -> {args.output}: {len(processes)} process(es) "
          f"[{', '.join(sorted(processes))}], {spans} span(s), "
          f"{flows} flow point(s)", file=sys.stderr)
    for entry in other.get("skipped", []):
        print(f"  skipped {entry.get('name')}: {entry.get('reason')}",
              file=sys.stderr)
    if not processes:
        print("fleet-trace: no process had tracing enabled — start the "
              "fleet with --trace DIR", file=sys.stderr)
        return 1
    return 0


def _history_report(args) -> int:
    """``gol history-report``: render a metrics-history ring as
    rate/value/percentile timelines (gol_tpu/obs/history.py)."""
    from gol_tpu.obs import history

    if not os.path.isdir(args.history_dir):
        raise ValueError(f"{args.history_dir} is not a directory (pass the "
                         "ring a --metrics-history run wrote)")
    sys.stdout.write(history.render_report(args.history_dir))
    return 0


def _generate(args) -> int:
    if args.output:
        # Streamed: north-star-sized grids (65536^2 = 4 GB of text) generate
        # in O(chunk) host memory.
        text_grid.generate_to_file(
            args.output, args.width, args.height, density=args.density, seed=args.seed
        )
    else:
        grid = text_grid.generate(
            args.width, args.height, density=args.density, seed=args.seed
        )
        sys.stdout.write(text_grid.encode(grid).decode("ascii"))
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="gol",
        description="TPU-native Game of Life (rebuild of the MPI/OpenMP/CUDA reference)",
    )
    sub = parser.add_subparsers(dest="command")

    run = sub.add_parser("run", help="run a simulation (also the default command)")
    run.add_argument("width", nargs="?", default=None)
    run.add_argument("height", nargs="?", default=None)
    run.add_argument("input_file", nargs="?", default=None)
    run.add_argument(
        "--variant",
        default="tpu",
        choices=sorted(VARIANTS),
        help="which reference program to reproduce (default: the TPU-native flagship)",
    )
    run.add_argument(
        "--mesh", default=None,
        help="device mesh RxC (default: the row-heaviest factorization that "
        "divides the grid — row-only when possible, the measured-fastest "
        "layout; mesh columns are added automatically when the height "
        "doesn't divide row-only or the grid width would exceed the fast "
        "kernel's per-shard VMEM cap)")
    run.add_argument(
        "--kernel",
        default="auto",
        help="stencil kernel: auto (best for the shape/backend), lax, pallas, "
        "or packed (bitpacked fast path)",
    )
    run.add_argument("--gen-limit", type=int, default=GameConfig().gen_limit)
    run.add_argument(
        "--gens", type=int, default=None, metavar="N",
        help="alias for --gen-limit (the deep-time spelling: the macro "
        "engine reaches e.g. --gens 1000000000 in O(log N) jumps)",
    )
    run.add_argument(
        "--similarity-frequency", type=int, default=GameConfig().similarity_frequency
    )
    run.add_argument(
        "--pattern", default=None, metavar="FILE",
        help="run an RLE pattern file (Gosper gun, r-pentomino, ...) placed "
        "into an otherwise-empty --universe instead of reading a dense "
        "input file — the giant-universe input path: the byte canvas is "
        "never materialized on the sparse lane",
    )
    run.add_argument(
        "--place", default="0,0", metavar="X,Y",
        help="top-left cell of the --pattern placement (column X, row Y; "
        "default 0,0)",
    )
    run.add_argument(
        "--universe", default=None, metavar="WxH",
        help="universe extents for --pattern (e.g. 65536x65536); defaults "
        "to the pattern's own RLE extents",
    )
    run.add_argument(
        "--engine", default="auto", choices=("auto", "dense", "sparse",
                                             "macro", "shard"),
        help="engine family: dense (the classic O(area) lanes), sparse "
        "(tiled O(live-area) — gol_tpu/sparse), macro (hash-consed "
        "macrocell, O(log gens) deep time — gol_tpu/macro), shard (one "
        "giant universe spanning a fleet's workers with per-super-step "
        "halo exchange — gol_tpu/shard; needs --shard-across), or auto "
        "(sparse above the area threshold when the extents tile evenly, "
        "upgraded to macro above the generation threshold when the "
        "placement keeps the run off the torus seam)",
    )
    run.add_argument(
        "--shard-across", default=None, metavar="URL",
        help="fleet router URL for --engine shard: the universe is "
        "partitioned across the router's workers by rendezvous hashing "
        "over tile coordinates and run as coordinated super-steps; the "
        "result is byte-identical to the sparse engine's",
    )
    run.add_argument(
        "--tile", type=int, default=0, metavar="N",
        help="sparse/macro engine tile edge (default 256); universe "
        "extents must be multiples of it (and it must be even for macro "
        "— the macrocell leaf splits in half)",
    )
    run.add_argument(
        "--macro-cas", default=None, metavar="DIR",
        help="mount a disk CAS tier under the macro engine's advance memo "
        "(gol_tpu/cache): memoized superstep results persist across "
        "runs and restarts, and `gol gc` budgets the directory",
    )
    run.add_argument("--no-check-similarity", action="store_true")
    run.add_argument("--output", default=None, help="override the output file path")
    run.add_argument("--host", action="store_true", help="run the NumPy oracle on CPU")
    run.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="capture a jax.profiler trace of the run into DIR (start/stop "
        "guarded: a run with nothing to capture proceeds unprofiled, a "
        "crashed run never leaves a torn trace directory)",
    )
    run.add_argument(
        "--trace", default=None, metavar="DIR",
        help="span tracing + flight recorder (gol_tpu/obs): phase/engine "
        "spans export to DIR as Chrome trace JSON when the run ends; a "
        "crash additionally dumps the last spans as flight-*.jsonl at the "
        "moment of death; SIGUSR1 dumps live. Summarize either file with "
        "`gol trace-report`",
    )
    run.add_argument(
        "--snapshot-every",
        type=int,
        default=None,
        metavar="N",
        help="write a resumable grid snapshot every N generations "
        "(exec time then includes snapshot writes)",
    )
    run.add_argument(
        "--snapshot-dir", default=None, help="snapshot directory (default ./snapshots)"
    )
    run.add_argument(
        "--snapshot-format",
        choices=("text", "zarr"),
        default="text",
        help="snapshot encoding: 'text' writes gen_NNNNNN.out files (valid "
        "input files, the reference's output-is-input resume); 'zarr' "
        "(packed lane only) writes sharded TensorStore stores — every host "
        "writes only its own shards, no shared POSIX mmap needed (pod "
        "object stores); resume by passing the gen_NNNNNN.zarr path as the "
        "input file with --resume-gen N",
    )
    run.add_argument(
        "--resume-gen",
        type=int,
        default=0,
        metavar="N",
        help="treat the input file as the state after N generations (a "
        "gen_NNNNNN.out snapshot) and continue to --gen-limit with the "
        "similarity phase realigned — exits and the reported total match "
        "the uninterrupted run exactly; composes with --snapshot-every. "
        "The snapshot must come from a run that had NOT early-exited: "
        "resuming from the final output of an exited run (e.g. a still "
        "life) replays it as mid-run state and reports a different count",
    )
    run.add_argument(
        "--warmup",
        action="store_true",
        help="run the compiled program once, untimed, before the measured run "
        "(excludes one-time runtime init from Execution time); implicit with "
        "--snapshot-every, whose zero-step compile call does the same",
    )
    run.add_argument(
        "--packed-io",
        action="store_true",
        help="stream the file directly to/from bitpacked device state via the "
        "native codec (width must divide by 32 x mesh cols)",
    )
    run.add_argument(
        "--checkpoint-every",
        type=int,
        default=None,
        metavar="N",
        help="write a crash-consistent checkpoint (fresh payload + atomically "
        "committed manifest) every N generations; a crash at any point "
        "leaves the newest prior checkpoint readable",
    )
    run.add_argument(
        "--checkpoint-dir",
        default=None,
        metavar="D",
        help="checkpoint directory (default ./checkpoints)",
    )
    run.add_argument(
        "--checkpoint-keep",
        type=int,
        default=2,
        metavar="K",
        help="retain the K newest checkpoints (default 2; >= 1)",
    )
    run.add_argument(
        "--auto-resume",
        action="store_true",
        help="restart from the newest valid checkpoint manifest in "
        "--checkpoint-dir (every process must be able to read it on "
        "multihost runs) — no --resume-gen arithmetic; resumed runs are "
        "bit-exact with uninterrupted ones",
    )
    run.add_argument(
        "--disk-reserve",
        type=int,
        default=0,
        metavar="N",
        help="disk-pressure watchdog on the checkpoint directory "
        "(resilience/diskguard.py): below 2N free bytes checkpoint saves "
        "shed loudly (the run continues; auto-resume falls back to the "
        "previous committed checkpoint) and recover automatically. "
        "0 (default) disables the guard",
    )
    run.add_argument(
        "--sync-checkpoints",
        action="store_true",
        help="write checkpoints synchronously (device idle during payload "
        "write + fsync). Default is the async writer (gol_tpu/pipeline): a "
        "boundary costs only a device->host snapshot, the payload writes on "
        "a background thread under the next segment's compute, and the "
        "manifest commits at the next boundary — bit-identical outputs and "
        "payloads either way; this flag is the A/B lever",
    )
    run.add_argument(
        "--fault-plan",
        default=None,
        metavar="SPEC",
        help="fault injection for the crash-recovery harness, k=v comma "
        "list (see gol_tpu/resilience/faults.py; also honored from the "
        "GOL_FAULTS env var). Testing only.",
    )
    run.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persist XLA/Mosaic compiles in DIR (JAX persistent "
        "compilation cache): re-running a tuned shape skips recompilation",
    )
    run.set_defaults(func=_run)

    shw = sub.add_parser("show", help="render a grid in the terminal (VT100, src/game.c:42-58)")
    shw.add_argument("width")
    shw.add_argument("height")
    shw.add_argument("input_file")
    shw.add_argument("--animate", type=int, default=0, metavar="N", help="evolve N generations live")
    shw.add_argument("--fps", type=float, default=10.0)
    shw.set_defaults(func=_show)

    gen = sub.add_parser("generate", help="emit a random grid (replaces generate.sh)")
    gen.add_argument("width", type=int)
    gen.add_argument("height", type=int)
    gen.add_argument("-o", "--output", default=None)
    gen.add_argument("--seed", type=int, default=None)
    gen.add_argument("--density", type=float, default=0.5)
    gen.set_defaults(func=_generate)

    srv = sub.add_parser(
        "serve",
        help="run the batched multi-tenant simulation service (HTTP JSON API)",
    )
    srv.add_argument("--host", default="127.0.0.1")
    srv.add_argument("--port", type=int, default=8000,
                     help="listen port (0 = pick a free one; printed on boot)")
    srv.add_argument(
        "--journal-dir", default=None, metavar="D",
        help="crash-safe job journal directory; a restarted server replays "
        "unfinished jobs from it and keeps serving finished results "
        "(default: no journal — jobs do not survive restarts)",
    )
    srv.add_argument("--max-queue-depth", type=int, default=1024,
                     help="admission cap: past this, POST /jobs returns 429")
    srv.add_argument("--max-batch", type=int, default=64,
                     help="boards per dispatched batch (<= 64)")
    srv.add_argument(
        "--flush-age", type=float, default=0.05, metavar="S",
        help="dispatch a partial bucket once its oldest job has waited S "
        "seconds (the latency/occupancy trade)",
    )
    srv.add_argument("--max-inflight", type=int, default=1,
                     help="concurrently running batches (worker threads)")
    srv.add_argument(
        "--pipeline-depth", type=int, default=1,
        help="pipelined dispatch window: at N >= 2 the single synchronous "
        "worker becomes a dispatcher/completer pair with N batches in "
        "flight — the device computes batch k while the host stages k+1 "
        "and journals k-1 (try 2). Default 1 keeps the classic worker; "
        "exactly-once journal semantics, admission, drain, and retry are "
        "identical at every depth",
    )
    srv.add_argument(
        "--resident-ring", type=int, default=0, metavar="R",
        help="device-resident mega-batch lanes: each padding bucket gets a "
        "ring of R slots bound to ONE compiled drain program — the "
        "dispatcher refills slots (async device_put) while a drain "
        "computes, up to R batches dispatch as one program with every "
        "slot's output aliased over its input, and the per-batch Python "
        "dispatch tax disappears from the hot path. Needs "
        "--pipeline-depth >= 2 (>= 2R keeps the device stream fed); "
        "0 (default) keeps the per-batch lanes. Results are byte-identical "
        "either way",
    )
    srv.add_argument(
        "--result-cache", action="store_true",
        help="serve repeat boards from the content-addressed result cache "
        "(gol_tpu/cache): identical submissions complete at admission in "
        "O(1), identical in-flight submissions run the engine once. Hits "
        "are journaled as normal DONE records (exactly-once unchanged); "
        "per-job no_cache opts out. With --journal-dir the on-disk CAS "
        "tier defaults to <journal-dir>/cache",
    )
    srv.add_argument(
        "--cache-dir", default=None, metavar="DIR",
        help="on-disk CAS tier for the result cache (implies "
        "--result-cache): content-addressed CRC-gated entries that "
        "survive restarts; corrupt entries evict loudly and re-run",
    )
    srv.add_argument(
        "--cache-entries", type=int, default=1024, metavar="N",
        help="in-process result-cache LRU bound (default 1024 entries)",
    )
    srv.add_argument(
        "--cache-payload", choices=("packed", "text", "ts"), default="packed",
        help="CAS payload encoding: 'packed' (default — the binary wire "
        "frame, io/wire.py, ~8x smaller than text at any width; packed "
        "hits serve without a decode/re-encode round trip), 'text' "
        "(self-contained meta JSON) or 'ts' (TensorStore zarr via "
        "io/ts_store.py). Entries of every encoding read back on every "
        "setting; unavailable lanes fall back to text loudly",
    )
    srv.add_argument(
        "--cache-disk-bytes", type=int, default=None, metavar="N",
        help="byte budget for the on-disk CAS tier: past it the cache "
        "garbage-collects itself, least-recently-used entries first "
        "(gol_tpu/cache/gc.py — eviction is always safe, the journal "
        "stays the source of truth). Default: unbounded; `gol gc` runs "
        "the same pass offline",
    )
    srv.add_argument(
        "--journal-segment-bytes", type=int, default=None, metavar="N",
        help="rotate the job journal into sealed segments past N bytes "
        "(default 8 MiB); sealed segments compact into a CRC-stamped "
        "snapshot on idle sampler ticks, bounding the durable footprint "
        "(gol_tpu/serve/compaction.py; `gol compact` runs it offline). "
        "0 disables rotation (the unbounded single-file journal)",
    )
    srv.add_argument(
        "--journal-retain", type=int, default=None, metavar="N",
        help="result-retention window: compaction keeps only the newest N "
        "terminal records in the snapshot — results older than the window "
        "answer 404 after a restart. Default: retain every result "
        "(replayed state identical to the unbounded log)",
    )
    srv.add_argument(
        "--disk-reserve", type=int, default=0, metavar="N",
        help="disk-pressure watchdog (resilience/diskguard.py): when free "
        "bytes on the journal partition fall below 4N the CAS stops "
        "taking writes, below 2N checkpoints shed, below N POST /jobs "
        "answers 507 (naming the partition and free bytes) while "
        "in-flight jobs still complete and journal; recovery is "
        "automatic with 25%% hysteresis. 0 (default) disables the guard",
    )
    srv.add_argument(
        "--warm-plans", action="store_true",
        help="pre-compile the bucket programs of every serve shape recorded "
        "by `gol tune` before accepting traffic",
    )
    srv.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persist XLA/Mosaic compiles in DIR (JAX persistent "
        "compilation cache): restarted servers skip recompilation",
    )
    srv.add_argument(
        "--trace", default=None, metavar="DIR",
        help="span tracing + flight recorder: per-batch spans (one per "
        "dispatched bucket batch) export to DIR as Chrome trace JSON on "
        "shutdown; GET /debug/trace snapshots them live; crashes dump "
        "flight-*.jsonl; SIGUSR1 dumps without stopping the server",
    )
    srv.add_argument(
        "--slo-shed", action="store_true",
        help="shed load when an SLO burn is critical: POST /jobs answers "
        "429 + Retry-After until the burn clears. Default is observe-only "
        "(burns log and export at GET /slo; admission is untouched)",
    )
    srv.add_argument(
        "--slo-latency-p99", type=float, default=60.0, metavar="S",
        help="the per-priority-class p99 end-to-end latency objective in "
        "seconds (default 60); error-rate (1%%) and queue-saturation (80%%) "
        "objectives are built in — see gol_tpu/obs/slo.py",
    )
    srv.add_argument(
        "--sample-interval", type=float, default=1.0, metavar="S",
        help="seconds between SLO/dispatch-gap sampler ticks (the "
        "gol-serve-sampler thread); <= 0 disables the background sampler "
        "(GET /slo then evaluates on demand)",
    )
    srv.add_argument(
        "--metrics-history", nargs="?", const="auto", default=None,
        metavar="DIR",
        help="durable metrics history (gol_tpu/obs/history.py): every "
        "sampler tick appends the serving metrics snapshot to a "
        "size-capped append-only JSONL ring in DIR, surviving restarts "
        "(render with `gol history-report DIR`, gate windows with "
        "tools/bench_diff.py --history). With no DIR the ring lands at "
        "<journal-dir>/history. Default: off (no per-tick cost)",
    )
    srv.add_argument(
        "--history-bytes", type=int, default=None, metavar="N",
        help="metrics-history ring cap in bytes (default 16 MiB); oldest "
        "segments compact away past it",
    )
    srv.add_argument(
        "--retry-budget", type=float, default=0.0, metavar="N",
        help="token-bucket budget on batch dispatch RETRIES (N tokens, "
        "refilled over a minute): under a brownout the scheduler degrades "
        "to first-attempt-only dispatch — surfacing the original error — "
        "instead of amplifying the overload with retry traffic. 0 "
        "(default) = unlimited, the pre-budget behavior",
    )
    srv.set_defaults(func=_serve)

    flt = sub.add_parser(
        "fleet",
        help="run the sharded serving fleet: a router front-end over N "
        "`gol serve` workers (same HTTP job API, bucket-consistent "
        "routing, partitioned journals, health-aware placement, "
        "fleet-wide drain)",
    )
    flt.add_argument("--host", default="127.0.0.1")
    flt.add_argument("--port", type=int, default=8000,
                     help="router listen port (0 = pick a free one)")
    flt.add_argument(
        "--workers", type=int, default=2, metavar="N",
        help="local worker subprocesses to run (default 2; partitions "
        "recovered from an existing --fleet-dir manifest count toward N)",
    )
    flt.add_argument(
        "--attach", action="append", default=[], metavar="URL",
        help="adopt an externally managed `gol serve` by URL (repeatable; "
        "the multi-host lane — boot workers where parallel/bootstrap.py "
        "put the devices, hand the router their URLs). Attached workers "
        "are health-checked and routed around, never respawned",
    )
    flt.add_argument(
        "--fleet-dir", default="./fleet", metavar="D",
        help="fleet state directory: the membership manifest plus one "
        "journal partition per local worker (default ./fleet). Restarting "
        "on the same directory reattaches live workers and respawns dead "
        "partitions, whose journals replay to exactly-once",
    )
    flt.add_argument(
        "--big-lane", action="store_true",
        help="spawn one dedicated worker for oversized boards (padded "
        "edge > --big-edge): giant compiles and batches never block the "
        "bucket workers",
    )
    flt.add_argument(
        "--big-edge", type=int, default=1024, metavar="N",
        help="padded board edge above which jobs route to the big-lane "
        "worker when one exists (default 1024)",
    )
    flt.add_argument(
        "--health-interval", type=float, default=1.0, metavar="S",
        help="seconds between worker health/burn probes (default 1)",
    )
    # Worker passthrough flags (forwarded to every spawned `gol serve`).
    flt.add_argument("--max-queue-depth", type=int, default=1024)
    flt.add_argument("--max-batch", type=int, default=64)
    flt.add_argument("--flush-age", type=float, default=0.05, metavar="S")
    flt.add_argument("--pipeline-depth", type=int, default=1)
    flt.add_argument("--resident-ring", type=int, default=0, metavar="R")
    flt.add_argument(
        "--warm-plans", action="store_true",
        help="each worker pre-compiles its tuner-recorded bucket programs "
        "at boot (per-worker plan warm-up from the shared plan cache)",
    )
    flt.add_argument("--compile-cache", default=None, metavar="DIR")
    flt.add_argument(
        "--result-cache", action="store_true",
        help="each worker mounts the tiered result cache (LRU + a CAS tier "
        "on its own journal partition) — repeat boards complete at "
        "admission; see `gol serve --result-cache`",
    )
    flt.add_argument(
        "--cache-route", action="store_true",
        help="route submissions by result FINGERPRINT instead of padding "
        "bucket (the fleet cache tier): every repeat of a board lands on "
        "the one worker whose cache holds its answer, and hot patterns "
        "spread across workers by fingerprint. Trade: a bucket's programs "
        "may compile on several workers (one-time, bought back by every "
        "repeat). Pair with --result-cache",
    )
    flt.add_argument(
        "--cache-disk-bytes", type=int, default=None, metavar="N",
        help="forwarded to every worker: per-partition CAS byte budget "
        "with LRU garbage collection (see `gol serve --cache-disk-bytes`)",
    )
    flt.add_argument(
        "--journal-segment-bytes", type=int, default=None, metavar="N",
        help="forwarded to every worker: journal segment rotation "
        "threshold (see `gol serve --journal-segment-bytes`)",
    )
    flt.add_argument(
        "--journal-retain", type=int, default=None, metavar="N",
        help="forwarded to every worker: result-retention window at "
        "compaction (see `gol serve --journal-retain`)",
    )
    flt.add_argument(
        "--disk-reserve", type=int, default=0, metavar="N",
        help="forwarded to every worker: per-partition disk-pressure "
        "watchdog — a full-disk partition sheds CAS writes, then "
        "checkpoints, then 507s new admission, alone, while the rest of "
        "the fleet serves (see `gol serve --disk-reserve`)",
    )
    flt.add_argument("--slo-shed", action="store_true")
    flt.add_argument("--slo-latency-p99", type=float, default=60.0,
                     metavar="S")
    flt.add_argument("--sample-interval", type=float, default=1.0,
                     metavar="S")
    flt.add_argument(
        "--trace", default=None, metavar="DIR",
        help="fleet-wide span tracing: arms the router AND every spawned "
        "worker (one pid-qualified export per process in DIR), and stamps "
        "X-Gol-Trace onto forwarded submits so worker spans join the "
        "router's trace. Stitch every live process's ring into ONE "
        "Perfetto timeline with `gol fleet-trace`",
    )
    flt.add_argument(
        "--metrics-history", action="store_true",
        help="durable metrics history for the whole fleet: every worker "
        "appends its snapshot ring beside its journal partition "
        "(<partition>/history) and the router appends the fleet-MERGED, "
        "respawn-floored view to <fleet-dir>/router-history — the "
        "cumulative series stay monotonic through worker respawns. "
        "Render with `gol history-report <dir>`",
    )
    flt.add_argument("--history-bytes", type=int, default=None, metavar="N",
                     help="per-process history ring cap in bytes "
                     "(default 16 MiB)")
    # The elastic fleet (gol_tpu/fleet/autoscale.py + affinity.py).
    flt.add_argument(
        "--autoscale", action="store_true",
        help="close the loop: spawn workers when SLO burn rates or queue "
        "saturation climb (up to --max-workers), drain+retire the "
        "emptiest when occupancy stays below the floor (down to "
        "--min-workers). Every decision is journaled to "
        "<fleet-dir>/autoscaler-history and visible in `gol top`",
    )
    flt.add_argument(
        "--min-workers", type=int, default=None, metavar="N",
        help="autoscaler floor (default: the --workers count)",
    )
    flt.add_argument(
        "--max-workers", type=int, default=None, metavar="N",
        help="autoscaler ceiling (default: max(4, --workers))",
    )
    flt.add_argument(
        "--scale-up-saturation", type=float, default=0.8, metavar="F",
        help="scale up when merged queue depth exceeds this fraction of "
        "the fleet-wide admission cap, sustained --scale-up-sustain ticks "
        "(default 0.8); SLO-critical burn on every window also triggers",
    )
    flt.add_argument(
        "--scale-down-occupancy", type=float, default=0.05, metavar="F",
        help="retire a worker when queued+inflight stays below this "
        "fraction of the cap for --scale-down-sustain ticks (default "
        "0.05; the wide gap to --scale-up-saturation is the hysteresis "
        "dead band)",
    )
    flt.add_argument("--scale-up-sustain", type=int, default=2, metavar="T",
                     help="consecutive health ticks the up condition must "
                     "hold (default 2)")
    flt.add_argument("--scale-down-sustain", type=int, default=10,
                     metavar="T",
                     help="consecutive health ticks the down condition "
                     "must hold (default 10)")
    flt.add_argument(
        "--scale-cooldown", type=float, default=30.0, metavar="S",
        help="seconds after any scale event before the next decision can "
        "fire (default 30; flap protection on top of the sustain windows)",
    )
    flt.add_argument(
        "--cores-per-worker", type=int, default=0, metavar="N",
        help="pin worker k to its own N-core `taskset` slice (local "
        "spawns only; autoscaled workers land on distinct slices) and "
        "weight it N for --affinity placement. 0 = no pinning (default)",
    )
    flt.add_argument(
        "--affinity", action="store_true",
        help="affinity-aware placement: rank workers by weighted HRW over "
        "per-worker capacity weights (--cores-per-worker pins, or each "
        "worker's tuned marginal rate advertised on /healthz) instead of "
        "hash rank alone. Off (the default) — and on with no weights "
        "configured — is byte-identical to plain HRW placement",
    )
    # The chaos-hardened data path (gol_tpu/chaos + fleet/breaker.py).
    flt.add_argument(
        "--no-breakers", action="store_true",
        help="disable the per-worker circuit breakers (on by default: "
        "consecutive failures or a degraded fraction of recent calls "
        "rank a worker LAST — never removed, so HRW bucket affinity "
        "survives recovery — until a half-open probe succeeds)",
    )
    flt.add_argument(
        "--breaker-cooldown", type=float, default=5.0, metavar="S",
        help="seconds an OPEN breaker holds before its single half-open "
        "probe (default 5)",
    )
    flt.add_argument(
        "--breaker-slow", type=float, default=1.0, metavar="S",
        help="forward latency above S seconds counts as degraded toward "
        "the breaker's windowed trip (default 1.0; <= 0 disables the "
        "latency signal)",
    )
    flt.add_argument(
        "--retry-budget", type=float, default=0.0, metavar="N",
        help="forwarded to every worker: token-bucket budget on batch "
        "dispatch retries (see `gol serve --retry-budget`)",
    )
    flt.add_argument(
        "--chaos", default=None, metavar="PLAN",
        help="mount a seeded fault-injecting proxy (gol_tpu/chaos) on the "
        "router->worker data path: PLAN is a k=v list, e.g. "
        "'seed=7,reset=0.05,latency=0.2,latency_ms=50,bitflip=0.05' "
        "(classes: refuse, reset, truncate, slowloris, bitflip, latency). "
        "Health probes stay direct — chaos exercises the data plane's "
        "defenses, not the supervisor. NEVER set this in production",
    )
    flt.add_argument(
        "--routers", type=int, default=1, metavar="N",
        help="total router replicas over this fleet (default 1). N-1 "
        "extra `gol router` subprocesses boot from the shared manifest, "
        "serve the full job API active-active, and contest the leader "
        "lease for the single-writer ticks — kill any one (the leader "
        "included) and the survivors carry the control plane",
    )
    flt.set_defaults(func=_fleet)

    rtr = sub.add_parser(
        "router",
        help="one attachable router replica over a running fleet: boots "
        "from the shared manifest (membership + config), inherits the "
        "durable floors/breaker state, contests the leader lease. "
        "SIGTERM stops this replica only — never the workers",
    )
    rtr.add_argument("--fleet-dir", required=True, metavar="DIR",
                     help="the running fleet's --fleet-dir (the manifest "
                     "is the only coordination channel)")
    rtr.add_argument("--router-id", required=True, metavar="ID",
                     help="this replica's identity (its durable state "
                     "lives under <fleet-dir>/routers/<ID>/)")
    rtr.add_argument("--host", default="127.0.0.1")
    rtr.add_argument("--port", type=int, default=0,
                     help="0 = any free port (default; the URL is "
                     "advertised in <fleet-dir>/routers/<ID>/advert.json)")
    rtr.set_defaults(func=_router)

    cpt = sub.add_parser(
        "compact",
        help="offline journal compaction: fold sealed segments into the "
        "CRC-stamped snapshot and retire them (a journal dir, or a fleet "
        "dir whose partitions compact independently)",
    )
    cpt.add_argument("dir", help="journal directory or fleet directory")
    cpt.add_argument(
        "--retain", type=int, default=None, metavar="N",
        help="keep only the newest N terminal records in the snapshot "
        "(the result-retention window; default: all)",
    )
    cpt.set_defaults(func=_compact_cmd)

    gcp = sub.add_parser(
        "gc",
        help="CAS garbage collection: sweep orphans + evict LRU entries "
        "to a byte budget (dry-run by default; --apply deletes)",
    )
    gcp.add_argument("dir", help="cache (CAS) directory")
    gcp.add_argument(
        "--budget", type=int, default=None, metavar="BYTES",
        help="target byte budget (default: sweep garbage only)",
    )
    gcp.add_argument("--apply", action="store_true",
                     help="actually delete (default is a dry-run report)")
    gcp.set_defaults(func=_gc_cmd)

    tun = sub.add_parser(
        "tune",
        help="offline measured search: pick kernel/depth/block/bucket plans "
        "and persist them to the plan cache (gol_tpu/tune/)",
    )
    tun.add_argument(
        "--shape", action="append", metavar="HxW",
        help="engine grid shape(s) to tune (repeatable; default 256x256)",
    )
    tun.add_argument(
        "--convention", choices=("c", "cuda", "both"), default="both",
        help="loop-accounting convention(s) to tune (default: both)",
    )
    tun.add_argument("--mesh", default=None,
                     help="tune the RxC-mesh context instead of single-device")
    tun.add_argument(
        "--gen-limit", type=int, default=64,
        help="generations per timed trial (default 64: long enough that the "
        "loop dominates dispatch, short enough to search exhaustively)",
    )
    tun.add_argument("--iters", type=int, default=5,
                     help="timed trials per candidate (trimmed median)")
    tun.add_argument(
        "--quick", action="store_true",
        help="prune the depth/block axes to their extremes (smoke/CI)",
    )
    tun.add_argument(
        "--packed", action="store_true",
        help="also tune the packed-state family (the --packed-io lane "
        "consults its own plans; widths must divide by 32)",
    )
    tun.add_argument(
        "--sparse-crossover", action="store_true",
        help="also measure the dense/sparse engine crossover on this host "
        "and persist it as the `--engine auto` area threshold (default: "
        "the bundled BENCH_r14 crossover, 2^25 cells)",
    )
    tun.add_argument(
        "--serve-board", default=None, metavar="HxW",
        help="also tune the serve batcher's bucket geometry on this request "
        "shape (recorded for `gol serve --warm-plans`)",
    )
    tun.add_argument(
        "--plan-cache", default=None, metavar="FILE",
        help="plan cache file (default: $GOL_PLAN_CACHE or "
        "~/.cache/gol_tpu/plans.json)",
    )
    tun.add_argument("--report", default=None, metavar="FILE",
                     help="write the human-readable report here")
    tun.add_argument(
        "--provenance", action="store_true",
        help="store the full per-candidate measurement series in the plan "
        "cache, not just the winner",
    )
    tun.add_argument(
        "--compile-cache", default=None, metavar="DIR",
        help="persist XLA/Mosaic compiles in DIR while searching",
    )
    tun.add_argument(
        "--trace", default=None, metavar="DIR",
        help="span tracing + flight recorder: per-trial events export to "
        "DIR as Chrome trace JSON when the search ends (SIGUSR1 dumps a "
        "long search's progress live)",
    )
    tun.set_defaults(func=_tune)

    ftr = sub.add_parser(
        "fleet-trace",
        help="stitch the live span rings of a whole fleet (router + every "
        "worker) into ONE clock-normalized Perfetto trace file with "
        "cross-process flow arrows per job",
    )
    ftr.add_argument("--server", default="http://127.0.0.1:8000",
                     help="the fleet router (or a single gol serve) URL")
    ftr.add_argument("--servers", default=None, metavar="A,B,C",
                     help="comma-separated router REPLICA URLs over one "
                     "fleet (overrides --server): the export tries each "
                     "in turn until one answers")
    ftr.add_argument("-o", "--output", default="fleet-trace.json",
                     help="stitched Chrome trace JSON path "
                     "(default fleet-trace.json)")
    ftr.set_defaults(func=_fleet_trace)

    hrp = sub.add_parser(
        "history-report",
        help="render a durable metrics-history ring (--metrics-history) as "
        "rate/value/percentile timelines with respawn boundaries marked",
    )
    hrp.add_argument("history_dir", help="a history directory "
                     "(e.g. <journal>/history or <fleet>/router-history)")
    hrp.set_defaults(func=_history_report)

    rpt = sub.add_parser(
        "trace-report",
        help="summarize a trace file (Chrome trace JSON from --trace, or a "
        "flight-recorder JSONL dump): per-phase p50/p95, span tree, gap "
        "analysis",
    )
    rpt.add_argument("trace_file", help="trace-*.json or flight-*.jsonl")
    rpt.set_defaults(func=_trace_report)

    topp = sub.add_parser(
        "top",
        help="live terminal dashboard over a running gol serve: queue "
        "depths, ring occupancy, latency percentiles, SLO burn rates, and "
        "the live dispatch-gap ratio",
    )
    topp.add_argument("--server", default="http://127.0.0.1:8000")
    topp.add_argument("--servers", default=None, metavar="A,B,C",
                      help="comma-separated router REPLICA URLs over one "
                      "fleet (overrides --server): each frame follows "
                      "whichever replica answers, and the title names it")
    topp.add_argument("--interval", type=float, default=2.0, metavar="S",
                      help="seconds between refreshes (default 2)")
    topp.add_argument(
        "--iterations", type=int, default=0, metavar="N",
        help="exit after N frames (default 0 = run until interrupted)",
    )
    topp.add_argument(
        "--no-ansi", action="store_true",
        help="plain frames, no screen clearing/colors (also automatic when "
        "stdout is not a terminal)",
    )
    topp.set_defaults(func=_top)

    slr = sub.add_parser(
        "slo-report",
        help="summarize SLO state from a running server's /slo endpoint or "
        "from a flight-recorder dump's slo state record",
    )
    slr.add_argument(
        "target",
        help="server URL (http://...) or a flight-*.jsonl dump path",
    )
    slr.set_defaults(func=_slo_report)

    sbm = sub.add_parser(
        "submit", help="submit jobs to a running gol serve and fetch results"
    )
    sbm.add_argument("width")
    sbm.add_argument("height")
    sbm.add_argument("input_files", nargs="+")
    sbm.add_argument("--server", default="http://127.0.0.1:8000")
    sbm.add_argument(
        "--servers", default=None, metavar="A,B,C",
        help="comma-separated router REPLICA URLs over one fleet "
        "(overrides --server): job-creating POSTs fail over ONLY on "
        "delivery-impossible errors (refused/DNS/unreachable — nothing "
        "reached any queue); ambiguous failures surface for audit, never "
        "blind-resubmit. Status/result GETs rotate freely",
    )
    sbm.add_argument(
        "--variant", default="tpu", choices=sorted(VARIANTS),
        help="reference program whose loop accounting the jobs use",
    )
    sbm.add_argument("--gen-limit", type=int, default=GameConfig().gen_limit)
    sbm.add_argument("--priority", type=int, default=0)
    sbm.add_argument("--deadline", type=float, default=None, metavar="S",
                     help="dispatch-ordering deadline, seconds from acceptance")
    sbm.add_argument(
        "--timeout", type=float, default=None, metavar="S",
        help="end-to-end latency BUDGET per job, propagated as the "
        "X-Gol-Deadline header and decremented per hop: the router stops "
        "forwarding, the worker refuses admission, and the scheduler "
        "skips dispatch once the budget is spent — each answering 504 "
        "(with the job's timeline attached at the dispatch gate) instead "
        "of burning capacity on an answer nobody is waiting for. Old "
        "servers ignore the header (behavior unchanged). Unlike "
        "--deadline, which only ORDERS dispatch, --timeout abandons work",
    )
    sbm.add_argument("--no-wait", dest="wait", action="store_false",
                     help="submit and print job ids without polling")
    sbm.add_argument(
        "--no-cache", action="store_true",
        help="opt these submissions out of the server's result cache "
        "(always a fresh engine run); result lines from cache-served "
        "repeats carry a 'cached:<tier>' marker otherwise",
    )
    sbm.add_argument(
        "--wire", choices=("text", "packed"), default="text",
        help="wire format for boards (io/wire.py): 'packed' submits binary "
        "frames (~8x fewer bytes than text) and fetches results with "
        "Accept: application/x-gol-packed. Degrades gracefully against "
        "old servers: a 415/400 submit answer retries as text (once, "
        "logged, per target), and JSON result answers parse as always",
    )
    sbm.add_argument("--poll-interval", type=float, default=0.2)
    sbm.add_argument(
        "--server-timeout", type=float, default=60.0, metavar="S",
        help="give up after S seconds without server contact while polling "
        "(transient connection errors — e.g. a server restart mid-replay — "
        "are retried until then)",
    )
    sbm.add_argument("--output-dir", default=None,
                     help="write results here (default: next to each input)")
    sbm.add_argument(
        "--shard-across", action="store_true",
        help="against a fleet router (`gol fleet`), fan the boards "
        "round-robin over the fleet's workers directly (GET /fleet lists "
        "them) instead of routing every submit through the front-end; "
        "membership is re-fetched every --shard-refresh seconds (and on "
        "a 429) so autoscaled workers absorb the load mid-submission; "
        "a no-op against a single `gol serve`",
    )
    sbm.add_argument(
        "--shard-refresh", type=float, default=5.0, metavar="S",
        help="seconds between --shard-across membership re-fetches "
        "(default 5)",
    )
    sbm.set_defaults(func=_submit)

    bat = sub.add_parser(
        "batch",
        help="offline batched lane: run N input files through the padding-"
        "bucket batcher in one process",
    )
    bat.add_argument("width")
    bat.add_argument("height")
    bat.add_argument("input_files", nargs="+")
    bat.add_argument(
        "--variant", default="tpu", choices=sorted(VARIANTS),
        help="reference program whose loop accounting the jobs use",
    )
    bat.add_argument("--gen-limit", type=int, default=GameConfig().gen_limit)
    bat.add_argument("--max-batch", type=int, default=64)
    bat.add_argument("--output-dir", default=None,
                     help="write results here (default: next to each input)")
    bat.set_defaults(func=_batch)
    return parser


def main(argv: list[str] | None = None) -> int:
    honor_platform_env()
    configure_cli_logging()
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    # Default command is `run`, preserving the bare `<w> <h> <file>` contract.
    if not argv or argv[0] not in (
        "run", "generate", "show", "serve", "fleet", "router", "submit",
        "batch", "tune", "trace-report", "fleet-trace", "history-report",
        "top", "slo-report", "compact", "gc", "-h", "--help"
    ):
        argv = ["run", *argv]
    args = build_parser().parse_args(argv)
    # --trace DIR (run/serve/tune): span tracing + flight recorder armed
    # before the lane starts; the Chrome trace exports when the lane ends
    # (including error returns and crash unwinds — a failed run's trace is
    # evidence). Arming happens INSIDE the try so a bad --trace path (a
    # file, an unwritable parent) gets the CLI's `gol: <error>` contract.
    export_trace = lambda: None  # noqa: E731 - replaced once arming succeeds
    try:
        export_trace = _arm_observability(getattr(args, "trace", None))
        return args.func(args)
    except (ValueError, OSError) as e:
        print(f"gol: {e}", file=sys.stderr)
        return 1
    finally:
        try:
            export_trace()
        except OSError as e:
            # A failed export (dir deleted mid-run, disk full) must not
            # mask the lane's result or crash a successful run.
            print(f"gol: trace export failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
