"""VT100 terminal renderer — the reference's unused ``show()`` made usable.

The serial reference carries a VT100 renderer that nothing calls
(src/game.c:42-58): cursor-home, reverse-video double-space for a live cell,
plain double-space for dead, next-line code per row. This module reproduces
that exact escape-code output and wires it to a CLI subcommand (``gol show``)
with optional live animation via the host oracle.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from gol_tpu import oracle

_HOME = "\033[H"
_LIVE = "\033[07m  \033[m"  # reverse video, two spaces (src/game.c:51)
_DEAD = "  "
_NEXT_LINE = "\033[E"
_CLEAR = "\033[2J"


def frame(grid: np.ndarray) -> str:
    """One grid as the reference's escape-code string (src/game.c:42-58)."""
    rows = [
        "".join(_LIVE if cell else _DEAD for cell in row) + _NEXT_LINE
        for row in np.asarray(grid)
    ]
    return _HOME + "".join(rows)


def show(grid: np.ndarray, out=None) -> None:
    out = out or sys.stdout
    out.write(frame(grid))
    out.flush()


def animate(
    grid: np.ndarray,
    generations: int,
    fps: float = 10.0,
    out=None,
    sleep=time.sleep,
) -> np.ndarray:
    """Render ``generations`` oracle steps live; returns the final grid."""
    out = out or sys.stdout
    out.write(_CLEAR)
    show(grid, out)
    delay = 1.0 / fps if fps > 0 else 0.0
    for _ in range(generations):
        grid = oracle.evolve(grid)
        if delay:
            sleep(delay)
        show(grid, out)
        if not grid.any():
            break
    return grid
