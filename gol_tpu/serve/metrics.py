"""Serving metrics: a thin façade over the shared obs registry.

PR 2 built the counter/gauge/histogram registry here; PR 4 hoisted the
implementation into ``gol_tpu/obs/registry.py`` so the engine, resilience,
and tune layers can feed the same machinery. This module keeps the serving
surface exactly as it was — ``Metrics`` (prefix ``gol_serve``), exported by
the server as ``snapshot()`` JSON and ``prometheus()`` text — and both
output contracts are byte-stable across the move (pinned test-for-test by
tests/test_serve.py and tests/test_obs.py).

Latency sources remain ``time.perf_counter()`` exclusively; the wall-clock
ban of tests/test_lint.py covers this package and gol_tpu/obs alike.
"""

from __future__ import annotations

from gol_tpu.obs.registry import QUANTILES, Registry, _fmt  # noqa: F401

# Kept importable under its historical name (PR 2 tests and embedders).
from gol_tpu.obs.registry import Histogram as _Histogram  # noqa: F401


class Metrics(Registry):
    """Registry of named counters, gauges, and histograms (serving prefix)."""

    def __init__(self, prefix: str = "gol_serve"):
        super().__init__(prefix=prefix)
