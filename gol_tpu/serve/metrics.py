"""Serving metrics registry: counters, gauges, latency histograms.

Stdlib-only and thread-safe (the accept path, worker threads, and the
metrics endpoint all touch it concurrently). Exported two ways by the
server: ``snapshot()`` as JSON and ``prometheus()`` as the text exposition
format, so both a human with curl and a scraper get the same numbers.

Latency sources are ``time.perf_counter()`` exclusively — monotonic, never
stepped by NTP. The wall clock is banned from this package's latency paths
by tests/test_lint.py; a clock that jumps backward mid-sample turns a p99
into fiction.

Histograms keep a bounded reservoir of the most recent samples (simple,
predictable memory; quantiles over "recent traffic" is what an operator
watching a serving system wants anyway) plus exact running count/sum.
"""

from __future__ import annotations

import collections
import threading

# Quantiles exported for every histogram.
QUANTILES = (0.5, 0.95, 0.99)

_RESERVOIR = 2048  # samples kept per histogram (most recent)


class _Histogram:
    __slots__ = ("samples", "count", "total")

    def __init__(self):
        self.samples = collections.deque(maxlen=_RESERVOIR)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        self.samples.append(float(value))
        self.count += 1
        self.total += float(value)

    def quantile(self, q: float) -> float | None:
        if not self.samples:
            return None
        ordered = sorted(self.samples)
        # Nearest-rank on the recent reservoir.
        idx = min(len(ordered) - 1, max(0, round(q * (len(ordered) - 1))))
        return ordered[idx]

    def summary(self) -> dict:
        out = {"count": self.count, "sum": self.total}
        for q in QUANTILES:
            v = self.quantile(q)
            out[f"p{int(q * 100)}"] = v
        return out


class Metrics:
    """Registry of named counters, gauges, and histograms."""

    def __init__(self, prefix: str = "gol_serve"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Histogram] = {}

    def inc(self, name: str, amount: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._hists.setdefault(name, _Histogram()).observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> dict:
        """Point-in-time JSON-able view of everything."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }

    def prometheus(self) -> str:
        """Prometheus text exposition format (quantiles as summary series)."""
        snap = self.snapshot()
        p = self.prefix
        lines: list[str] = []
        for name, value in sorted(snap["counters"].items()):
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {_fmt(value)}")
        for name, value in sorted(snap["gauges"].items()):
            lines.append(f"# TYPE {p}_{name} gauge")
            lines.append(f"{p}_{name} {_fmt(value)}")
        for name, summary in sorted(snap["histograms"].items()):
            lines.append(f"# TYPE {p}_{name} summary")
            for q in QUANTILES:
                v = summary.get(f"p{int(q * 100)}")
                if v is not None:
                    lines.append(f'{p}_{name}{{quantile="{q}"}} {_fmt(v)}')
            lines.append(f"{p}_{name}_sum {_fmt(summary['sum'])}")
            lines.append(f"{p}_{name}_count {_fmt(summary['count'])}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    # Prometheus wants plain decimal/scientific; repr of a float is both.
    return repr(float(v)) if isinstance(v, float) and not v.is_integer() else str(int(v))
