"""Admission control, batch forming, and dispatch.

The queueing half of the serving story. Jobs arrive one at a time; the
scheduler pools them per padding bucket and flushes a bucket to the device
when it is *worth a dispatch*:

- **size**: the bucket reached ``max_batch`` boards (a full program), or
- **age**: its oldest job has waited ``flush_age`` seconds (bounded latency
  for sparse traffic), or
- **deadline**: some job's deadline is due, or
- **drain**: the server is shutting down and flushes everything queued.

Which ready bucket goes first — and which jobs within it when it holds more
than a batch — follows ``Job.dispatch_key``: priority first, then nearest
deadline, then arrival. Deadlines order dispatch; they do not abandon work
(a job past its deadline runs at the front, not never — dropping accepted
jobs would violate the journal's every-accepted-job-terminates contract).

Admission control is a hard queue-depth cap: past it ``submit`` raises
``QueueFull`` (the server maps it to HTTP 429) instead of letting the queue
grow unboundedly while compile-warming buckets.

Dispatch is wrapped in the tree's one ``RetryPolicy``: a transient device
error retries the whole batch (GoL runs are pure functions of the input, so
a re-run is idempotent); a persistent one fails the batch's jobs with the
error recorded in journal and job state.

Graceful drain: ``drain()`` stops admission, flushes every queued bucket,
and returns when the last in-flight batch completes — the SIGTERM story for
``gol serve``.

**Result cache** (``cache=ResultCache(...)``, ``gol serve
--result-cache``): the scheduler consults the tiered content-addressed
cache (gol_tpu/cache) BEFORE enqueueing work. A hit completes the job at
admission — journaled as a completely normal DONE record, so exactly-once
and replay semantics are unchanged (a crash between the submit and done
records re-runs the job idempotently, exactly like a lost engine-path
record). A miss registers the job's fingerprint as *in flight*: further
identical submissions coalesce behind that leader and are all completed —
each with its own journaled DONE — by the leader's single engine run.
Engine results write through to every tier; ``no_cache`` jobs bypass all
of it. The cache is an accelerator, never a source of truth.

**Pipelined dispatch** (``pipeline_depth`` >= 2, ``gol serve
--pipeline-depth``): the single synchronous worker — stage, compute,
readback, journal strictly in series, host idle while the device computes
and vice versa — is replaced by a two-thread pipeline over a bounded
in-flight window: a *dispatcher* claims batches, stages host operands
(``batcher.stage``: stacking + ``packbits``), and posts the async device
dispatch without blocking; a *completer* blocks on readback, journals, and
finalizes — so the device computes batch N while the host stages N+1 and
journals N-1 (the iwrite/wait-at-next-boundary discipline of the
reference's async variant, applied to batch dispatch;
gol_tpu/pipeline/inflight.py is the handoff). Everything observable is
preserved: exactly-once journal semantics, admission caps, drain, and
per-batch retry (the retry wraps dispatch+complete of one batch — a
failed completion re-dispatches from the retained host staging), and
COMPLETION order, not submission order, drives ``inflight_batches``. At
the default depth 1 the original worker loop runs, untouched.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any

from gol_tpu.cache.fingerprint import job_fingerprint
from gol_tpu.cache.store import CacheEntry
from gol_tpu.obs import trace as obs_trace
from gol_tpu.obs.registry import metric_label
from gol_tpu.resilience.retry import RetryPolicy, is_transient_io
from gol_tpu.serve import batcher
from gol_tpu.serve.batcher import BucketKey, bucket_for, pad_batch
from gol_tpu.serve.jobs import (
    CANCELLED, DONE, FAILED, QUEUED, RUNNING, SCHEDULED,
    Job, JobJournal, JobResult, priority_class,
)
from gol_tpu.serve.metrics import Metrics

logger = logging.getLogger(__name__)


class QueueFull(Exception):
    """Admission rejected: the queue is at max depth."""


class Draining(Exception):
    """Admission rejected: the server is draining."""


class JournalUnavailable(Exception):
    """Admission rejected: the SUBMIT record could not be journaled.

    The asymmetry with terminal records is the whole point: a lost *done*
    record costs an idempotent re-run after a restart (the job is still in
    the journal), so ``_journal_append`` survives ENOSPC/EIO there. A lost
    *submit* record is a job the server acknowledged but the journal never
    heard of — it would silently VANISH on replay, breaking the
    every-accepted-job-terminates contract. So a failing submit append
    refuses the accept instead: the server maps this to HTTP 503 (the
    client's retry signal; nothing was admitted, nothing will run)."""


class DeadlineExceeded(Exception):
    """The job's propagated deadline budget (X-Gol-Deadline) is spent.

    Raised at admission when the budget arrives already expired (the server
    maps it to HTTP 504 without creating a job) and used as the failure
    error at batch dispatch when a queued job's budget runs out before the
    device sees it — the job terminates (journaled FAILED, so the
    every-accepted-job-terminates contract holds) and ``GET /result``
    answers 504 with the job's timeline attached instead of 410."""


# Dispatch retry: a transient device/runtime hiccup retries the batch twice
# more with short backoff; anything else fails the jobs immediately.
DEFAULT_DISPATCH_RETRY = RetryPolicy(attempts=3, base_delay=0.05,
                                     multiplier=4.0, max_delay=1.0)


@dataclasses.dataclass
class _Flight:
    """One claimed batch moving through the dispatcher->completer pipeline.

    ``inflight`` holds the async-dispatched device futures (None when the
    split path is unavailable — an injected ``run_batch`` — or when staging
    itself failed, recorded in ``error`` for the completer's retry policy
    to classify)."""

    key: BucketKey
    batch: list
    started: float
    staged: Any = None  # retained host staging (retries re-dispatch from it)
    inflight: Any = None
    error: Exception | None = None
    consumed: bool = False  # first completion attempt taken


class Scheduler:
    """Owns the queue, the worker threads, and the job table."""

    def __init__(
        self,
        journal: JobJournal | None = None,
        metrics: Metrics | None = None,
        max_queue_depth: int = 1024,
        max_batch: int = batcher.MAX_BATCH,
        flush_age: float = 0.05,
        max_inflight: int = 1,
        pipeline_depth: int = 1,
        resident_ring: int = 0,
        retry: RetryPolicy = DEFAULT_DISPATCH_RETRY,
        retryable=is_transient_io,
        run_batch=batcher.run_batch,
        split_batch=None,
        cache=None,
        retry_budget=None,
        clock=time.perf_counter,
    ):
        if max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got {max_queue_depth}")
        if not 1 <= max_batch <= batcher.MAX_BATCH:
            raise ValueError(
                f"max_batch must be in [1, {batcher.MAX_BATCH}], got {max_batch}"
            )
        if max_inflight < 1:
            raise ValueError(f"max_inflight must be >= 1, got {max_inflight}")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}"
            )
        if pipeline_depth > 1 and max_inflight != 1:
            raise ValueError(
                "pipeline_depth > 1 replaces the worker pool with the "
                "dispatcher/completer pipeline; leave max_inflight at 1"
            )
        if resident_ring < 0 or resident_ring == 1:
            raise ValueError(
                f"resident_ring must be 0 (off) or >= 2, got {resident_ring}"
            )
        if resident_ring > 1 and pipeline_depth < 2:
            raise ValueError(
                "the resident ring rides the dispatcher/completer pipeline; "
                "set pipeline_depth >= 2 (>= 2x the ring keeps the device "
                "stream fed)"
            )
        if resident_ring > 1 and (
            run_batch is not batcher.run_batch or split_batch is not None
        ):
            raise ValueError(
                "resident_ring requires the default batcher engine; an "
                "injected run_batch/split_batch has no ring lane"
            )
        self.journal = journal
        self.metrics = metrics or Metrics()
        self.max_queue_depth = max_queue_depth
        self.max_batch = max_batch
        self.flush_age = flush_age
        self.max_inflight = max_inflight
        self.pipeline_depth = pipeline_depth
        self.retry = retry
        self.retryable = retryable
        # The token-bucket retry budget (resilience/retry.RetryBudget) or
        # None (unlimited — the pre-budget behavior, test-pinned). Shared
        # across every batch retry this scheduler takes: under a brownout
        # the budget drains and dispatch degrades to first-attempt-only
        # instead of amplifying the overload with retry traffic.
        self.retry_budget = retry_budget
        if retry_budget is not None:
            self.metrics.set_gauge("retry_budget_remaining",
                                   round(retry_budget.remaining(), 3))
        self._run_batch = run_batch
        # The staged dispatch path (stage -> async dispatch -> complete).
        # Auto-wired to the batcher's split only when run_batch is the
        # default batcher entry: an injected run_batch (tests, alternative
        # engines) has no split, so the completer runs it whole — pipeline
        # semantics hold, only the stage/compute overlap is lost. With
        # resident_ring on, the split's dispatch/complete ride the
        # per-bucket ring lanes (gol_tpu/serve/resident.py) instead of
        # posting one device program per batch.
        self.resident_ring = resident_ring
        self._resident = None
        if resident_ring > 1:
            from gol_tpu.serve.resident import ResidentEngine

            self._resident = ResidentEngine(resident_ring, clock=clock)
            split_batch = self._resident.split()
        elif split_batch is None and run_batch is batcher.run_batch:
            split_batch = (batcher.stage, batcher.dispatch, batcher.complete)
        self._split = split_batch
        self._window = None  # dispatcher->completer handoff (pipelined mode)
        # Resident mode detaches terminal journaling from the completer's
        # critical path: record appends ride a dedicated writer thread (the
        # journal fsync was the last per-batch host cost serializing with
        # readbacks). The durability contract is unchanged — a done record
        # was always allowed to be lost to a crash (the re-run is
        # idempotent); stop() drains the queue before returning, so a clean
        # shutdown loses nothing.
        self._journal_window = None
        self._journal_thread = None
        self._clock = clock
        # The tiered result cache (gol_tpu/cache.ResultCache) or None.
        # _inflight_fp maps a fingerprint to its LEADER job (queued or
        # running); _followers holds identical submissions coalescing
        # behind it. Both are guarded by _cv.
        self.cache = cache
        self._inflight_fp: dict[str, Job] = {}
        self._followers: dict[str, list[Job]] = {}
        self._cv = threading.Condition()
        self._jobs: dict[str, Job] = {}
        self._buckets: dict[BucketKey, list[Job]] = {}
        self._queued = 0
        self._inflight = 0
        self._draining = False
        self._stopped = False
        self._threads: list[threading.Thread] = []

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._cv:
            if self._threads:
                return
            self._stopped = False
            if self._resident is not None:
                self._resident.reopen()  # state provider (no-op first time)
            if self.pipeline_depth > 1:
                # Pipelined dispatch: one dispatcher (claim + stage + async
                # dispatch) and one completer (readback + journal), with at
                # most pipeline_depth batches between claim and completion.
                from gol_tpu.pipeline.inflight import Handoff

                if self._resident is not None and self.journal is not None:
                    self._journal_window = Handoff()
                    self._journal_thread = threading.Thread(
                        target=self._journal_loop, name="gol-serve-journal",
                        daemon=True,
                    )
                    self._journal_thread.start()
                self._window = Handoff()
                for name, target in (
                    ("gol-serve-dispatch", self._dispatch_loop),
                    ("gol-serve-complete", self._complete_loop),
                ):
                    t = threading.Thread(target=target, name=name, daemon=True)
                    t.start()
                    self._threads.append(t)
                return
            # One worker per allowed in-flight batch: the thread count IS
            # the max-in-flight-batches admission knob.
            for i in range(self.max_inflight):
                t = threading.Thread(
                    target=self._worker, name=f"gol-serve-worker-{i}", daemon=True
                )
                t.start()
                self._threads.append(t)

    def stop(self, drain: bool = True, timeout: float | None = None) -> bool:
        drained = self.drain(timeout=timeout) if drain else True
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=5)
        if self._journal_window is not None:
            # After the completer is gone nothing enqueues: close the
            # window and let the writer drain every pending record — even
            # a drain=False stop flushes the journal before returning.
            # (If a completer join above timed out, its late enqueue races
            # the close — _journal_terminal falls back to an inline append
            # in that case, so the record still lands.)
            self._journal_window.close()
            self._journal_thread.join(timeout=30)
            if self._journal_thread.is_alive():
                logger.warning(
                    "gol-serve-journal did not drain within 30s; pending "
                    "done records may be lost (restart re-runs those jobs)"
                )
            self._journal_window = None
            self._journal_thread = None
        if self._resident is not None:
            # After the threads are gone: drop the recorder state provider
            # and the lanes (ring hygiene; start() re-registers).
            self._resident.close()
        return drained

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admission, flush everything queued, wait for quiescence.

        Returns True when the queue and all in-flight batches emptied within
        ``timeout`` (None = wait forever)."""
        deadline = None if timeout is None else self._clock() + timeout
        with self._cv:
            self._draining = True
            self._cv.notify_all()
            while self._queued > 0 or self._inflight > 0:
                remaining = None
                if deadline is not None:
                    remaining = deadline - self._clock()
                    if remaining <= 0:
                        return False
                self._cv.wait(timeout=remaining)
            return True

    @property
    def draining(self) -> bool:
        with self._cv:
            return self._draining

    # -- admission ---------------------------------------------------------

    def submit(self, job: Job, record: bool = True) -> Job:
        """Accept a job into its bucket (raises QueueFull/Draining).

        ``record=False`` resubmits a journal-replayed job: it is not
        journaled again (its submit record already exists) and it bypasses
        the draining/depth admission gates — a replayed job was ALREADY
        accepted by a previous server, and bouncing it at restart would
        turn a full-queue crash into an unrecoverable restart loop (replay
        can legitimately exceed ``max_queue_depth`` by the jobs that were
        in flight when the process died)."""
        key = bucket_for(job)  # raises on un-runnable jobs before admission
        # Fingerprint + tier consult OUTSIDE the lock: hashing the board and
        # a CAS read are real work, and workers must not stall behind them.
        # The race this opens (a leader completing between our miss and our
        # lock) costs at most one redundant — idempotent — engine run.
        # The admission gates are pre-checked FIRST (racy, lock-free reads;
        # the authoritative checks re-run under the lock below): a
        # submission that will be 429'd must not amplify overload with a
        # CAS disk read, nor count a consult in the hit/miss series.
        # Sparse jobs (job.board is None) never enter the job-level result
        # cache: their answer IS memoized tile work (gol_tpu/sparse/memo),
        # and a dense CacheEntry cannot carry an RLE universe.
        fp = hit = None
        if self.cache is not None and not job.no_cache \
                and job.board is not None and not (
            record and (self._draining
                        or self._queued >= self.max_queue_depth)
        ):
            fp = job_fingerprint(job)
            hit = self.cache.get(fp)
        with self._cv:
            if record and self._draining:
                self.metrics.inc("jobs_rejected_total")
                raise Draining("server is draining; not accepting jobs")
            if record and self._queued >= self.max_queue_depth:
                self.metrics.inc("jobs_rejected_total")
                raise QueueFull(
                    f"queue at max depth {self.max_queue_depth}"
                )
            if job.id in self._jobs:
                raise ValueError(f"duplicate job id {job.id}")
            # Journal BEFORE the job becomes visible to workers (still under
            # the lock): otherwise a fast worker could append this job's
            # `done` record ahead of its `submit` record, and a replay would
            # re-queue — i.e. double-run — an already-completed job. The
            # fsync inside the critical section is the price of the
            # exactly-once ledger ordering.
            # A FAILING submit append (ENOSPC, EIO) refuses the accept: an
            # acknowledged job absent from the journal would vanish on
            # replay — the one failure mode strictly worse than a 503.
            # Nothing is admitted here (the job is not yet in _jobs, no
            # bucket slot, no in-flight registration), so the refusal is
            # clean and the client's retry starts from zero.
            if record and self.journal is not None:
                try:
                    self.journal.record_submit(job)
                except OSError as err:
                    self.metrics.inc("journal_errors_total")
                    self.metrics.inc("jobs_rejected_total")
                    logger.error(
                        "journal submit append failed for job %s — refusing "
                        "the accept (an acknowledged-but-unjournaled job "
                        "would vanish on replay): %s: %s",
                        job.id, type(err).__name__, err,
                    )
                    raise JournalUnavailable(
                        f"cannot journal the submit record: "
                        f"{type(err).__name__}: {err}"
                    ) from err
            job.accepted_at = self._clock()
            job.timeline["accepted"] = job.accepted_at
            self._jobs[job.id] = job
            self.metrics.inc("jobs_accepted_total")
            if hit is not None:
                # Cache hit: complete at admission — never enqueued, never
                # batched. State flips under the lock; the (fsynced) done
                # record is appended after it, on this thread, so its
                # ledger ordering after the submit record holds.
                entry, tier = hit
                self._complete_from_cache_locked(job, entry, tier)
            elif fp is not None and fp in self._inflight_fp:
                # An identical board is already queued/running: coalesce.
                # The leader's ONE engine run completes every follower,
                # each with its own journaled DONE record.
                job.fingerprint = fp
                self._followers.setdefault(fp, []).append(job)
                self._queued += 1
                self.metrics.inc("cache_inflight_coalesced_total")
                self.metrics.set_gauge("queue_depth", self._queued)
                self._fold_urgency_locked(self._inflight_fp[fp], job)
            else:
                if fp is not None:
                    job.fingerprint = fp
                    self._inflight_fp[fp] = job
                self._buckets.setdefault(key, []).append(job)
                self._queued += 1
                self.metrics.set_gauge("queue_depth", self._queued)
                self._cv.notify_all()
        # Flow START: with tracing on, the job's lifecycle becomes a Perfetto
        # arrow chain from here to its finish inside a batch span. A job
        # carrying a propagated trace id (obs/propagate.py) chains onto the
        # ROUTER's flow start instead of opening its own — phase "t", under
        # the fleet-wide id.
        obs_trace.flow("job", job.flow_id(), "t" if job.trace else "s",
                       bucket=key.label())
        if hit is not None:
            self._journal_terminal(JobJournal.record_done, job)
            obs_trace.flow("job", job.flow_id(), "f", state="cached")
        return job

    def _complete_from_cache_locked(self, job: Job, entry: CacheEntry,
                                    tier: str) -> None:
        """Finish a job from a cache entry (caller holds the lock and
        journals the done record afterwards). Engine-work counters
        (batches/boards/cell-updates) are deliberately NOT fed — a hit did
        no engine work, and claiming otherwise would corrupt the
        dispatch-gap monitor's achieved-rate numerator."""
        finished = self._clock()
        job.finished_at = finished
        job.timeline["done"] = finished
        job.result = JobResult(
            grid=entry.grid,
            generations=entry.generations,
            exit_reason=entry.exit_reason,
            cached=tier,
            # A packed CAS payload's words ride through to the response:
            # a binary hit answers a packed GET /result with the stored
            # word bytes — no decode→re-encode round trip.
            words=entry.words,
        )
        job.transition(DONE)
        self.metrics.inc("jobs_completed_total")
        latency = finished - job.accepted_at
        self.metrics.observe("job_latency_seconds", latency)
        self.metrics.observe(
            "job_latency_seconds_" + priority_class(job.priority), latency
        )

    def resubmit_replayed(self, replayed: list[Job]) -> int:
        """Queue journal-replayed jobs (already durable; not re-recorded)."""
        n = 0
        for job in replayed:
            self.submit(job, record=False)
            n += 1
        if n:
            logger.info("replayed %d unfinished job(s) from the journal", n)
        return n

    def now(self) -> float:
        """This scheduler's clock reading (the server stamps deadline
        expiries with it so injected-clock tests stay coherent)."""
        return self._clock()

    def job(self, job_id: str) -> Job | None:
        with self._cv:
            return self._jobs.get(job_id)

    def cancel(self, job_id: str) -> bool:
        """Cancel a job that has not been claimed by a batch yet.

        A coalesced follower cancels out of its leader's wait list; a
        QUEUED *leader* with followers hands the bucket slot (and the
        in-flight registration) to its first follower, so the remaining
        duplicates still run exactly once."""
        with self._cv:
            job = self._jobs.get(job_id)
            if job is None or job.state != QUEUED:
                return False
            key = bucket_for(job)
            bucket = self._buckets.get(key, [])
            followers = (self._followers.get(job.fingerprint, [])
                         if job.fingerprint is not None else [])
            if job in bucket:
                bucket.remove(job)
                self._promote_follower_locked(job, bucket)
            elif job in followers:
                followers.remove(job)
            else:
                # QUEUED but in neither structure: another thread is
                # completing it right now (cache/coalesce handoff window).
                return False
            self._queued -= 1
            job.transition(CANCELLED)
            self.metrics.inc("jobs_cancelled_total")
            self.metrics.set_gauge("queue_depth", self._queued)
            self._cv.notify_all()
        if self.journal is not None:
            self.journal.record_cancelled(job)
        return True

    def _fold_urgency_locked(self, leader: Job, follower: Job) -> None:
        """Fold a follower's dispatch urgency into its still-QUEUED leader.

        Followers never sit in a bucket, so ``_claim_locked`` and
        ``_bucket_due_at`` only ever see the leader — without this fold, a
        high-priority or tight-deadline duplicate would inherit its
        leader's (possibly lowest) urgency, breaking the priority/deadline
        ordering guarantee for exactly the repeat traffic the cache
        targets. The leader's priority class (SLO histograms) follows the
        bump deliberately: its one engine run IS serving the most urgent
        request coalesced behind it. Once claimed, dispatch order is
        already decided — nothing to fold."""
        if leader.state != QUEUED:
            return
        changed = False
        if follower.priority > leader.priority:
            leader.priority = follower.priority
            changed = True
        if follower.deadline_s is not None:
            follower_due = follower.accepted_at + follower.deadline_s
            leader_due = (leader.accepted_at + leader.deadline_s
                          if leader.deadline_s is not None else None)
            if leader_due is None or follower_due < leader_due:
                leader.deadline_s = follower_due - leader.accepted_at
                changed = True
        if changed:
            # The leader's bucket may have become due earlier than the
            # wait a worker computed from the old urgency.
            self._cv.notify_all()

    def _promote_follower_locked(self, leader: Job, bucket: list) -> None:
        """A queued leader left the bucket (cancel): its first follower —
        if any — takes over as the fingerprint's leader and engine run,
        inheriting the remaining followers' folded urgency."""
        fp = leader.fingerprint
        if fp is None or self._inflight_fp.get(fp) is not leader:
            return
        followers = self._followers.get(fp, [])
        if followers:
            promoted = followers.pop(0)
            self._inflight_fp[fp] = promoted
            bucket.append(promoted)  # same board => same bucket key
            for waiting in followers:
                self._fold_urgency_locked(promoted, waiting)
        else:
            del self._inflight_fp[fp]

    # -- batch forming -----------------------------------------------------

    def _bucket_due_at(self, jobs: list[Job]) -> float:
        """When this bucket becomes dispatch-ready on its own (age/deadline)."""
        oldest = min(j.accepted_at for j in jobs)
        due = oldest + self.flush_age
        for j in jobs:
            if j.deadline_s is not None:
                due = min(due, j.accepted_at + j.deadline_s)
        return due

    def _bucket_ready(self, pending: list[Job], now: float) -> bool:
        """The ONE dispatch-readiness predicate (size / age+deadline /
        drain), shared by claiming and by the pipelined dispatcher's
        stall classification so the two can never disagree."""
        return (
            self._draining
            or len(pending) >= self.max_batch
            or self._bucket_due_at(pending) <= now
        )

    def _claim_locked(self, now: float):
        """Pick the most urgent ready bucket and take a batch from it."""
        best = None
        for key, pending in self._buckets.items():
            if not pending or not self._bucket_ready(pending, now):
                continue
            urgency = min(j.dispatch_key() for j in pending)
            if best is None or urgency < best[0]:
                best = (urgency, key)
        if best is None:
            return None
        key = best[1]
        pending = sorted(self._buckets[key], key=Job.dispatch_key)
        take, rest = pending[: self.max_batch], pending[self.max_batch:]
        self._buckets[key] = rest
        self._queued -= len(take)
        for job in take:
            job.transition(SCHEDULED)
        self._inflight += 1
        self.metrics.set_gauge("queue_depth", self._queued)
        self.metrics.set_gauge("inflight_batches", self._inflight)
        return key, take

    def _next_due(self) -> float | None:
        due = None
        for pending in self._buckets.values():
            if pending:
                d = self._bucket_due_at(pending)
                due = d if due is None else min(due, d)
        return due

    # -- the worker --------------------------------------------------------

    def _worker(self) -> None:
        while True:
            with self._cv:
                claimed = None
                while not self._stopped:
                    claimed = self._claim_locked(self._clock())
                    if claimed is not None:
                        break
                    due = self._next_due()
                    wait = None if due is None else max(0.0, due - self._clock())
                    self._cv.wait(timeout=wait)
                if claimed is None:
                    return  # stopped
            key, batch = claimed
            try:
                self._execute(key, batch)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self.metrics.set_gauge("inflight_batches", self._inflight)
                    self._cv.notify_all()

    @staticmethod
    def _stamp(batch: list[Job], milestone: str, t: float) -> None:
        """Stamp one timeline milestone on every job of a batch (the splits
        run at batch granularity, so batchmates share each stamp)."""
        for job in batch:
            job.timeline[milestone] = t

    def _begin_batch(self, batch: list[Job], started: float) -> None:
        for job in batch:
            job.started_at = started
            job.timeline["claimed"] = started
            job.transition(RUNNING)
            self.metrics.observe(
                "queue_latency_seconds", started - job.accepted_at
            )
            obs_trace.flow("job", job.flow_id(), "t", state="claimed")

    def _on_retry(self, key: BucketKey, batch: list[Job]):
        def on_retry(attempt, err, delay):
            self.metrics.inc("batch_retries_total")
            if self.retry_budget is not None:
                # Exported on the SERVING registry so it fleet-merges and
                # reaches `gol top` like every other serving series.
                self.metrics.set_gauge(
                    "retry_budget_remaining",
                    round(self.retry_budget.remaining(), 3),
                )
            logger.warning(
                "batch %s (%d jobs) failed attempt %d, retrying in %.2fs "
                "(%s: %s)",
                key.label(), len(batch), attempt, delay,
                type(err).__name__, err,
            )

        return on_retry

    def _fail_batch(self, key: BucketKey, batch: list[Job], err) -> None:
        finished = self._clock()
        logger.error(
            "batch %s (%d jobs) failed: %s: %s",
            key.label(), len(batch), type(err).__name__, err,
        )
        # Followers coalesced behind these leaders share their fate: the
        # one engine run they were waiting on is not coming.
        for job in batch + self._take_followers(batch):
            job.finished_at = finished
            job.timeline["done"] = finished
            job.error = f"{type(err).__name__}: {err}"
            job.transition(FAILED)
            self.metrics.inc("jobs_failed_total")
            obs_trace.flow("job", job.flow_id(), "f", state="failed")
            self._journal_terminal(JobJournal.record_failed, job)

    def _take_followers(self, batch: list[Job]) -> list[Job]:
        """Atomically claim every follower coalesced behind these jobs and
        retire their in-flight registrations. Called AFTER the leaders'
        results are in the cache (finish) or known unobtainable (fail), so
        a submit racing this pop either still coalesces or hits the
        fresh cache entry — never falls through to a third path that
        loses the result."""
        taken: list[Job] = []
        with self._cv:
            for job in batch:
                if job.fingerprint is None:
                    continue
                # Followers belong to whoever holds the in-flight
                # registration. A deadline-expired leader hands its
                # registration to a promoted follower BEFORE failing —
                # the waiters behind the new leader are not this job's
                # to take.
                if self._inflight_fp.get(job.fingerprint) is job:
                    del self._inflight_fp[job.fingerprint]
                    taken.extend(self._followers.pop(job.fingerprint, []))
            if taken:
                self._queued -= len(taken)
                self.metrics.set_gauge("queue_depth", self._queued)
                self._cv.notify_all()
        return taken

    def _finish_batch(self, key: BucketKey, batch: list[Job], results,
                      started: float) -> None:
        finished = self._clock()
        elapsed = max(finished - started, 1e-9)
        # The same rung run_batch padded to: occupancy is boards over the
        # slots the compiled program actually ran.
        slots = pad_batch(len(batch))
        self.metrics.inc("batches_total")
        self.metrics.inc("boards_total", len(batch))
        self.metrics.observe("batch_occupancy", len(batch) / slots)
        self.metrics.observe("run_latency_seconds", elapsed)
        self.metrics.set_gauge("boards_per_sec", len(batch) / elapsed)
        cells = 0
        sparse_tiles = 0
        sparse_occupancy = None
        for job, result in zip(batch, results):
            job.finished_at = finished
            job.timeline["done"] = finished
            job.result = result
            job.transition(DONE)
            self.metrics.inc("jobs_completed_total")
            # End-to-end latency per SLO priority class (obs/slo.py keys
            # its per-priority p99 objectives on these histogram names).
            latency = finished - job.accepted_at
            self.metrics.observe("job_latency_seconds", latency)
            self.metrics.observe(
                "job_latency_seconds_" + priority_class(job.priority), latency
            )
            # Achieved useful work: actual board cells times the generations
            # the board really ran (padding slots and canvas don't count).
            # Sparse results report their own achieved work — active tiles
            # times tile area — because universe x generations is exactly
            # the cost the sparse lane exists to NOT pay.
            if result.cell_updates is not None:
                cells += result.cell_updates
            else:
                cells += job.height * job.width * result.generations
            if result.tiles_simulated is not None:
                sparse_tiles += result.tiles_simulated
            if result.occupancy is not None:
                sparse_occupancy = result.occupancy
        # Fed to the dispatch-gap sampler (obs/sampler.py): achieved
        # cell-updates per bucket vs the tuned plan's marginal kernel rate.
        self.metrics.inc("serve_cell_updates_total", cells)
        self.metrics.inc(
            "serve_cell_updates_total_" + metric_label(key.label()), cells
        )
        # Sparse-lane work series on the SERVING registry (they fleet-merge
        # and reach `gol top` like any serving series): tile-steps executed
        # and the last finished universe's live-tile occupancy.
        if sparse_tiles:
            self.metrics.inc("sparse_tiles_simulated_total", sparse_tiles)
        if sparse_occupancy is not None:
            self.metrics.set_gauge("sparse_occupancy", sparse_occupancy)
        # Write-through BEFORE retiring the in-flight registrations: a
        # submit racing the handoff either still coalesces behind the
        # leader or hits the tier the result just landed in — there is no
        # window where it would redundantly re-run. A no_cache job never
        # acquired a fingerprint, so it never writes.
        if self.cache is not None:
            for job in batch:
                if job.fingerprint is not None:
                    r = job.result
                    self.cache.put(job.fingerprint, CacheEntry(
                        grid=r.grid,
                        generations=r.generations,
                        exit_reason=r.exit_reason,
                        # Packed-kernel readbacks carry their word layout:
                        # the CAS packed payload then writes without a
                        # re-pack, exactly as a packed response serves.
                        words=r.words,
                    ))
        followers = self._take_followers(batch)
        for f in followers:
            leader = self._inflight_result(f, batch)
            f.finished_at = finished
            f.timeline["done"] = finished
            f.result = JobResult(
                grid=leader.grid,
                generations=leader.generations,
                exit_reason=leader.exit_reason,
                cached="coalesced",
                words=leader.words,
            )
            f.transition(DONE)
            self.metrics.inc("jobs_completed_total")
            latency = finished - f.accepted_at
            self.metrics.observe("job_latency_seconds", latency)
            self.metrics.observe(
                "job_latency_seconds_" + priority_class(f.priority), latency
            )
            obs_trace.flow("job", f.flow_id(), "f", state="coalesced")
        # One journal append + fsync for the whole batch's done records
        # (identical lines to per-job appends — replay is oblivious): the
        # per-record fsync was the last per-*job* serial host cost on the
        # hot path. Durability contract unchanged: a crash before the
        # append re-runs the batch idempotently after replay, exactly like
        # a single lost record.
        self._journal_terminal(JobJournal.record_done_many, batch + followers)

    @staticmethod
    def _inflight_result(follower: Job, batch: list[Job]) -> JobResult:
        """The leader result a follower coalesced behind (same fingerprint,
        same batch — leaders complete with their own batch)."""
        for job in batch:
            if job.fingerprint == follower.fingerprint:
                return job.result
        raise RuntimeError(
            f"follower {follower.id} has no leader in its batch "
            f"(fingerprint {follower.fingerprint})"
        )

    def _drop_expired(self, key: BucketKey, batch: list[Job]) -> list[Job]:
        """Deadline enforcement at batch dispatch: jobs whose propagated
        budget (X-Gol-Deadline -> Job.expires_at) is already spent fail
        HERE — with the DeadlineExceeded 504 contract and their timeline
        intact — instead of burning a slot in the compiled program for an
        answer nobody is waiting for. Jobs without a budget (every old
        client) pass untouched; a batch can lose any subset including all
        of it (the caller skips the dispatch entirely then)."""
        now = self._clock()
        expired = [j for j in batch
                   if j.expires_at is not None and j.expires_at <= now]
        if not expired:
            return batch
        self.metrics.inc("deadline_expired_total", len(expired))
        # An expired LEADER's followers are other clients' jobs with
        # their own (possibly absent) budgets — only the leader's clock
        # ran out. Promote the first follower into the bucket as the
        # fingerprint's new leader (the cancel path's move) before
        # failing, so _fail_batch's follower sweep — which only claims
        # followers still registered to the failing job — takes nobody
        # who can still make their deadline.
        with self._cv:
            bucket = self._buckets.setdefault(key, [])
            for job in expired:
                self._promote_follower_locked(job, bucket)
            self._cv.notify_all()
        self._fail_batch(key, expired, DeadlineExceeded(
            "deadline budget spent before dispatch"
        ))
        return [j for j in batch if j not in expired]

    def _execute(self, key: BucketKey, batch: list[Job]) -> None:
        batch = self._drop_expired(key, batch)
        if not batch:
            return
        started = self._clock()
        self._begin_batch(batch, started)
        staged = None

        def attempt():
            # Stage ONCE, retry dispatch+complete from the retained host
            # staging: re-staging on retry would re-run the whole stack +
            # np.packbits pass for operands that are already retained (and
            # bit-identical — staging is deterministic). The
            # engine_stage_packs_total counter pins zero re-packs on the
            # retry path. A failure inside stage() itself leaves ``staged``
            # unset, so the next attempt re-stages — the only case where
            # staging can legitimately run twice.
            nonlocal staged
            if self._split is None:
                return self._run_batch(key, batch)
            stage_fn, dispatch_fn, complete_fn = self._split
            if staged is None:
                t0 = self._clock()
                with obs_trace.span("pipeline.stage", bucket=key.label(),
                                    jobs=len(batch)):
                    staged = stage_fn(key, batch)
                self._stamp(batch, "stage_start", t0)
                self._stamp(batch, "staged", self._clock())
            inflight = dispatch_fn(staged)
            t = self._clock()
            self._stamp(batch, "dispatched", t)
            # The classic worker blocks on readback immediately, so the
            # device segment collapses to ~0 here and the compute time
            # shows in `readback` — the pipelined lanes pull them apart.
            self._stamp(batch, "readback_start", t)
            results = complete_fn(inflight)
            self._stamp(batch, "completed", self._clock())
            return results

        try:
            # The batch span: what a traced `gol serve` session exports and
            # what `GET /debug/trace` shows mid-flight. One span per
            # dispatched batch, labeled with its padding bucket — a session
            # serving two bucket shapes shows two distinct batch lanes.
            with obs_trace.span("serve.batch", bucket=key.label(),
                                jobs=len(batch)):
                results = self.retry.call(
                    attempt,
                    retryable=self.retryable,
                    on_retry=self._on_retry(key, batch),
                    budget=self.retry_budget,
                )
                # Flow FINISH inside the batch span, so Perfetto binds the
                # arrow head to the enclosing serve.batch slice.
                for job in batch:
                    obs_trace.flow("job", job.flow_id(), "f",
                                   bucket=key.label())
        except Exception as err:  # noqa: BLE001 - every job must terminate
            self._fail_batch(key, batch, err)
            return
        self._finish_batch(key, batch, results, started)

    # -- the pipelined dispatcher/completer pair ---------------------------

    def _ready_bucket_exists(self, now: float) -> bool:
        """Whether some bucket is dispatch-ready (the claim predicate,
        without claiming) — used only to classify a full-window wait as a
        pipeline stall."""
        return any(
            pending and self._bucket_ready(pending, now)
            for pending in self._buckets.values()
        )

    def _dispatch_loop(self) -> None:
        """Claim -> stage -> async dispatch; never blocks on device results.

        Claims only while fewer than ``pipeline_depth`` batches are between
        claim and completion (the bounded in-flight window); a wait forced
        by a full window with work ready counts as ``pipeline_stalls_total``
        (the signal that depth, not load, is the limiter)."""
        window = self._window
        while True:
            with self._cv:
                claimed = None
                stalled = False
                while not self._stopped:
                    now = self._clock()
                    if self._inflight >= self.pipeline_depth:
                        # Window full: only a completion (or stop) can make
                        # progress — wait for its notify, NOT for a bucket
                        # due time (a past-due bucket would turn the timed
                        # wait into a hot spin against the completer's lock).
                        if not stalled and self._ready_bucket_exists(now):
                            stalled = True
                            self.metrics.inc("pipeline_stalls_total")
                        self._cv.wait()
                        continue
                    claimed = self._claim_locked(now)
                    if claimed is not None:
                        break
                    due = self._next_due()
                    wait = None if due is None else max(0.0, due - self._clock())
                    self._cv.wait(timeout=wait)
                if claimed is None:
                    break  # stopped
            key, batch = claimed
            window.put(self._launch(key, batch))
        # Completion order is the window order; the sentinel follows every
        # already-posted flight, so the completer drains then exits.
        window.close()

    def _launch(self, key: BucketKey, batch: list[Job]) -> _Flight:
        batch = self._drop_expired(key, batch)
        started = self._clock()
        flight = _Flight(key=key, batch=batch, started=started)
        if not batch:
            return flight  # everything expired: an empty (no-op) flight
        self._begin_batch(batch, started)
        if self._split is None:
            return flight  # completer runs self._run_batch whole
        stage_fn, dispatch_fn, _ = self._split
        try:
            t0 = self._clock()
            with obs_trace.span("pipeline.stage", bucket=key.label(),
                                jobs=len(batch)):
                flight.staged = stage_fn(key, batch)
            self._stamp(batch, "stage_start", t0)
            self._stamp(batch, "staged", self._clock())
            flight.inflight = dispatch_fn(flight.staged)
            self._stamp(batch, "dispatched", self._clock())
        except Exception as err:  # noqa: BLE001 - completer owns terminality
            # Carried to the completer so ONE code path (its retry policy)
            # classifies every failure: a transient dispatch error retries
            # the whole batch there; a hard one fails the jobs there.
            flight.error = err
        return flight

    def _complete_loop(self) -> None:
        """Readback + journal, in completion (window) order."""
        window = self._window
        while True:
            flight = window.get()
            if flight is None:
                return  # dispatcher closed the window after its last put
            try:
                self._complete_flight(flight)
            finally:
                with self._cv:
                    self._inflight -= 1
                    self.metrics.set_gauge("inflight_batches", self._inflight)
                    self._cv.notify_all()

    def _complete_flight(self, flight: _Flight) -> None:
        key, batch = flight.key, flight.batch
        if not batch:
            return  # every job expired at launch; nothing was dispatched
        complete_fn = self._split[2] if self._split is not None else None

        def attempt():
            # First attempt consumes the pipelined dispatch; retries re-run
            # dispatch + complete of THIS batch from the retained host
            # staging (no re-stacking/packbits) — GoL runs are pure
            # functions of the input, so a re-run is idempotent (the same
            # contract the depth-1 worker's retry relies on). When there is
            # no staging to retain (injected run_batch, or the failure was
            # in stage() itself), the retry re-runs the whole batch.
            if not flight.consumed:
                flight.consumed = True
                if flight.error is not None:
                    raise flight.error
                if flight.inflight is not None:
                    self._stamp(batch, "readback_start", self._clock())
                    results = complete_fn(flight.inflight)
                    self._stamp(batch, "completed", self._clock())
                    return results
            if self._split is not None and flight.staged is not None:
                _, dispatch_fn, _ = self._split
                inflight = dispatch_fn(flight.staged)
                t = self._clock()
                self._stamp(batch, "dispatched", t)
                self._stamp(batch, "readback_start", t)
                results = complete_fn(inflight)
                self._stamp(batch, "completed", self._clock())
                return results
            return self._run_batch(key, batch)

        try:
            with obs_trace.span("serve.batch", bucket=key.label(),
                                jobs=len(batch)):
                results = self.retry.call(
                    attempt,
                    retryable=self.retryable,
                    on_retry=self._on_retry(key, batch),
                    budget=self.retry_budget,
                )
                for job in batch:
                    obs_trace.flow("job", job.flow_id(), "f",
                                   bucket=key.label())
        except Exception as err:  # noqa: BLE001 - every job must terminate
            self._fail_batch(key, batch, err)
            return
        self._finish_batch(key, batch, results, flight.started)

    def _journal_terminal(self, record_fn, job_or_batch) -> None:
        """Append terminal record(s), surviving journal I/O failure.

        A failing fsync/write (ENOSPC, EIO) here must never escape: it would
        kill the worker thread, strand the rest of the batch in RUNNING, and
        stop all dispatch. The in-memory state stays authoritative for this
        process; the cost of a dropped terminal record is a re-run after a
        restart (idempotent), logged loudly and counted so operators see the
        journal degrading before that.

        In resident mode the append rides the ``gol-serve-journal`` writer
        thread so the completer's readbacks overlap the fsyncs; everywhere
        else (the classic worker and the plain pipeline — PR-5 behavior,
        test-pinned) it runs inline."""
        if self.journal is None:
            return
        window = self._journal_window  # snapshot: stop() may null the field
        if window is not None:
            try:
                window.put((record_fn, job_or_batch))
            except RuntimeError:
                # stop() closed the window after a join timeout while this
                # completion was still in flight — append inline rather
                # than drop the record (or kill the completer).
                self._journal_append(record_fn, job_or_batch)
                return
            self.metrics.set_gauge("journal_queue_depth", len(window))
            return
        self._journal_append(record_fn, job_or_batch)

    def _journal_append(self, record_fn, job_or_batch) -> None:
        try:
            record_fn(self.journal, job_or_batch)
            # The timeline's final milestone: the terminal record is durable
            # (fsynced). Stamped here so it is correct on BOTH journal lanes
            # — inline (classic/pipelined) and the resident writer thread,
            # where it visibly trails `done` (journal_lag_seconds).
            t = self._clock()
            jobs = (job_or_batch if isinstance(job_or_batch, list)
                    else [job_or_batch])
            for j in jobs:
                j.timeline["journaled"] = t
        except OSError as err:
            self.metrics.inc("journal_errors_total")
            jobs = (job_or_batch if isinstance(job_or_batch, list)
                    else [job_or_batch])
            logger.error(
                "journal append failed for job(s) %s (%s) — state is held "
                "in-memory only; a restart will re-run them: %s: %s",
                ",".join(j.id for j in jobs), jobs[0].state,
                type(err).__name__, err,
            )

    def _journal_loop(self) -> None:
        """The resident lanes' journal writer: drains (record_fn, jobs)
        items until the window closes, then exits — stop() joins it, so a
        clean shutdown (drained or not) flushes every pending record. The
        window is captured once: a stop() that times out waiting and nulls
        the field cannot make a still-draining writer drop queued items."""
        window = self._journal_window
        while True:
            item = window.get()
            if item is None:
                return
            self._journal_append(*item)
            self.metrics.set_gauge("journal_queue_depth", len(window))

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._cv:
            out = {
                "queued": self._queued,
                "coalesced_waiting": sum(
                    len(v) for v in self._followers.values()
                ),
                "inflight_batches": self._inflight,
                "buckets": {
                    k.label(): len(v) for k, v in self._buckets.items() if v
                },
                "draining": self._draining,
                "jobs": len(self._jobs),
            }
        if self._resident is not None:
            out["resident_rings"] = self._resident.state()
        return out


# Re-exported for callers that only import the scheduler module.
__all__ = [
    "DEFAULT_DISPATCH_RETRY",
    "DeadlineExceeded",
    "Draining",
    "JournalUnavailable",
    "QueueFull",
    "Scheduler",
]
