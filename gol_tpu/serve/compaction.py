"""Journal segmentation + snapshot compaction: bounded durable queue state.

The PR-2 journal is append-only forever — correct, and unbounded: a
long-running partition accretes every submit record (a full board each) and
every terminal record (a full grid each) it ever served, until the disk
ends the service. This module bounds it without touching the append path's
crash contract:

- **Segments**: the live journal (``journal.jsonl``, one ``O_APPEND`` fd,
  unchanged) rotates at a byte threshold into sealed, immutable
  ``journal-<seq>.jsonl`` files (the obs/history ring's staging
  discipline: numbering never reuses an index, so "oldest" stays
  well-defined across restarts AND across compactions — ``next_index``
  reads the snapshot's high-water mark too).
- **Snapshot**: ``compact()`` folds the sealed segments into one
  CRC-stamped ``snapshot.jsonl`` of *live state*: the submit records of
  still-pending jobs plus the terminal records (done/failed/cancelled
  tombstones) — everything replay needs, with the dead weight (submit
  records of finished jobs, superseded duplicates) gone. The snapshot is
  record-for-record the journal's own vocabulary, so replay applies it
  with the same parser it applies segments with.
- **Retirement**: only after the new snapshot commits (staged + fsync +
  ``os.replace`` — the tree's one atomic step) are the folded segments
  deleted. Replay = snapshot + segments newer than it + the live journal.

SIGKILL-safe at every boundary, by construction:

- killed mid-snapshot-write: the staged temp is invisible (staging
  suffix); the old snapshot + all segments are untouched. Retried next
  tick.
- killed between commit and retirement: the new snapshot AND the folded
  segments coexist; replay skips segments ``seq <= covers`` (they are a
  prefix of the snapshot), and the next compaction deletes them.
- a torn/corrupt snapshot (external damage — the commit is atomic) fails
  its CRC/trailer check and is ignored loudly; segments were never
  deleted before a snapshot covering them committed, so full-log replay
  still stands.

Everything here works on RAW record dicts — no ``Job`` objects — so the
state fold is exactly the replay parser's semantics at the record level,
and the module stays import-light (``serve/jobs.py`` imports it, not the
other way around).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import tempfile
import zlib

from gol_tpu.fleet import lease
from gol_tpu.resilience import STAGING_SUFFIX, faults, fsio

logger = logging.getLogger(__name__)

ACTIVE_FILENAME = "journal.jsonl"
SNAPSHOT_FILENAME = "snapshot.jsonl"
LOCK_FILENAME = "compaction.lock"
# Rotate the live journal past this many bytes (gol serve
# --journal-segment-bytes; 0/None disables rotation — the PR-2 layout).
DEFAULT_SEGMENT_BYTES = 8 << 20

_SEGMENT_RE = re.compile(r"journal-(\d{8})\.jsonl$")
_HEADER_EVENT = "snapshot_header"
_COMMIT_EVENT = "snapshot_commit"
_VERSION = 1

# Journal events that terminate a job (tombstones the snapshot retains).
_TERMINAL_EVENTS = ("done", "failed", "cancelled")


def segment_name(index: int) -> str:
    return f"journal-{index:08d}.jsonl"


def sealed_segments(directory: str) -> list[tuple[int, str]]:
    """(seq, path) for every sealed segment, oldest first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _SEGMENT_RE.fullmatch(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def snapshot_covers(directory: str) -> int:
    """The snapshot's segment high-water mark from its HEADER line alone
    (no CRC pass, no record parse — the seq-minting path must not scale
    with history). -1 when absent or unreadable. Over-reading is harmless
    (a skipped seq number); under-reading is impossible for a committed
    snapshot because ``os.replace`` makes header and body one atomic
    unit."""
    try:
        with open(snapshot_path(directory), "rb") as f:
            header = json.loads(f.readline().decode("utf-8"))
        if header.get("event") == _HEADER_EVENT:
            return int(header["covers"])
    except (OSError, ValueError, KeyError, UnicodeDecodeError):
        pass
    return -1


def next_index(directory: str) -> int:
    """The next segment seq: past every sealed segment on disk AND past the
    snapshot's high-water mark — a rotation right after a compaction that
    retired every segment must not mint a seq replay would skip as
    already-folded."""
    segs = sealed_segments(directory)
    high = segs[-1][0] if segs else -1
    return max(high, snapshot_covers(directory)) + 1


def journal_bytes(directory: str) -> int:
    """Total durable journal footprint: snapshot + sealed segments + the
    live journal (the ``journal_bytes`` gauge)."""
    paths = [os.path.join(directory, ACTIVE_FILENAME),
             os.path.join(directory, SNAPSHOT_FILENAME)]
    paths.extend(p for _seq, p in sealed_segments(directory))
    total = 0
    for p in paths:
        try:
            total += os.path.getsize(p)
        except OSError:
            pass
    return total


@dataclasses.dataclass
class Snapshot:
    """A validated snapshot: the records to apply before any segment."""

    covers: int  # every segment with seq <= covers is folded in
    records: list[dict]


@dataclasses.dataclass
class CompactionReport:
    """What one ``compact()`` call did."""

    compacted: bool  # a new snapshot was committed
    covers: int  # the snapshot's segment high-water mark (-1: none)
    segments_retired: int  # sealed segment files deleted
    records_kept: int  # records in the (new or existing) snapshot
    terminal_dropped: int  # tombstones dropped by the retention window
    bytes_before: int
    bytes_after: int
    torn_lines: int  # unparseable lines encountered in the fold


def snapshot_path(directory: str) -> str:
    return os.path.join(directory, SNAPSHOT_FILENAME)


def read_snapshot(directory: str) -> Snapshot | None:
    """The committed snapshot, fully validated (header + record lines +
    CRC-stamped trailer), or None — missing is silent, a torn/corrupt one
    warns loudly and is IGNORED (replay falls back to the segments, which
    are never deleted before a valid snapshot covers them)."""
    path = snapshot_path(directory)
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    except OSError as err:
        logger.warning("journal snapshot %s unreadable (%s); ignoring it",
                       path, err)
        return None
    try:
        if raw.endswith(b"\n"):
            body_end = raw.rfind(b"\n", 0, len(raw) - 1) + 1
        else:
            body_end = raw.rfind(b"\n") + 1
        trailer = json.loads(raw[body_end:].decode("utf-8"))
        if trailer.get("event") != _COMMIT_EVENT:
            raise ValueError("missing commit trailer")
        if int(trailer["crc"]) != zlib.crc32(raw[:body_end]):
            raise ValueError("snapshot CRC mismatch")
        lines = [ln for ln in raw[:body_end].split(b"\n") if ln]
        header = json.loads(lines[0].decode("utf-8"))
        if header.get("event") != _HEADER_EVENT:
            raise ValueError("missing snapshot header")
        if header.get("version") != _VERSION:
            raise ValueError(f"unknown snapshot version {header.get('version')}")
        records = [json.loads(ln.decode("utf-8")) for ln in lines[1:]]
        if len(records) != int(trailer["records"]):
            raise ValueError(
                f"record count {len(records)} != trailer {trailer['records']}")
        return Snapshot(covers=int(header["covers"]), records=records)
    except (ValueError, KeyError, IndexError, UnicodeDecodeError) as err:
        logger.warning(
            "journal snapshot %s is torn/corrupt (%s: %s); ignoring it — "
            "the uncompacted segments replay instead and the next "
            "compaction rewrites it", path, type(err).__name__, err)
        return None


def write_snapshot(directory: str, covers: int, records: list[dict]) -> str:
    """Commit a snapshot atomically (staged + fsync + ``os.replace``).
    The ``snapshot`` fault boundary fires with the temp fully staged but
    the commit not yet done — the window where a kill must cost nothing."""
    header = json.dumps(
        {"event": _HEADER_EVENT, "version": _VERSION, "covers": int(covers)},
        separators=(",", ":"),
    ).encode("utf-8") + b"\n"
    body = b"".join(
        json.dumps(rec, separators=(",", ":")).encode("utf-8") + b"\n"
        for rec in records
    )
    trailer = json.dumps(
        {"event": _COMMIT_EVENT, "crc": zlib.crc32(header + body),
         "records": len(records)},
        separators=(",", ":"),
    ).encode("utf-8") + b"\n"
    fd, tmp = tempfile.mkstemp(dir=directory, prefix="snapshot.",
                               suffix=STAGING_SUFFIX)
    try:
        with os.fdopen(fd, "wb") as f:
            fsio.write_stream(f, header + body + trailer, "journal snapshot")
            f.flush()
            os.fsync(f.fileno())
        faults.on_compaction("snapshot")
        os.replace(tmp, snapshot_path(directory))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return snapshot_path(directory)


def _fold(records_iter, pending: dict, terminal: dict, torn: list) -> None:
    """Apply raw records to the fold state: ``pending`` maps id -> submit
    record, ``terminal`` maps id -> tombstone record (both insertion-
    ordered — the snapshot preserves arrival order). The semantics are the
    replay parser's, at the record level."""
    for rec in records_iter:
        try:
            event = rec["event"]
            if event == "submit":
                pending[rec["job"]["id"]] = rec
            elif event in _TERMINAL_EVENTS:
                terminal[rec["id"]] = rec
                pending.pop(rec["id"], None)
            elif event in (_HEADER_EVENT, _COMMIT_EVENT):
                pass  # structural lines never reach here, but be lenient
            else:
                raise ValueError(f"unknown event {event!r}")
        except (KeyError, TypeError, ValueError):
            torn[0] += 1


def _iter_lines(path: str, torn: list):
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return
    for line in raw.split(b"\n"):
        if not line:
            continue
        try:
            yield json.loads(line.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            torn[0] += 1


def iter_records(directory: str):
    """Every replay-visible record, in replay order: the committed
    snapshot's records, then sealed segments newer than it, then the live
    journal. The ONE enumeration exactly-once auditors must use — reading
    ``journal.jsonl`` alone misses everything rotation sealed and
    compaction folded. Unparseable lines are skipped (replay's leniency)."""
    snap = read_snapshot(directory)
    covers = -1
    if snap is not None:
        covers = snap.covers
        yield from snap.records
    torn = [0]
    paths = [p for seq, p in sealed_segments(directory) if seq > covers]
    paths.append(os.path.join(directory, ACTIVE_FILENAME))
    for path in paths:
        yield from _iter_lines(path, torn)


def compact(directory: str,
            retain_results: int | None = None) -> CompactionReport:
    """Fold every sealed segment into a fresh snapshot, then retire them.

    ``retain_results`` bounds the terminal tombstones the snapshot carries
    (the result-retention window): only the newest N survive compaction —
    a restarted server then answers 404 for results older than the window,
    the documented trade for a bounded journal. None (the default) retains
    every tombstone: replayed state is exactly full-log replay's.

    Touches ONLY sealed segments and the snapshot — the live journal (and
    whoever is appending to it) is never read, never locked, never moved —
    so an online server compacts concurrently with admission. Compactions
    themselves are mutually exclusive via an advisory ``flock`` on
    ``compaction.lock`` (auto-released on process death — SIGKILL-safe):
    two interleaved passes (an offline ``gol compact`` racing the live
    server's idle tick) could otherwise commit a STALE snapshot over a
    newer one whose folded segments are already deleted, losing their
    records. The loser skips and reports ``compacted=False``. (The flock
    discipline itself — open+LOCK_EX|LOCK_NB, close-releases, kernel
    drops it on SIGKILL — is the shared ``fleet/lease.py`` helper; the
    replicated control plane's manifest writes and leader lease ride the
    same primitive.)"""
    lock_fd = lease.acquire(os.path.join(directory, LOCK_FILENAME))
    if lock_fd is None:
        logger.warning(
            "journal compaction in %s skipped: another compaction "
            "holds the lock (a live server's tick, or a concurrent "
            "`gol compact`)", directory)
        bytes_now = journal_bytes(directory)
        return CompactionReport(
            compacted=False, covers=snapshot_covers(directory),
            segments_retired=0, records_kept=0, terminal_dropped=0,
            bytes_before=bytes_now, bytes_after=bytes_now,
            torn_lines=0,
        )
    try:
        return _compact_locked(directory, retain_results)
    finally:
        lease.release(lock_fd)  # closing releases the flock


def _compact_locked(directory: str,
                    retain_results: int | None) -> CompactionReport:
    before = journal_bytes(directory)
    snap = read_snapshot(directory)
    covered = snap.covers if snap is not None else -1
    segs = sealed_segments(directory)
    stale = [(seq, p) for seq, p in segs if seq <= covered]
    fold = [(seq, p) for seq, p in segs if seq > covered]
    torn = [0]
    if not fold:
        # Nothing new to fold; just sweep retirement leftovers from a
        # compaction killed between commit and delete.
        for _seq, p in stale:
            try:
                os.unlink(p)
            except OSError:
                pass
        return CompactionReport(
            compacted=False, covers=covered, segments_retired=len(stale),
            records_kept=len(snap.records) if snap else 0,
            terminal_dropped=0, bytes_before=before,
            bytes_after=journal_bytes(directory), torn_lines=0,
        )
    pending: dict[str, dict] = {}
    terminal: dict[str, dict] = {}
    if snap is not None:
        _fold(snap.records, pending, terminal, torn)
    for _seq, path in fold:
        _fold(_iter_lines(path, torn), pending, terminal, torn)
    dropped = 0
    tombstones = list(terminal.values())
    if retain_results is not None and len(tombstones) > retain_results:
        dropped = len(tombstones) - retain_results
        tombstones = tombstones[dropped:]
    records = tombstones + list(pending.values())
    covers = fold[-1][0]
    write_snapshot(directory, covers, records)
    # The commit landed: the folded (and any stale) segments are now a
    # strict prefix of the snapshot. The ``retire`` fault boundary fires
    # here — a kill leaves them coexisting, which replay handles by
    # skipping seq <= covers.
    faults.on_compaction("retire")
    retired = 0
    for _seq, path in fold + stale:
        try:
            os.unlink(path)
            retired += 1
        except OSError as err:
            logger.warning("compaction: could not retire %s: %s", path, err)
    if torn[0]:
        logger.warning(
            "journal compaction in %s: dropped %d unparseable line(s) "
            "(same leniency as replay)", directory, torn[0])
    return CompactionReport(
        compacted=True, covers=covers, segments_retired=retired,
        records_kept=len(records), terminal_dropped=dropped,
        bytes_before=before, bytes_after=journal_bytes(directory),
        torn_lines=torn[0],
    )


__all__ = [
    "ACTIVE_FILENAME", "CompactionReport", "DEFAULT_SEGMENT_BYTES",
    "LOCK_FILENAME", "SNAPSHOT_FILENAME", "Snapshot", "compact",
    "iter_records", "journal_bytes", "next_index", "read_snapshot",
    "sealed_segments", "segment_name", "snapshot_covers", "snapshot_path",
    "write_snapshot",
]
