"""Padding-bucket batcher: which requests share a compiled program.

The placement question the process-to-node-mapping literature asks across a
cluster is asked here intra-chip: two jobs may ride one compiled program iff
they agree on everything the trace bakes in. That agreement is the
``BucketKey`` — (padded height, padded width, convention, kernel flavor,
similarity settings). Everything else (each board's true extent and its
generation limit) is a dynamic operand of the batched runner, so one program
per bucket serves every job the bucket ever sees, for the life of the server
(``engine.make_batch_runner`` is lru-cached; the first dispatch of a bucket
pays the compile, every later one only dispatch).

Padding policy: board extents round up to ``PAD_QUANTUM`` so near-miss shapes
(30x30, 31x32, ...) pool in one bucket instead of fragmenting the program
cache; boards that exactly fill their canvas take the fast uniform kernels
(bit-packed words when the width packs), padded boards the masked gather
kernel. Batch sizes round up the ``BATCH_SIZES`` ladder, with inert zero
boards in the padding slots, so a bucket compiles at most
``len(BATCH_SIZES)`` programs ever, not one per request count.
"""

from __future__ import annotations

import bisect
import dataclasses
import logging

import numpy as np

from gol_tpu import engine
from gol_tpu.obs import trace as obs_trace
from gol_tpu.serve.jobs import Job, JobResult

logger = logging.getLogger(__name__)

# Board extents round up to multiples of this (also the packed-word width, so
# every exact-fit bucket width packs). DEFAULT: a measured plan
# (gol_tpu/tune, written by `gol tune`) overrides the quantum and the ladder
# below via the per-process consult in _plan(); with no plan cached the
# consult returns exactly these values, byte-identically (test-pinned).
PAD_QUANTUM = 32

# The batch-size ladder: request counts round up to the next rung so the
# compiled-program space stays small. The last rung is the hard batch cap —
# an invariant plans cannot change (space.valid_serve_plan pins every
# ladder's top rung to MAX_BATCH, so scheduler/server admission bounds hold
# under any plan).
BATCH_SIZES = (1, 2, 4, 8, 16, 32, 64)
MAX_BATCH = BATCH_SIZES[-1]

# The sparse-lane bucket kernel tag (gol_tpu/sparse/): jobs submitted as
# RLE patterns over giant universes. A sparse bucket's jobs are not
# stacked into one canvas — each job batches its own active TILES through
# this module's ladder inside the sparse engine — so the stage/dispatch/
# complete split below routes sparse keys to gol_tpu/sparse/serve.
SPARSE_KERNEL = "sparse"

_PLAN = None  # resolved once per process; tests reset via _reset_plan()


def _plan():
    global _PLAN
    if _PLAN is None:
        from gol_tpu.tune import select

        _PLAN = select.serve_plan(MAX_BATCH)
    return _PLAN


def _reset_plan() -> None:
    """Forget the consulted plan (tests, and in-process tune-then-serve)."""
    global _PLAN
    _PLAN = None


def pad_dim(n: int, plan=None) -> int:
    """Round a board extent up to the bucket quantum.

    ``plan`` (a tune ServePlan) overrides the consulted geometry — the
    tuner's search measures THROUGH these helpers, so the geometry it times
    is by construction the geometry the server later runs."""
    quantum = (plan or _plan()).pad_quantum
    return max(quantum, -(-n // quantum) * quantum)


def pad_batch(n: int, plan=None) -> int:
    """Round a job count (1..MAX_BATCH) up the plan's batch-size ladder.

    Always returns a rung >= n — the padded size the compiled program
    actually runs, which is also the denominator of the occupancy metric
    (occupancy must never exceed 1).
    """
    if not 1 <= n <= MAX_BATCH:
        raise ValueError(f"batch count must be in [1, {MAX_BATCH}], got {n}")
    ladder = (plan or _plan()).batch_ladder
    return ladder[bisect.bisect_left(ladder, n)]


@dataclasses.dataclass(frozen=True)
class BucketKey:
    """Everything two jobs must agree on to share a compiled program."""

    height: int  # padded canvas height
    width: int  # padded canvas width
    convention: str
    kernel: str  # engine batch mode: packed | byte | masked
    check_similarity: bool = True
    similarity_frequency: int = 3

    def label(self) -> str:
        return (
            f"{self.height}x{self.width}/{self.convention}/{self.kernel}"
            + ("" if self.check_similarity else "/nosim")
        )


def bucket_for(job: Job) -> BucketKey:
    """Assign a job its padding bucket.

    Exact-fit boards (extents already on the quantum) get the uniform fast
    kernels; anything else is padded into the masked bucket of its rounded
    shape. The quantum is 32, so every uniform bucket width packs — "byte"
    only arises for hypothetical non-multiple-of-32 quanta, but the routing
    stays honest via ``engine.resolve_batch_mode`` rather than assuming.

    Sparse (RLE) jobs get the sparse bucket of their universe extents —
    no padding (the extents never reach a compiled program's shape; the
    tile size does, inside the sparse engine).
    """
    if job.rle is not None:
        return BucketKey(
            height=job.height,
            width=job.width,
            convention=job.convention,
            kernel=SPARSE_KERNEL,
            check_similarity=job.check_similarity,
            similarity_frequency=job.similarity_frequency,
        )
    ph, pw = pad_dim(job.height), pad_dim(job.width)
    mode = engine.resolve_batch_mode([job.height], [job.width], (ph, pw))
    return BucketKey(
        height=ph,
        width=pw,
        convention=job.convention,
        kernel=mode,
        check_similarity=job.check_similarity,
        similarity_frequency=job.similarity_frequency,
    )


@dataclasses.dataclass
class StagedServeBatch:
    """One bucket batch staged on the host (validated, stacked, packed)."""

    key: BucketKey
    jobs: list
    staged: engine.StagedBatch


@dataclasses.dataclass
class InflightServeBatch:
    """One bucket batch dispatched to the device, results not yet fetched."""

    key: BucketKey
    jobs: list
    inflight: engine.InflightBatch


def stage(key: BucketKey, jobs: list[Job]) -> StagedServeBatch:
    """Host half of a dispatch: validate membership, stack, pad, pack.

    Pure CPU work (the ``packbits`` staging for packed buckets lives
    here), so the pipelined scheduler runs it while the device computes a
    previous batch. Raises on empty/oversized batches and foreign jobs —
    the same checks ``run_batch`` has always enforced."""
    if key.kernel == SPARSE_KERNEL:
        from gol_tpu.sparse import serve as sparse_serve

        return sparse_serve.stage(key, jobs)
    if not jobs:
        raise ValueError("cannot stage an empty batch")
    if len(jobs) > MAX_BATCH:
        raise ValueError(f"batch of {len(jobs)} exceeds MAX_BATCH={MAX_BATCH}")
    for job in jobs:
        jk = bucket_for(job)
        if jk != key:
            raise ValueError(
                f"job {job.id} belongs to bucket {jk.label()}, "
                f"not {key.label()}"
            )
    staged = engine.stage_batch(
        [job.board for job in jobs],
        [job.config for job in jobs],
        padded_shape=(key.height, key.width),
        pad_batch_to=pad_batch(len(jobs)),
        temporal_depth=_plan().temporal_depth,
        # Packed wire submits retained their payload words (Job.words):
        # when every job of a packed-kernel bucket has them, the engine
        # stages straight from the wire layout — no cell canvas, no
        # np.packbits pass (engine_stage_packs_total visibly drops).
        packed_boards=(
            [job.words for job in jobs] if key.kernel == "packed" else None
        ),
    )
    return StagedServeBatch(key=key, jobs=list(jobs), staged=staged)


def dispatch(staged: StagedServeBatch) -> InflightServeBatch:
    """Dispatch a staged batch; returns immediately (JAX async dispatch)."""
    if staged.key.kernel == SPARSE_KERNEL:
        from gol_tpu.sparse import serve as sparse_serve

        return sparse_serve.dispatch(staged)
    return InflightServeBatch(
        key=staged.key, jobs=staged.jobs,
        inflight=engine.dispatch_batch(staged.staged),
    )


def complete(inflight: InflightServeBatch) -> list[JobResult]:
    """Block on an in-flight batch and crop per-job results (job order)."""
    if inflight.key.kernel == SPARSE_KERNEL:
        from gol_tpu.sparse import serve as sparse_serve

        return sparse_serve.complete(inflight)
    results = engine.complete_batch(inflight.inflight)
    return [
        JobResult(grid=r.grid, generations=r.generations,
                  exit_reason=r.exit_reason, words=r.words)
        for r in results
    ]


def run_batch(key: BucketKey, jobs: list[Job]) -> list[JobResult]:
    """Dispatch one bucket's batch through the batched engine.

    Stacks the boards into the bucket canvas (batch dimension rounded up the
    ladder with inert zero boards), runs the cached compiled program, and
    crops each board's slice back out. Per-board results are bit-identical
    to solo runs (the engine contract); ordering matches ``jobs``.

    This synchronous form rides ``engine.simulate_batch`` (itself the
    staged split back to back, one thread); the pipelined scheduler calls
    ``stage``/``dispatch``/``complete`` from its own threads instead.
    """
    if key.kernel == SPARSE_KERNEL:
        from gol_tpu.sparse import serve as sparse_serve

        return sparse_serve.run_batch(key, jobs)
    if not jobs:
        return []
    if len(jobs) > MAX_BATCH:
        raise ValueError(f"batch of {len(jobs)} exceeds MAX_BATCH={MAX_BATCH}")
    for job in jobs:
        jk = bucket_for(job)
        if jk != key:
            raise ValueError(
                f"job {job.id} belongs to bucket {jk.label()}, "
                f"not {key.label()}"
            )
    total = pad_batch(len(jobs))
    with obs_trace.span("batcher.run_batch", bucket=key.label(),
                        jobs=len(jobs), slots=total):
        results = engine.simulate_batch(
            [job.board for job in jobs],
            [job.config for job in jobs],
            padded_shape=(key.height, key.width),
            pad_batch_to=total,
            temporal_depth=_plan().temporal_depth,
        )
    return [
        JobResult(grid=r.grid, generations=r.generations,
                  exit_reason=r.exit_reason, words=r.words)
        for r in results
    ]


def warm(key: BucketKey, batch: int = MAX_BATCH) -> None:
    """Pre-compile a bucket's program (optional server warmup path).

    ``make_batch_runner`` returns a *lazy* jitted callable — tracing and
    compilation happen at the first call, so building it alone warms
    nothing. This dispatches the runner once on inert operands (all-zero
    boards with generation limit 0 never enter the loop in either
    convention), which pays the trace+compile now and executes in
    microseconds; the scalar readback blocks until the program is live.
    """
    import jax.numpy as jnp

    if key.kernel == SPARSE_KERNEL:
        return  # sparse buckets compile per tile size, not per canvas
    total = pad_batch(min(batch, MAX_BATCH))
    runner = engine.make_batch_runner(
        (key.height, key.width),
        total,
        key.convention,
        key.check_similarity,
        key.similarity_frequency,
        key.kernel,
        _plan().temporal_depth,
    )
    if key.kernel == "packed":
        boards = np.zeros((total, key.height, key.width // 32), np.uint32)
    else:
        boards = np.zeros((total, key.height, key.width), np.uint8)
    # Extents of 1 (not 0): the masked kernel wraps indices mod each
    # board's extent, and a zero extent would divide by zero.
    ones = np.ones((total,), np.int32)
    _, gens, _ = runner(
        jnp.asarray(boards), jnp.asarray(ones), jnp.asarray(ones),
        jnp.asarray(np.zeros((total,), np.int32)),
    )
    int(gens[0])
