"""Stdlib-only HTTP JSON API over the scheduler.

Endpoints (all JSON unless noted):

- ``POST /jobs``      — submit a job; body ``{"width", "height", "cells",
  "convention"?, "gen_limit"?, "check_similarity"?, "similarity_frequency"?,
  "priority"?, "deadline_s"?, "no_cache"?}`` where ``cells`` is the
  text-grid encoding (the same bytes the CLI reads/writes). 202 + ``{"id",
  "state"}`` on acceptance, 429 when the queue is full or draining, 400 on
  a bad request. With the result cache mounted (``--result-cache``) a
  repeat board completes at admission; ``no_cache: true`` opts out. An
  ``X-Gol-Trace`` header (a tracing fleet router's stamp) is adopted as
  the job's flow id when tracing is enabled here, and ignored otherwise —
  requests and responses are byte-identical either way (obs/propagate.py).

  **Wire negotiation** (``io/wire.py``): with ``Content-Type:
  application/x-gol-packed`` the body is ONE packed wire frame — the
  header carries width/height, the frame meta carries the remaining
  fields (everything above except ``cells``), the payload carries the
  board at a bit per cell (~8x smaller than text). The retained payload
  words stage straight into packed-kernel buckets (no text decode, no
  ``packbits`` pass). Unknown ``application/x-gol-*`` types (and
  newer frame versions) answer 415 — the client's retry-as-text signal;
  anything else takes the JSON path, byte-identically to pre-wire
  servers (test-pinned). The body cap is content-type-aware: both
  formats accept the same universe of board AREAS
  (``wire.max_body_bytes``), not the same byte count.
- ``GET /jobs/<id>``  — lifecycle state + timings.
- ``GET /result/<id>``— final grid (text-grid string), generations, exit
  reason; 409 while the job is not DONE, 410 for FAILED/CANCELLED. A
  result served by the cache (or a coalesced duplicate) carries
  ``"cached": "memory"|"disk"|"coalesced"``. With
  ``Accept: application/x-gol-packed`` the 200 answer is a packed wire
  frame instead (meta: id/generations/exit_reason/cached; payload: the
  grid) — encoded from result words already in hand when the packed
  kernel or a packed CAS payload produced them, so a binary hit never
  decodes and re-encodes. Error statuses stay JSON for all clients.
- ``DELETE /jobs/<id>`` — cancel a still-QUEUED job; 409 once it has been
  claimed by a batch (dispatch is not interruptible), 404 if unknown.
- ``GET /jobs/<id>/timeline`` — the job's milestone/segment decomposition
  (obs/timeline.py): where this request's latency went, queue-wait through
  journaled DONE. 404 unknown; restored (pre-restart) jobs report
  ``restored`` with no timeline (milestones are process-local).
- ``GET /metrics``    — Prometheus text format (contract byte-stable);
  ``?format=json`` for the JSON snapshot, which additionally carries the
  process-global registry (gauges + histogram summaries — ring occupancy,
  dispatch-gap histogram) under ``process``, the same values
  ``gol trace-report`` renders from a flight dump.
- ``GET /slo``        — the SLO engine's status (obs/slo.py): overall
  health, per-objective multi-window burn rates, shedding state.
- ``GET /debug/trace``— observability snapshot (gol_tpu/obs): tracing
  state, the retained span ring, and the process-global registry counters
  (engine/checkpoint/retry/tuner/halo). Live and read-only — the HTTP
  counterpart of a SIGUSR1 flight-recorder dump.
- ``POST /drain``     — stop admission, flush the queue, wait for in-flight
  batches; responds when quiescent. Idempotent.
- ``GET /healthz``    — liveness + queue stats.

With ``slo_shed`` (CLI ``--slo-shed``) a critical SLO burn sheds new jobs:
``POST /jobs`` answers 429 with a ``Retry-After`` header until the burn
clears. The default is observe-only (test-pinned): burns log and export,
admission is untouched.

The server composes replay-on-start with PR 1's auto-resume story: started
on a journal directory that holds unfinished jobs, it re-queues exactly
those (``JobJournal.replay``) and keeps serving results of finished ones —
kill -9 at any point loses no accepted job and double-runs none.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse, parse_qs

from gol_tpu.io import text_grid, wire
from gol_tpu.obs import (
    history as obs_history,
    propagate as obs_propagate,
    recorder as obs_recorder,
    registry as obs_registry,
    sampler as obs_sampler,
    slo as obs_slo,
    timeline as obs_timeline,
    trace as obs_trace,
)
from gol_tpu.serve.jobs import DONE, FAILED, CANCELLED, JobJournal, new_job
from gol_tpu.serve.metrics import Metrics
from gol_tpu.serve.scheduler import (
    DeadlineExceeded, Draining, JournalUnavailable, QueueFull, Scheduler,
)

# The journaled error-string prefix that marks a failure as a deadline
# expiry (scheduler._fail_batch formats errors as "TypeName: message"):
# result fetches answer 504 for these — including REPLAYED failures,
# where the prefix is all that survives the restart.
_DEADLINE_ERROR_PREFIX = DeadlineExceeded.__name__ + ":"

logger = logging.getLogger(__name__)

# Body caps live in io/wire.py (wire.max_body_bytes, shared with the
# jax-free router so both tiers agree): 64 MiB for text/JSON —
# byte-identical to the pre-wire cap, test-pinned — and the same
# board-AREA universe for packed bodies.


def _decode_cells(cells, width: int, height: int):
    """Strict submit-body board decode: the ``cells`` field must be an
    ASCII string whose cell count matches the declared geometry EXACTLY.
    Every malformed shape — wrong type, non-ASCII bytes, too short, too
    long — raises ValueError/TypeError here, which the handler maps to the
    400 error contract (the reference parser's lenient truncation is for
    FILES; an API body that disagrees with its own geometry is a client
    error, never a silently-cropped board)."""
    if not isinstance(cells, str):
        raise TypeError(
            f"cells must be a string, got {type(cells).__name__}"
        )
    try:
        raw = cells.encode("ascii")
    except UnicodeEncodeError:
        raise ValueError(
            "cells must be ASCII ('0'/'1' rows, newline-separated); "
            "got non-ASCII characters"
        ) from None
    return text_grid.decode(raw, width, height, exact=True)


def _tuned_marginal_rates() -> dict[str, float]:
    """The tuned plan's recorded marginal kernel rates for the dispatch-gap
    monitor, degrading to {} like every other cache problem (a server with
    no tuned marginals still serves; it just has no roofline to compare
    against)."""
    try:
        from gol_tpu.tune import select

        return select.marginal_rates()
    except Exception:  # noqa: BLE001 - cache trouble must not block boot
        logger.warning("could not load tuned marginal rates; the "
                       "dispatch-gap monitor will report rates only",
                       exc_info=True)
        return {}


class GolServer:
    """The serving process: scheduler + journal + HTTP front end."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        journal_dir: str | None = None,
        scheduler: Scheduler | None = None,
        metrics: Metrics | None = None,
        slo: obs_slo.SloEngine | None = None,
        slo_shed: bool = False,
        slo_latency_target: float = 60.0,
        sample_interval: float = 1.0,
        result_cache: bool = False,
        cache_dir: str | None = None,
        cache_entries: int = 1024,
        cache_payload: str = "packed",
        cache_disk_bytes: int | None = None,
        journal_segment_bytes: int | None = None,
        journal_retain: int | None = None,
        disk_reserve: int = 0,
        history_dir: str | None = None,
        history_bytes: int | None = None,
        **scheduler_kwargs,
    ):
        self.metrics = metrics or Metrics()
        journal = (
            JobJournal(journal_dir, **(
                {"segment_bytes": journal_segment_bytes}
                if journal_segment_bytes is not None else {}
            ))
            if journal_dir else None
        )
        self.journal_dir = journal_dir
        self.journal_retain = journal_retain
        # The sharded single-job lane (gol_tpu/shard): mounted lazily on
        # the first /shard/* RPC — a worker that never joins a sharded
        # job pays nothing for the subsystem.
        self._shard = None
        self._shard_lock = threading.Lock()
        # Durable metrics history (obs/history.py): OFF by default — no
        # writer object, no per-tick work. With --metrics-history, every
        # sampler tick appends the serving registry snapshot to the
        # size-capped ring, so this process's window survives it. Built
        # FIRST so the disk guard can journal its transitions into it.
        self.history = None
        if history_dir:
            kwargs = {}
            if history_bytes:
                kwargs["total_bytes"] = history_bytes
                kwargs["segment_bytes"] = min(
                    obs_history.DEFAULT_SEGMENT_BYTES,
                    max(1, history_bytes // 4),
                )
            self.history = obs_history.HistoryWriter(
                history_dir, source="serve", **kwargs
            )
        # The disk-pressure watchdog (resilience/diskguard.py): with
        # --disk-reserve N, free bytes on the journal partition are read
        # every sampler tick and the service degrades in tiers — shed CAS
        # writes, shed checkpoints, refuse admission with 507 — recovering
        # automatically. 0 (the default) mounts no guard.
        self.disk_guard = None
        if disk_reserve and journal_dir:
            from gol_tpu.resilience.diskguard import DiskGuard

            self.disk_guard = DiskGuard(
                journal_dir,
                admission_bytes=disk_reserve,
                registry=self.metrics,
                history=self.history,
                partition=journal_dir,
            )
        # The tiered result cache (gol_tpu/cache): --result-cache mounts the
        # in-process LRU, --cache-dir adds the on-disk CAS tier (and implies
        # enablement). Counters ride the serving registry so hit ratios
        # merge fleet-wide like any other serving series. --cache-disk-bytes
        # budgets the CAS (atime-LRU GC, cache/gc.py); the disk guard sheds
        # its writes first under pressure.
        cache = None
        if result_cache or cache_dir:
            from gol_tpu.cache import ResultCache

            cache = ResultCache(
                memory_entries=cache_entries,
                cas_dir=cache_dir,
                metrics=self.metrics,
                payload=cache_payload,
                disk_bytes=cache_disk_bytes,
                guard=self.disk_guard,
            )
        self.cache = cache
        self.scheduler = scheduler or Scheduler(
            journal=journal, metrics=self.metrics, cache=cache,
            **scheduler_kwargs
        )
        # The SLO engine evaluates the scheduler's own metrics registry;
        # observe-only unless slo_shed (the pinned default). An injected
        # engine keeps its own objectives/thresholds.
        self.slo = slo or obs_slo.SloEngine(
            obs_slo.default_objectives(
                self.scheduler.max_queue_depth,
                latency_target_s=slo_latency_target,
            ),
            registry=self.metrics,
            shed=slo_shed,
        )
        # One background thread ticks the SLO evaluation AND the dispatch-
        # gap monitor (and, when mounted, the metrics-history append);
        # sample_interval <= 0 disables the thread (tests call
        # sampler.tick() themselves).
        self.sampler = obs_sampler.ServeSampler(
            self.metrics,
            slo=self.slo,
            interval=sample_interval if sample_interval > 0 else 1.0,
            marginal_rates=_tuned_marginal_rates(),
            history=self.history,
        )
        # The storage-lifecycle tick: disk-guard watermarks, journal/CAS
        # byte gauges, and idle-time journal compaction all ride the
        # sampler (one thread, one cadence — the gol-serve-sampler).
        self.sampler.add_hook(self.storage_tick)
        self._sample_interval = sample_interval
        # The capacity weight this worker advertises on /healthz (the
        # affinity layer's measured-capacity source, fleet/affinity.py):
        # the tuned per-bucket marginal rates folded to one number — the
        # mean, so two hosts of the same class compare regardless of
        # which buckets each tuned. None when untuned (key omitted).
        rates = self.sampler.marginal_rates
        self.advertised_weight = (
            sum(rates.values()) / len(rates) if rates else None
        )
        self.replayed = 0
        self._replay_results = {}
        self._replay_failed = {}
        self._replay_cancelled = set()
        if journal is not None:
            replay = journal.replay()
            self._replay_results = replay.results
            self._replay_failed = replay.failed
            self._replay_cancelled = replay.cancelled
            self.replayed = self.scheduler.resubmit_replayed(replay.pending)
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    @property
    def url(self) -> str:
        host, port = self.address
        return f"http://{host}:{port}"

    def _boot(self) -> None:
        self.scheduler.start()
        # The SLO state rides every flight-recorder dump: a crash report
        # answers "was the service healthy when it died" on its own.
        obs_recorder.add_state_provider(obs_slo.STATE_PROVIDER, self.slo.state)
        if self.disk_guard is not None:
            # Same standard for the disk guard: a post-mortem should show
            # what pressure level the process died at.
            from gol_tpu.resilience import diskguard

            obs_recorder.add_state_provider(
                diskguard.STATE_PROVIDER, self.disk_guard.state
            )
        if self._sample_interval > 0:
            self.sampler.start()

    def start(self) -> None:
        self._boot()
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="gol-serve-http", daemon=True
        )
        self._thread.start()
        logger.info("gol serve listening on %s", self.url)

    def serve_forever(self) -> None:
        self._boot()
        logger.info("gol serve listening on %s", self.url)
        self.httpd.serve_forever()

    def drain(self, timeout: float | None = None) -> bool:
        return self.scheduler.drain(timeout=timeout)

    def shutdown(self, drain: bool = True) -> None:
        self.sampler.stop()
        if self.history is not None:
            self.history.close()
        obs_recorder.remove_state_provider(obs_slo.STATE_PROVIDER)
        if self.disk_guard is not None:
            from gol_tpu.resilience import diskguard

            obs_recorder.remove_state_provider(diskguard.STATE_PROVIDER)
        self.scheduler.stop(drain=drain)
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self.scheduler.journal is not None:
            self.scheduler.journal.close()

    # -- request-level operations (handler methods stay thin) -------------

    @property
    def shard(self):
        """The lazily-mounted shard host (gol_tpu/shard/worker.py): its
        checkpoint logs live in this worker's journal partition, so a
        respawn on the same partition finds them."""
        if self._shard is None:
            with self._shard_lock:
                if self._shard is None:
                    from gol_tpu.shard.worker import ShardHost

                    self._shard = ShardHost(journal_dir=self.journal_dir)
        return self._shard

    def shard_request(self, leg: str, raw: bytes):
        """One ``POST /shard/<leg>`` RPC -> (status, payload). The packed
        legs (halo, adopt) take GOLP frames; the rest JSON bodies.
        ValueError (ShardError, WireError, malformed JSON) propagates to
        the handler's 400 mapping; an exhausted halo-send budget answers
        503 naming the peer — the coordinator's recovery cue."""
        from gol_tpu.shard.worker import PeerUnreachable

        host = self.shard
        try:
            if leg == "halo":
                return 200, host.halo_in(raw)
            if leg == "adopt":
                return 200, host.adopt(raw)
            body = json.loads(raw.decode("utf-8"))
            if not isinstance(body, dict):
                raise ValueError("shard request body must be a JSON object")
            if leg == "init":
                return 200, host.init_job(body)
            if leg == "step":
                return 200, host.step_job(body["job"], body["step"])
            if leg == "checkpoint":
                return 200, host.checkpoint(body["job"], body["step"])
            if leg == "rewind":
                return 200, host.rewind(body["job"], body["step"],
                                        body.get("peers"))
            if leg == "restore":
                return 200, host.restore_job(body)
            if leg == "status":
                return 200, host.status(body["job"])
            if leg == "rebalance":
                return 200, host.rebalance(body)
            if leg == "collect":
                return 200, host.collect(body["job"],
                                         body.get("which", "current"))
            if leg == "done":
                return 200, host.finish(body["job"])
            return 404, {"error": f"unknown shard leg {leg!r}"}
        except PeerUnreachable as e:
            return 503, {"error": str(e)}

    def submit_json(self, body: dict, trace_header: str | None = None,
                    deadline_header: str | None = None) -> dict:
        if "rle" in body:
            return self._submit_sparse(body, trace_header, deadline_header)
        if body.get("shard"):
            raise ValueError("shard jobs take the sparse input form (rle)")
        required = ("width", "height", "cells")
        missing = [k for k in required if k not in body]
        if missing:
            raise ValueError(f"missing required field(s): {missing}")
        width, height = int(body["width"]), int(body["height"])
        if width <= 0 or height <= 0:
            raise ValueError(f"dimensions must be positive, got {height}x{width}")
        board = _decode_cells(body["cells"], width, height)
        return self._submit_board(board, None, width, height, body,
                                  trace_header, deadline_header)

    def _submit_sparse(self, body: dict,
                       trace_header: str | None = None,
                       deadline_header: str | None = None) -> dict:
        """``POST /jobs`` with an ``rle`` field: a sparse job — a pattern
        placed at (``x``, ``y``) of an otherwise-empty ``width x height``
        universe, run on the sparse tiled engine. Same contract shape as a
        dense submit (202 + id); the full canvas never exists anywhere."""
        required = ("width", "height", "rle")
        missing = [k for k in required if k not in body]
        if missing:
            raise ValueError(f"missing required field(s): {missing}")
        if "cells" in body:
            raise ValueError("a job carries either cells or rle, not both")
        width, height = int(body["width"]), int(body["height"])
        if width <= 0 or height <= 0:
            raise ValueError(f"dimensions must be positive, got {height}x{width}")
        kwargs = {}
        for field in (
            "convention", "gen_limit", "check_similarity",
            "similarity_frequency", "priority", "no_cache", "macro",
            "shard",
        ):
            if field in body:
                kwargs[field] = body[field]
        if body.get("deadline_s") is not None:
            kwargs["deadline_s"] = float(body["deadline_s"])
        job = new_job(
            width, height, None,
            rle=body["rle"],
            place_x=body.get("x", 0),
            place_y=body.get("y", 0),
            tile=body.get("tile", 0),
            **kwargs,
        )
        self.metrics.inc("sparse_submits_total")
        if job.macro:
            self.metrics.inc("macro_submits_total")
        return self._admit(job, trace_header, deadline_header)

    def submit_packed(self, raw: bytes,
                      trace_header: str | None = None,
                      deadline_header: str | None = None) -> dict:
        """``POST /jobs`` with the packed wire Content-Type: one frame in,
        the same 202 payload out. The frame's payload words are retained
        on the job (when the width packs), so a packed-kernel bucket
        stages them without the text decode OR the ``packbits`` pass."""
        frame = wire.decode_frame(raw)
        clash = {"cells", "width", "height", "words"} & frame.meta.keys()
        if clash:
            raise ValueError(
                f"packed frame meta must not carry {sorted(clash)} — "
                "geometry rides the header, the board rides the payload"
            )
        width, height = frame.width, frame.height
        if width <= 0 or height <= 0:
            raise ValueError(f"dimensions must be positive, got {height}x{width}")
        board = frame.grid()
        words = frame.words if width % 32 == 0 else None
        self.metrics.inc("wire_packed_submits_total")
        return self._submit_board(board, words, width, height, frame.meta,
                                  trace_header, deadline_header)

    def _submit_board(self, board, words, width: int, height: int,
                      body: dict, trace_header: str | None,
                      deadline_header: str | None = None) -> dict:
        """The format-independent half of a submit: field validation via
        Job, trace adoption, scheduler admission. ``body`` is the JSON
        object (text lane) or the frame meta (packed lane) — identical
        field vocabulary, so the two lanes cannot drift."""
        kwargs = {}
        for field in (
            "convention", "gen_limit", "check_similarity",
            "similarity_frequency", "priority", "no_cache",
        ):
            if field in body:
                kwargs[field] = body[field]
        if body.get("deadline_s") is not None:
            kwargs["deadline_s"] = float(body["deadline_s"])
        job = new_job(width, height, board, words=words, **kwargs)
        return self._admit(job, trace_header, deadline_header)

    def _admit(self, job, trace_header: str | None,
               deadline_header: str | None = None) -> dict:
        """Trace adoption + deadline adoption + scheduler admission (shared
        by the dense text, packed wire, and sparse RLE submit lanes).

        Trace-context adoption (obs/propagate.py): a router forwarding
        under `--trace` stamps X-Gol-Trace; when tracing is enabled HERE
        too, the job's flow events ride the fleet-wide id and chain onto
        the router's trace. Tracing disabled (the default) never looks at
        the header — an old client (no header) and a headered forward are
        byte-identical through this path, response included (test-pinned).

        Deadline adoption (X-Gol-Deadline, same degradation standard): a
        submit carrying a remaining-budget header is refused 504 HERE when
        the budget arrived spent (scheduler-admission enforcement: no job,
        no journal record, no queue slot), and otherwise stamps
        ``job.expires_at`` for the dispatch-time gate. The budget also
        tightens ``deadline_s`` so dispatch ORDERING sees the urgency. No
        header — every old client and router — changes nothing (pinned);
        malformed values drop silently, exactly like a malformed trace.
        """
        if trace_header is not None and obs_trace.enabled():
            ctx = obs_propagate.decode(trace_header)
            if ctx is not None:
                job.trace = ctx[0]
        budget = obs_propagate.decode_deadline(deadline_header)
        if budget is not None:
            if budget <= 0:
                self.metrics.inc("deadline_expired_total")
                raise DeadlineExceeded(
                    f"deadline budget spent before admission "
                    f"({budget:.3f}s remaining)"
                )
            job.expires_at = self.scheduler.now() + budget
            if job.deadline_s is None or budget < job.deadline_s:
                job.deadline_s = budget
        self.scheduler.submit(job)
        return {"id": job.id, "state": job.state}

    def should_shed(self) -> tuple[bool, float]:
        """Admission-path SLO check (observe-only engines always pass)."""
        shed, retry_after = self.slo.should_shed()
        if shed:
            self.metrics.inc("jobs_shed_total")
        return shed, retry_after

    def should_refuse_disk(self):
        """Admission-path disk check: ``(refuse, free_bytes)``. True only
        at the watchdog's deepest level — the handler answers 507 naming
        the partition, BEFORE reading the body (refusing for lack of disk
        must not first buffer a 17MB board)."""
        if self.disk_guard is None or not self.disk_guard.refuse_admission():
            return False, None
        self.metrics.inc("jobs_refused_disk_total")
        return True, self.disk_guard.free_bytes

    def storage_tick(self) -> None:
        """One storage-lifecycle tick (riding the gol-serve-sampler):
        watchdog watermarks, durable-footprint gauges, and idle-time
        journal compaction — a sealed segment compacts as soon as the
        queue is quiet, or regardless once four have piled up (a busy
        server must still converge on a bounded journal)."""
        if self.disk_guard is not None:
            self.disk_guard.tick()
        journal = self.scheduler.journal
        if journal is not None:
            self.metrics.set_gauge("journal_bytes", journal.bytes_on_disk())
            sealed = journal.sealed_count()
            self.metrics.set_gauge("journal_segments", sealed)
            if sealed >= 1 and (sealed >= 4
                                or self.scheduler.stats()["queued"] == 0):
                try:
                    report = journal.compact(
                        retain_results=self.journal_retain
                    )
                except OSError as err:
                    # ENOSPC while compacting: the segments stay, replay
                    # still works, the next tick retries (ideally after
                    # the guard shed enough writers to free space).
                    self.metrics.inc("journal_errors_total")
                    logger.warning("journal compaction failed (will retry): "
                                   "%s: %s", type(err).__name__, err)
                else:
                    if report.compacted:
                        self.metrics.inc("compactions_total")
                        self.metrics.set_gauge(
                            "journal_bytes", journal.bytes_on_disk()
                        )
                        self.metrics.set_gauge("journal_segments",
                                               journal.sealed_count())
        if self.cache is not None and self.cache.cas is not None:
            self.metrics.set_gauge("cas_bytes", self.cache.cas.usage_bytes())

    def timeline_json(self, job_id: str) -> dict | None:
        """GET /jobs/<id>/timeline payload, or None for an unknown id."""
        job = self.scheduler.job(job_id)
        if job is None:
            if (job_id in self._replay_results
                    or job_id in self._replay_failed
                    or job_id in self._replay_cancelled):
                # The job predates this process; its perf_counter milestones
                # died with the process that ran it.
                return {"id": job_id, "restored": True,
                        "milestones": {}, "segments": {}}
            return None
        # dict() snapshot: worker/journal threads stamp concurrently.
        return {
            "id": job.id,
            "state": job.state,
            **obs_timeline.summary(dict(job.timeline)),
        }

    def job_json(self, job_id: str) -> dict | None:
        job = self.scheduler.job(job_id)
        if job is None:
            if job_id in self._replay_results:
                return {"id": job_id, "state": DONE, "restored": True}
            if job_id in self._replay_failed:
                return {
                    "id": job_id, "state": FAILED, "restored": True,
                    "error": self._replay_failed[job_id],
                }
            if job_id in self._replay_cancelled:
                return {"id": job_id, "state": CANCELLED, "restored": True}
            return None
        out = {"id": job.id, "state": job.state}
        if job.error:
            out["error"] = job.error
        if job.started_at is not None:
            out["queue_seconds"] = job.started_at - job.accepted_at
        if job.finished_at is not None and job.started_at is not None:
            out["run_seconds"] = job.finished_at - job.started_at
        return out

    def _find_result(self, job_id: str):
        """The job's JobResult when it is DONE (live or replayed), else
        None — the format-independent half of GET /result/<id>."""
        job = self.scheduler.job(job_id)
        result = job.result if job is not None and job.state == DONE else None
        if result is None and job_id in self._replay_results:
            result = self._replay_results[job_id]
        return job, result

    def result_json(self, job_id: str):
        """(status_code, payload) for GET /result/<id>."""
        job, result = self._find_result(job_id)
        if result is not None:
            if result.grid is None:
                # Sparse result: the universe answers as RLE (O(live runs)
                # — never dense), plus its live-cell count.
                h, w = result.universe
                return 200, {
                    "id": job_id,
                    "generations": result.generations,
                    "exit_reason": result.exit_reason,
                    "width": int(w),
                    "height": int(h),
                    "rle": result.rle,
                    "population": int(result.population or 0),
                    **({"cached": result.cached} if result.cached else {}),
                }
            return 200, {
                "id": job_id,
                "generations": result.generations,
                "exit_reason": result.exit_reason,
                "width": int(result.grid.shape[1]),
                "height": int(result.grid.shape[0]),
                "grid": text_grid.encode(result.grid).decode("ascii"),
                # Only on cache/coalesced completions (clients print the
                # marker; old-server payloads simply lack the key).
                **({"cached": result.cached} if result.cached else {}),
            }
        if job is None:
            if job_id in self._replay_failed:
                error = self._replay_failed[job_id]
                if error.startswith(_DEADLINE_ERROR_PREFIX):
                    # A deadline expiry that predates this process: the
                    # 504 contract survives the restart (the prefix is
                    # journaled); its perf_counter timeline did not.
                    return 504, {"id": job_id, "state": FAILED,
                                 "error": error, "restored": True}
                return 410, {"id": job_id, "state": FAILED, "error": error}
            if job_id in self._replay_cancelled:
                return 410, {"id": job_id, "state": CANCELLED, "error": None}
            return 404, {"error": f"unknown job {job_id}"}
        if (job.state == FAILED and job.error
                and job.error.startswith(_DEADLINE_ERROR_PREFIX)):
            # The deadline-expiry contract: 504 (the budget ran out, the
            # engine never saw the job) with the PR-7 timeline attached —
            # where the budget actually went is the answer the client
            # needs, and this job will never have a result to carry it.
            return 504, {
                "id": job_id,
                "state": FAILED,
                "error": job.error,
                **obs_timeline.summary(dict(job.timeline)),
            }
        if job.state in (FAILED, CANCELLED):
            return 410, {"id": job_id, "state": job.state, "error": job.error}
        return 409, {"id": job_id, "state": job.state,
                     "error": "result not ready"}

    def result_packed(self, job_id: str):
        """GET /result/<id> under ``Accept: application/x-gol-packed``:
        (status, frame bytes) on success — encoded from the result's
        retained words when a packed kernel or packed CAS payload produced
        them (zero re-pack), from the grid otherwise, byte-identically —
        or (status, JSON payload) on every non-200 (errors stay JSON for
        all clients)."""
        _job, result = self._find_result(job_id)
        if result is None or result.grid is None:
            # No result yet, or a sparse (RLE) result — a giant universe
            # has no packed-frame form; clients parse by response
            # content type, so the JSON answer degrades transparently.
            return self.result_json(job_id)
        meta = {
            "id": job_id,
            "generations": result.generations,
            "exit_reason": result.exit_reason,
            **({"cached": result.cached} if result.cached else {}),
        }
        height, width = (int(x) for x in result.grid.shape)
        self.metrics.inc("wire_packed_results_total")
        if result.words is not None:
            return 200, wire.encode_frame(
                meta, words=result.words, width=width, height=height
            )
        return 200, wire.encode_frame(meta, grid=result.grid)


def _make_handler(server: GolServer):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # Socket timeout for the whole exchange: a client announcing more
        # Content-Length than it sends must not pin a handler thread forever.
        timeout = 60

        # Route logs through logging, not the BaseHTTPRequestHandler default
        # of raw stderr writes (the tree-wide lint rule).
        def log_message(self, format, *args):  # noqa: A002 - stdlib signature
            logger.debug("%s - %s", self.address_string(), format % args)

        def _reply(self, code: int, payload, content_type="application/json",
                   headers=None):
            if isinstance(payload, (bytes, bytearray)):
                body = bytes(payload)  # packed wire frames go out verbatim
            elif content_type == "application/json":
                body = json.dumps(payload).encode("utf-8")
            else:
                body = payload.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            if code >= 400:
                # Error paths may not have consumed the request body (e.g.
                # an over-MAX_BODY reject); closing is the safe way to keep
                # a keep-alive client from desynchronizing.
                self.send_header("Connection", "close")
                self.close_connection = True
            self.end_headers()
            self.wfile.write(body)

        def _read_raw(self) -> bytes:
            """Read the request body under the CONTENT-TYPE-AWARE cap
            (wire.max_body_bytes): the 64 MiB text cap was sized for
            text's ~8x inflation, so packed bodies are capped by the
            equivalent board AREA — the two formats accept the same
            universe of board sizes (boundary-pinned by tests)."""
            length = int(self.headers.get("Content-Length", 0))
            cap = wire.max_body_bytes(self.headers.get("Content-Type"))
            if length > cap:
                raise ValueError(f"body of {length} bytes exceeds {cap}")
            return self.rfile.read(length) if length else b"{}"

        def _read_body(self) -> dict:
            body = json.loads(self._read_raw().decode("utf-8"))
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            return body

        def _discard_body(self) -> None:
            """Drain an unparsed request body: on HTTP/1.1 keep-alive,
            unread body bytes would be parsed as the NEXT request line and
            desynchronize the connection."""
            length = int(self.headers.get("Content-Length", 0))
            while length > 0:
                chunk = self.rfile.read(min(length, 1 << 16))
                if not chunk:
                    break
                length -= len(chunk)

        def do_POST(self):
            path = urlparse(self.path).path
            try:
                if path == "/jobs":
                    # SLO-driven shedding (only ever with --slo-shed): a
                    # critical burn answers 429 + Retry-After BEFORE the
                    # body is read — load shedding that first parses a 17MB
                    # board sheds nothing.
                    shed, retry_after = server.should_shed()
                    if shed:
                        self._reply(
                            429,
                            {"error": "shedding load: SLO burn is critical",
                             "retry_after_s": retry_after},
                            headers={"Retry-After": str(int(retry_after))},
                        )
                        return
                    # Disk-pressure admission refusal (the watchdog's
                    # deepest tier): 507 Insufficient Storage naming the
                    # partition and its free bytes, BEFORE the body is
                    # read. In-flight jobs keep running and their done
                    # records still land — only NEW work is refused, and
                    # admission recovers on its own above the watermark.
                    refuse, free = server.should_refuse_disk()
                    if refuse:
                        self._reply(507, {
                            "error": "insufficient storage: journal "
                                     "partition is under disk pressure",
                            "partition": server.journal_dir,
                            "free_bytes": free,
                        })
                        return
                    ctype = wire.content_type_of(
                        self.headers.get("Content-Type")
                    )
                    trace_header = self.headers.get(
                        obs_propagate.TRACE_HEADER
                    )
                    deadline_header = self.headers.get(
                        obs_propagate.DEADLINE_HEADER
                    )
                    try:
                        if ctype == wire.CONTENT_TYPE:
                            out = server.submit_packed(
                                self._read_raw(), trace_header=trace_header,
                                deadline_header=deadline_header,
                            )
                        elif ctype.startswith(wire.CONTENT_TYPE_FAMILY):
                            # A gol wire format this server does not speak
                            # (a future revision's content type): 415 is
                            # the client's retry-as-text signal. Anything
                            # OUTSIDE the family takes the JSON path — the
                            # compat default, byte-identical to pre-wire
                            # servers (test-pinned).
                            self._discard_body()
                            self._reply(415, {
                                "error": f"unsupported content type "
                                         f"{ctype}; this server speaks "
                                         f"{wire.CONTENT_TYPE} and "
                                         "application/json",
                            })
                            return
                        else:
                            out = server.submit_json(
                                self._read_body(),
                                trace_header=trace_header,
                                deadline_header=deadline_header,
                            )
                    except wire.UnsupportedWire as e:
                        self._reply(415, {"error": str(e)})
                        return
                    except DeadlineExceeded as e:
                        # Admission-time deadline enforcement: the budget
                        # arrived spent — no job was created, no batch
                        # slot will burn for it.
                        self._reply(504, {"error": str(e)})
                        return
                    except (QueueFull, Draining) as e:
                        self._reply(429, {"error": str(e)})
                        return
                    except JournalUnavailable as e:
                        # The submit record could not be journaled (ENOSPC
                        # on the partition): nothing was admitted — 503 is
                        # the client's retry signal, and acknowledging a
                        # job the journal never heard of would let it
                        # vanish on replay.
                        self._reply(503, {"error": str(e)})
                        return
                    self._reply(202, out)
                elif path == "/drain":
                    self._discard_body()
                    drained = server.drain()
                    self._reply(200, {
                        "drained": drained,
                        "stats": server.scheduler.stats(),
                    })
                elif path.startswith("/shard/"):
                    # The sharded single-job lane's worker RPCs
                    # (gol_tpu/shard): halo frames, super-steps,
                    # checkpoints, recovery. Driven by the router's
                    # coordinator, worker-to-worker for halo/adopt.
                    code, payload = server.shard_request(
                        path[len("/shard/"):], self._read_raw()
                    )
                    self._reply(code, payload)
                else:
                    self._discard_body()
                    self._reply(404, {"error": f"no such endpoint {path}"})
            except (ValueError, KeyError, TypeError, OverflowError,
                    json.JSONDecodeError) as e:
                # TypeError covers wrong JSON *types* in otherwise-present
                # fields (priority: null, gen_limit: "x"); OverflowError
                # covers absurd numeric fields reaching numpy/struct
                # boundaries — client errors all, never allowed past Job
                # validation into the queue (and never a 500).
                self._reply(400, {"error": str(e)})

        def do_DELETE(self):
            path = urlparse(self.path).path
            if not path.startswith("/jobs/"):
                self._reply(404, {"error": f"no such endpoint {path}"})
                return
            job_id = path[len("/jobs/"):]
            if server.scheduler.cancel(job_id):
                self._reply(200, {"id": job_id, "state": "cancelled"})
                return
            out = server.job_json(job_id)
            if out is None:
                self._reply(404, {"error": f"unknown job {job_id}"})
            else:
                # Known but no longer cancellable (claimed or terminal).
                self._reply(409, {
                    "id": job_id, "state": out["state"],
                    "error": "job is not queued; cannot cancel",
                })

        def do_GET(self):
            parsed = urlparse(self.path)
            path = parsed.path
            if path.startswith("/jobs/"):
                rest = path[len("/jobs/"):]
                if rest.endswith("/timeline"):
                    out = server.timeline_json(rest[: -len("/timeline")])
                else:
                    out = server.job_json(rest)
                if out is None:
                    self._reply(404, {"error": "unknown job"})
                else:
                    self._reply(200, out)
            elif path.startswith("/result/"):
                job_id = path[len("/result/"):]
                if wire.accepts_packed(self.headers.get("Accept")):
                    code, payload = server.result_packed(job_id)
                    self._reply(
                        code, payload,
                        content_type=(
                            wire.CONTENT_TYPE
                            if isinstance(payload, (bytes, bytearray))
                            else "application/json"
                        ),
                    )
                else:
                    code, payload = server.result_json(job_id)
                    self._reply(code, payload)
            elif path == "/metrics":
                fmt = parse_qs(parsed.query).get("format", ["prometheus"])[0]
                if fmt == "json":
                    # Parity with what `gol trace-report` renders from a
                    # flight dump: the serving snapshot PLUS the process-
                    # global registry's gauges and histogram summaries
                    # (ring occupancy, dispatch-gap distribution, engine
                    # counters) under "process". The Prometheus text
                    # contract below stays byte-stable — serving series
                    # only, test-pinned.
                    snap = server.metrics.snapshot()
                    snap["process"] = obs_registry.default().snapshot()
                    self._reply(200, snap)
                else:
                    self._reply(
                        200, server.metrics.prometheus(),
                        content_type="text/plain; version=0.0.4",
                    )
            elif path == "/slo":
                self._reply(200, server.slo.status())
            elif path == "/debug/trace":
                tracer = obs_trace.tracer()
                self._reply(200, {
                    "enabled": tracer.enabled,
                    "meta": tracer.metadata(),
                    "spans": tracer.snapshot(),
                    "registry": obs_registry.default().snapshot(),
                })
            elif path == "/healthz":
                payload = {"ok": True, "stats": server.scheduler.stats()}
                # Affinity advertisement (fleet/affinity.py): the tuned
                # marginal kernel rate of THIS host's plan cache, when one
                # was measured — a fleet router with --affinity weights
                # bucket placement by it. Absent (the untuned default),
                # the key is omitted and the payload is byte-identical to
                # the pre-affinity contract.
                if server.advertised_weight is not None:
                    payload["weight"] = server.advertised_weight
                self._reply(200, payload)
            else:
                self._reply(404, {"error": f"no such endpoint {path}"})

    return Handler
