"""The ``Job`` record and the crash-safe append-only job journal.

A job is one board's simulation request plus its lifecycle state machine:

    QUEUED -> SCHEDULED -> RUNNING -> DONE | FAILED
    QUEUED -> CANCELLED

The journal is the serving counterpart of ``gol_tpu/resilience/checkpoint``'s
durability discipline, adapted to a queue: instead of write-fresh-then-commit
(state that is *replaced*), a queue's history only ever *grows*, so the
crash-safe shape is an append-only JSONL log where every record is a single
``os.write`` to an ``O_APPEND`` descriptor followed by ``fsync``. A crash can
tear at most the final line; replay tolerates (and drops) a torn tail, so the
journal a restarted server reads is always a prefix of accepted truth —
exactly the property the checkpoint manifest's atomic ``os.replace`` buys for
snapshots.

Replay returns (a) every accepted job with no terminal record — the work a
restarted server must finish — and (b) the results of completed jobs, so
``GET /result/<id>`` keeps answering across restarts. A job is DONE exactly
once: the scheduler only dispatches jobs replay handed back as pending, and
replay drops a pending job the moment a ``done`` record for its id appears.

Timestamps: queue/run latencies use ``time.perf_counter()`` (monotonic; the
wall clock is banned from this package's latency paths by tests/test_lint.py
— wall clocks step under NTP and make p99s lie).
Perf-counter values are process-local, so they are never journaled; replayed
jobs get fresh arrival stamps.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import uuid

import numpy as np

from gol_tpu.config import Convention, GameConfig
from gol_tpu.io import text_grid
from gol_tpu.resilience import fsio
from gol_tpu.serve import compaction

logger = logging.getLogger(__name__)

# Lifecycle states (the serving state machine).
QUEUED = "queued"
SCHEDULED = "scheduled"  # claimed by a forming batch, not yet on device
RUNNING = "running"  # batch dispatched to the compiled program
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"

TERMINAL_STATES = frozenset({DONE, FAILED, CANCELLED})

# Legal transitions; anything else is a server bug and raises loudly.
# Batch retries happen while jobs are held in RUNNING (the RetryPolicy wraps
# the dispatch; nothing ever re-queues a claimed job), so RUNNING's only
# exits are terminal.
# QUEUED -> DONE is the result-cache path (gol_tpu/cache): a hit — or a
# coalesced duplicate completed by its in-flight leader — finishes without
# ever being claimed by a batch.
_TRANSITIONS = {
    QUEUED: {SCHEDULED, CANCELLED, FAILED, DONE},
    SCHEDULED: {RUNNING, FAILED},
    RUNNING: {DONE, FAILED},
    DONE: set(),
    FAILED: set(),
    CANCELLED: set(),
}


@dataclasses.dataclass
class JobResult:
    """What a finished job hands back (mirrors engine.BatchBoardResult).

    Sparse jobs (gol_tpu/sparse/) answer with ``grid=None`` and the final
    universe as RLE instead — a giant universe's dense cells must never
    travel the stack; ``universe`` carries the (height, width) the dense
    path reads off ``grid.shape``."""

    grid: np.ndarray | None  # uint8 {0,1}, (height, width); None = sparse
    generations: int
    exit_reason: str  # engine.EXIT_REASONS member
    # How the answer was produced: None = the engine ran it; "memory"/"disk"
    # = a result-cache tier served it; "coalesced" = an identical in-flight
    # submission's engine run completed it. Journaled in the done record so
    # restarted servers keep reporting it (clients print the marker).
    cached: str | None = None
    # The grid's packed wire words (io/wire.py row layout), when a hop
    # already had them in hand — a packed-kernel engine readback or a
    # packed CAS payload. Lets a packed GET /result answer without a
    # re-pack; None (replayed results, masked/byte kernels) means the
    # responder packs from ``grid`` on demand. Process-local, never
    # journaled (the journal's done records stay text).
    words: np.ndarray | None = None
    # Sparse-lane result fields (gol_tpu/sparse/): the final universe as
    # an RLE document + its live-cell count, with the universe extents
    # (height, width) the dense path reads off ``grid.shape``. RLE and
    # population are journaled (they ARE the result); the work accounting
    # below is process-local (serving metrics only — tile-steps executed
    # and the cell updates they represent, the sparse analog of
    # height x width x generations).
    rle: str | None = None
    population: int | None = None
    universe: tuple[int, int] | None = None
    tiles_simulated: int | None = None
    cell_updates: int | None = None
    occupancy: float | None = None


@dataclasses.dataclass
class Job:
    """One simulation request moving through the service.

    Two input forms: dense (``board`` holds the (height, width) cells —
    the classic lane) and sparse (``rle`` holds a pattern placed at
    ``(place_x, place_y)`` in an otherwise-empty ``height x width``
    universe; ``board`` is None and the job runs on the sparse tiled
    engine). ``width``/``height`` are the universe extents either way, so
    routing (fleet placement, bucket keys) reads one vocabulary."""

    id: str
    width: int
    height: int
    board: np.ndarray | None  # uint8 {0,1}, (height, width); None = sparse
    convention: str = Convention.C
    gen_limit: int = GameConfig().gen_limit
    check_similarity: bool = True
    similarity_frequency: int = GameConfig().similarity_frequency
    priority: int = 0  # higher dispatches first within a bucket
    deadline_s: float | None = None  # seconds from acceptance; orders dispatch
    no_cache: bool = False  # opt this submission out of the result cache
    # Sparse job fields (gol_tpu/sparse/): an RLE pattern document placed
    # with its top-left cell at column place_x, row place_y of the
    # universe; tile 0 means the engine default. All journaled — a
    # replayed sparse job re-runs from exactly this spec (the occupancy
    # index is rebuilt from it, so replay needs no dense cells).
    rle: str | None = None
    place_x: int = 0
    place_y: int = 0
    tile: int = 0
    # Run this sparse job on the macrocell engine (gol_tpu/macro/) instead
    # of the per-generation sparse loop. Journaled (replay must pick the
    # same engine for work-accounting stability) but NOT a result axis:
    # the macro engine is byte-identical to sparse by contract, so the
    # flag is an execution hint, like picking a kernel.
    macro: bool = False
    # The sharded single-job form (gol_tpu/shard): accepted ONLY by a
    # fleet router, which runs the job as coordinated super-steps across
    # its workers instead of queueing it here. The field exists on Job so
    # a shard submit aimed at a plain worker fails loudly at admission
    # (400) rather than silently running single-worker.
    shard: bool = False
    state: str = QUEUED
    # The result-cache key (gol_tpu/cache/fingerprint.py), computed by the
    # scheduler at admission when a cache is mounted; None otherwise (and
    # for no_cache jobs). Process-local — replayed jobs re-derive it.
    fingerprint: str | None = None
    # The board's packed wire words, retained from a packed submit
    # (io/wire.py) when the width packs (W % 32 == 0): the batcher hands
    # them straight to the packed-kernel staging lane, skipping the
    # ``np.packbits`` pass the text path pays (engine_stage_packs_total
    # visibly drops under packed traffic). Process-local like the stamps
    # below — never journaled; replayed jobs re-stage from ``board``.
    words: np.ndarray | None = None
    # The propagated fleet trace id (obs/propagate.py): set at admission
    # when the router stamped an ``X-Gol-Trace`` header AND tracing is
    # enabled in this process — the job's flow events then carry the
    # fleet-wide id and chain onto the router's trace. Process-local like
    # the perf_counter stamps; never journaled (replayed jobs have no
    # live trace to join).
    trace: str | None = None
    # The propagated deadline budget's expiry (obs/propagate.py
    # X-Gol-Deadline): an ABSOLUTE perf_counter instant set at admission
    # when the submit carried a remaining-budget header. Enforced at batch
    # dispatch (scheduler: an expired job fails with the 504 contract
    # instead of burning a batch slot). Process-local like every other
    # perf_counter stamp — never journaled; a replayed job has no live
    # client waiting on the old budget, so it simply runs (the journal's
    # every-accepted-job-terminates contract wins).
    expires_at: float | None = None
    # perf_counter stamps, process-local (never journaled).
    accepted_at: float = 0.0
    started_at: float | None = None
    finished_at: float | None = None
    result: JobResult | None = None
    error: str | None = None
    # Per-job milestone stamps (obs/timeline.py vocabulary): perf_counter
    # values keyed by milestone name, stamped by the scheduler identically
    # across the classic/pipelined/resident lanes. Process-local like the
    # *_at fields above — never journaled; replayed jobs restart empty.
    timeline: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        # Normalize numeric fields FIRST: jobs arrive from untrusted JSON,
        # and a job admitted with e.g. priority=None would not fail until a
        # worker computes its dispatch key — killing the worker thread, not
        # the request. int()/float() raise TypeError/ValueError here, inside
        # the admission path, where the server maps them to HTTP 400.
        self.width, self.height = int(self.width), int(self.height)
        self.gen_limit = int(self.gen_limit)
        self.similarity_frequency = int(self.similarity_frequency)
        # Strict bool: bool("false") is True, so coercion would silently
        # ENABLE the check a string-typed client asked to disable.
        if not isinstance(self.check_similarity, bool):
            raise TypeError(
                f"check_similarity must be a JSON boolean, got "
                f"{type(self.check_similarity).__name__}"
            )
        # Same strictness for the cache opt-out: bool("false") is True, and
        # a truthy-string no_cache would silently bypass the cache (the
        # harmless direction) while {"no_cache": 0} meaning "do cache"
        # already works — a non-bool is a client error either way.
        if not isinstance(self.no_cache, bool):
            raise TypeError(
                f"no_cache must be a JSON boolean, got "
                f"{type(self.no_cache).__name__}"
            )
        # Same strictness again for the engine hint, and it only means
        # anything on the sparse input form.
        if not isinstance(self.macro, bool):
            raise TypeError(
                f"macro must be a JSON boolean, got "
                f"{type(self.macro).__name__}"
            )
        if self.macro and self.rle is None:
            raise ValueError("macro jobs take the sparse input form (rle)")
        if not isinstance(self.shard, bool):
            raise TypeError(
                f"shard must be a JSON boolean, got "
                f"{type(self.shard).__name__}"
            )
        if self.shard:
            raise ValueError(
                "shard jobs are router-driven: submit them to a fleet "
                "router (gol fleet), not directly to a worker"
            )
        self.priority = int(self.priority)
        if self.deadline_s is not None:
            self.deadline_s = float(self.deadline_s)
        if self.width <= 0 or self.height <= 0:
            raise ValueError(
                f"job dimensions must be positive, got {self.height}x{self.width}"
            )
        if self.gen_limit < 0:
            raise ValueError(f"gen_limit must be >= 0, got {self.gen_limit}")
        if self.similarity_frequency <= 0:
            raise ValueError(
                f"similarity_frequency must be > 0, got {self.similarity_frequency}"
            )
        if self.convention not in (Convention.C, Convention.CUDA):
            raise ValueError(f"unknown convention: {self.convention!r}")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {self.deadline_s}")
        if self.rle is not None:
            self._init_sparse()
        else:
            self.board = np.ascontiguousarray(
                np.asarray(self.board, dtype=np.uint8)
            )
            if self.board.shape != (self.height, self.width):
                raise ValueError(
                    f"board shape {self.board.shape} does not match declared "
                    f"{self.height}x{self.width}"
                )
        # Retained wire words are a pure staging accelerator: anything that
        # does not exactly match the packed-kernel operand shape is dropped
        # (the board stages through the classic pack), never trusted.
        if self.words is not None and (
            self.width % 32 != 0
            or self.words.shape != (self.height, self.width // 32)
        ):
            self.words = None

    def _init_sparse(self) -> None:
        """Validate + pre-parse a sparse (RLE) job at admission: every
        malformed shape raises here, inside the server's 400 mapping,
        never on a worker thread. The full byte canvas is NEVER built —
        only the small pattern array (process-local; replay re-parses)."""
        from gol_tpu.io import rle as rle_codec
        from gol_tpu.sparse.board import DEFAULT_TILE, MIN_TILE

        if not isinstance(self.rle, str):
            raise TypeError(
                f"rle must be a string, got {type(self.rle).__name__}"
            )
        if self.board is not None:
            raise ValueError("a job carries either cells or rle, not both")
        self.place_x = int(self.place_x)
        self.place_y = int(self.place_y)
        self.tile = int(self.tile)
        if self.tile == 0:
            self.tile = DEFAULT_TILE
        if self.tile < MIN_TILE:
            raise ValueError(f"tile must be >= {MIN_TILE}, got {self.tile}")
        if self.height % self.tile or self.width % self.tile:
            raise ValueError(
                f"universe {self.height}x{self.width} does not divide into "
                f"{self.tile}^2 tiles"
            )
        self.pattern = rle_codec.parse(self.rle)
        ph, pw = self.pattern.shape
        if (self.place_x < 0 or self.place_y < 0
                or self.place_y + ph > self.height
                or self.place_x + pw > self.width):
            raise ValueError(
                f"pattern {ph}x{pw} at ({self.place_x},{self.place_y}) does "
                f"not fit the {self.height}x{self.width} universe"
            )

    @property
    def config(self) -> GameConfig:
        return GameConfig(
            gen_limit=self.gen_limit,
            check_similarity=self.check_similarity,
            similarity_frequency=self.similarity_frequency,
            convention=self.convention,
        )

    def transition(self, new_state: str) -> None:
        if new_state not in _TRANSITIONS[self.state]:
            raise ValueError(
                f"job {self.id}: illegal transition {self.state} -> {new_state}"
            )
        self.state = new_state

    def flow_id(self) -> str:
        """The Perfetto flow id this job's lifecycle events ride: the
        propagated fleet trace id when a router stamped one (so the chain
        crosses the process boundary), the job id otherwise — byte-for-byte
        the pre-propagation behavior."""
        return self.trace or self.id

    def dispatch_key(self):
        """Sort key for dispatch order inside a bucket: higher priority
        first, then nearest deadline, then arrival order."""
        deadline = (
            self.accepted_at + self.deadline_s
            if self.deadline_s is not None
            else float("inf")
        )
        return (-self.priority, deadline, self.accepted_at, self.id)

    def to_record(self) -> dict:
        """The journaled (durable) fields — everything needed to re-run.

        Sparse jobs journal their RLE spec (pattern + placement + tile)
        instead of dense cells: the occupancy index is a pure function of
        the spec, so replay rebuilds it without a canvas ever existing."""
        if self.rle is not None:
            payload = {
                "rle": self.rle,
                "x": self.place_x,
                "y": self.place_y,
                "tile": self.tile,
                # Only when set, like no_cache below: default-engine
                # records stay byte-stable and old journals replay sparse.
                **({"macro": True} if self.macro else {}),
            }
        else:
            payload = {"cells": text_grid.encode(self.board).decode("ascii")}
        return {
            "id": self.id,
            "width": self.width,
            "height": self.height,
            "convention": self.convention,
            "gen_limit": self.gen_limit,
            "check_similarity": self.check_similarity,
            "similarity_frequency": self.similarity_frequency,
            "priority": self.priority,
            "deadline_s": self.deadline_s,
            **payload,
            # Only when set: default-path submit records stay byte-stable,
            # and old journals replay with the default (cache allowed).
            **({"no_cache": True} if self.no_cache else {}),
        }

    @classmethod
    def from_record(cls, rec: dict) -> "Job":
        sparse = "rle" in rec
        board = None if sparse else text_grid.decode(
            rec["cells"].encode("ascii"), rec["width"], rec["height"]
        )
        extra = {}
        if sparse:
            extra = {
                "rle": rec["rle"],
                "place_x": rec.get("x", 0),
                "place_y": rec.get("y", 0),
                "tile": rec.get("tile", 0),
                "macro": rec.get("macro", False),
            }
        return cls(
            id=rec["id"],
            width=rec["width"],
            height=rec["height"],
            board=board,
            **extra,
            convention=rec.get("convention", Convention.C),
            gen_limit=rec.get("gen_limit", GameConfig().gen_limit),
            check_similarity=rec.get("check_similarity", True),
            similarity_frequency=rec.get(
                "similarity_frequency", GameConfig().similarity_frequency
            ),
            priority=rec.get("priority", 0),
            deadline_s=rec.get("deadline_s"),
            no_cache=rec.get("no_cache", False),
            accepted_at=time.perf_counter(),
        )


def priority_class(priority: int) -> str:
    """The SLO bucketing of a job priority: objectives are declared per
    *class* (high > 0, normal == 0, low < 0), not per raw integer — a fleet
    cannot carry one latency histogram per arbitrary client-chosen int."""
    if priority > 0:
        return "high"
    if priority < 0:
        return "low"
    return "normal"


def new_job(width: int, height: int, board, **kwargs) -> Job:
    return Job(
        id=uuid.uuid4().hex,
        width=width,
        height=height,
        board=board,
        accepted_at=time.perf_counter(),
        **kwargs,
    )


@dataclasses.dataclass
class ReplayState:
    """What a journal replay recovers."""

    pending: list  # Jobs accepted but not terminal — re-run these
    results: dict  # id -> JobResult for DONE jobs — keep serving these
    failed: dict  # id -> error string
    cancelled: set  # ids
    torn_lines: int  # dropped unparseable tail/garbage lines


class JobJournal:
    """Append-only JSONL journal; every append is one write + fsync.

    **Segmented** (gol_tpu/serve/compaction.py): the live file rotates into
    sealed ``journal-<seq>.jsonl`` segments past ``segment_bytes``, and
    ``compact()`` folds sealed segments into a CRC-stamped snapshot so the
    durable footprint stays bounded. Replay = snapshot + segments newer
    than it + the live file — the append path (and its crash contract) is
    byte-identical to the unsegmented journal; rotation is one atomic
    rename under the same lock. ``segment_bytes`` None/0 disables rotation
    (the PR-2 single-file layout, which replay still reads forever)."""

    FILENAME = compaction.ACTIVE_FILENAME

    def __init__(self, directory: str,
                 segment_bytes: int | None = compaction.DEFAULT_SEGMENT_BYTES):
        self.directory = directory
        self.segment_bytes = segment_bytes or 0
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, self.FILENAME)
        self._fd = os.open(self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._active_bytes = os.fstat(self._fd).st_size
        # The next segment seq, computed ONCE (one snapshot-header read)
        # and counted up in-process: seqs are minted only here, and our
        # own compactions can only fold seqs we already minted, so the
        # cached counter can never fall at or below `covers` — and the
        # append lock never waits on an O(history) snapshot re-read.
        self._next_seq = (compaction.next_index(directory)
                          if self.segment_bytes else 0)
        # Appends come from both the accept path and worker threads. A
        # process-level lock (not just O_APPEND) keeps records whole even
        # when os.write returns short (large done records, ENOSPC mid-way):
        # the write-all loop below may take several syscalls, and another
        # thread's record landing between two chunks would weld both records
        # into one unparseable line — losing TWO events, one of which could
        # be a `done` (a replay would then re-run a completed job).
        self._lock = threading.Lock()

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def _append(self, record: dict) -> None:
        self._append_encoded(
            (json.dumps(record, separators=(",", ":")) + "\n").encode("utf-8")
        )

    def _append_encoded(self, data: bytes) -> None:
        with self._lock:
            fsio.write_all(self._fd, data, "journal append")
            os.fsync(self._fd)
            self._active_bytes += len(data)
            if self.segment_bytes and self._active_bytes >= self.segment_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Seal the live file as the next segment and open a fresh one.

        Rename first, close-and-reopen second: the O_APPEND fd stays valid
        across the rename, so if anything here fails the journal keeps
        appending with zero lost records. A failure BETWEEN the two steps
        is rolled back (rename the file back under the live name): the
        appender must never keep writing a file that carries a SEALED
        name, because compaction folds-and-deletes sealed segments — a
        concurrent compaction would silently drop every record appended
        after the half-rotation."""
        sealed = os.path.join(self.directory,
                              compaction.segment_name(self._next_seq))
        try:
            os.replace(self.path, sealed)
        except OSError as err:
            logger.warning(
                "journal rotation in %s failed (%s); continuing to append "
                "to the current file", self.directory, err)
            return
        try:
            new_fd = os.open(
                self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
            )
        except OSError as err:
            try:
                os.replace(sealed, self.path)
                logger.warning(
                    "journal rotation in %s could not open a fresh live "
                    "file (%s); rolled the rename back", self.directory, err)
            except OSError as undo_err:
                # Same-directory rename-back almost cannot fail; if it
                # does, appends continue on the held fd but the file now
                # wears a sealed name — scream, because only an operator
                # can restore the invariant.
                logger.critical(
                    "journal rotation in %s stranded the live journal "
                    "under sealed name %s (open: %s; rollback: %s) — "
                    "records keep appending there but COMPACTION MAY "
                    "RETIRE IT; free descriptors/space and restart",
                    self.directory, sealed, err, undo_err)
            return
        os.close(self._fd)
        self._fd = new_fd
        self._active_bytes = 0
        self._next_seq += 1

    # -- storage lifecycle --------------------------------------------------

    def bytes_on_disk(self) -> int:
        """Durable footprint: snapshot + sealed segments + the live file."""
        return compaction.journal_bytes(self.directory)

    def sealed_count(self) -> int:
        return len(compaction.sealed_segments(self.directory))

    def compact(self, retain_results: int | None = None):
        """Fold sealed segments into the snapshot (compaction.compact):
        safe while this journal is live — compaction never touches the
        file the appender holds."""
        return compaction.compact(self.directory,
                                  retain_results=retain_results)

    def record_submit(self, job: Job) -> None:
        self._append({"event": "submit", "job": job.to_record()})

    @staticmethod
    def _done_record(job: Job) -> dict:
        r = job.result
        if r.grid is None:
            # Sparse result: the final universe travels as RLE (O(live
            # runs) — a 2^16-square answer must never be journaled dense).
            h, w = r.universe
            return {
                "event": "done",
                "id": job.id,
                "generations": r.generations,
                "exit_reason": r.exit_reason,
                "width": int(w),
                "height": int(h),
                "rle": r.rle,
                "population": int(r.population or 0),
                **({"cached": r.cached} if r.cached else {}),
            }
        return {
            "event": "done",
            "id": job.id,
            "generations": r.generations,
            "exit_reason": r.exit_reason,
            # Self-contained: replay decodes the result without needing
            # the submit record to have survived.
            "width": int(r.grid.shape[1]),
            "height": int(r.grid.shape[0]),
            "grid": text_grid.encode(r.grid).decode("ascii"),
            # Only on cache/coalesced completions: engine-path records stay
            # byte-stable, old journals replay as engine results.
            **({"cached": r.cached} if r.cached else {}),
        }

    def record_done(self, job: Job) -> None:
        self._append(self._done_record(job))

    def record_done_many(self, jobs: list[Job]) -> None:
        """One write-all + ONE fsync for a whole batch's done records.

        The lines are byte-identical to ``record_done`` per job, so replay
        is oblivious; batching only amortizes the fsync — the dominant
        per-job serial host cost of the serve hot path. A torn tail still
        loses at most a suffix of complete lines (each line is appended
        whole), which replay already tolerates by re-running those jobs.
        A single job routes through ``record_done`` so the two paths cannot
        drift (and tests that instrument it see every singleton append).
        """
        if not jobs:
            return
        if len(jobs) == 1:
            self.record_done(jobs[0])
            return
        self._append_encoded(b"".join(
            (json.dumps(self._done_record(j), separators=(",", ":")) + "\n")
            .encode("utf-8")
            for j in jobs
        ))

    def record_failed(self, job: Job) -> None:
        self._append({"event": "failed", "id": job.id, "error": job.error or ""})

    def record_cancelled(self, job: Job) -> None:
        self._append({"event": "cancelled", "id": job.id})

    @staticmethod
    def _apply_record(rec: dict, pending: dict, results: dict,
                      failed: dict, cancelled: set) -> None:
        """Apply ONE parsed journal record to the replay state (shared by
        snapshot records and journal lines — the snapshot speaks the
        journal's exact vocabulary, so one parser serves both)."""
        event = rec["event"]
        if event == "submit":
            job = Job.from_record(rec["job"])
            pending[job.id] = job
        elif event == "done":
            if "rle" in rec:
                results[rec["id"]] = JobResult(
                    grid=None,
                    generations=rec["generations"],
                    exit_reason=rec["exit_reason"],
                    rle=rec["rle"],
                    population=rec.get("population"),
                    universe=(rec["height"], rec["width"]),
                    cached=rec.get("cached"),
                )
            else:
                grid = text_grid.decode(
                    rec["grid"].encode("ascii"),
                    rec["width"],
                    rec["height"],
                )
                results[rec["id"]] = JobResult(
                    grid=grid,
                    generations=rec["generations"],
                    exit_reason=rec["exit_reason"],
                    cached=rec.get("cached"),
                )
            pending.pop(rec["id"], None)
        elif event == "failed":
            failed[rec["id"]] = rec.get("error", "")
            pending.pop(rec["id"], None)
        elif event == "cancelled":
            cancelled.add(rec["id"])
            pending.pop(rec["id"], None)
        else:
            raise ValueError(f"unknown event {event!r}")

    def _replay_file(self, path: str, pending: dict, results: dict,
                     failed: dict, cancelled: set) -> int:
        """Apply one JSONL file's records; returns the torn-line count."""
        torn = 0
        if not os.path.exists(path):
            return 0
        with open(path, "rb") as f:
            raw = f.read()
        for line in raw.split(b"\n"):
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
                self._apply_record(rec, pending, results, failed, cancelled)
            except (ValueError, KeyError, UnicodeDecodeError):
                torn += 1
        return torn

    def replay(self) -> ReplayState:
        """Rebuild queue state from the journal (crash-tolerant).

        Reads, in order: the committed snapshot (if any), sealed segments
        NEWER than it — a segment at or below the snapshot's high-water
        mark is a fully-folded leftover of a compaction killed between
        commit and retirement, skipped here and swept by the next
        compaction — and finally the live file. Unparseable lines are
        dropped, not fatal: the only way one arises is a crash mid-append
        (a torn tail) — by the append discipline there can be at most one,
        but replay is lenient to all of them and reports the count so
        operators see unexpected corruption.
        """
        pending: dict[str, Job] = {}
        results: dict[str, JobResult] = {}
        failed: dict[str, str] = {}
        cancelled: set[str] = set()
        torn = 0
        covers = -1
        snap = compaction.read_snapshot(self.directory)
        if snap is not None:
            covers = snap.covers
            for rec in snap.records:
                try:
                    self._apply_record(rec, pending, results, failed,
                                       cancelled)
                except (ValueError, KeyError, UnicodeDecodeError):
                    torn += 1
        for seq, seg_path in compaction.sealed_segments(self.directory):
            if seq <= covers:
                continue  # folded into the snapshot (torn retirement)
            torn += self._replay_file(seg_path, pending, results, failed,
                                      cancelled)
        torn += self._replay_file(self.path, pending, results, failed,
                                  cancelled)
        if torn:
            logger.warning(
                "job journal %s: dropped %d unparseable line(s) on replay "
                "(a crash tears at most the final append; more suggests "
                "external corruption)",
                self.path, torn,
            )
        return ReplayState(
            pending=list(pending.values()),
            results=results,
            failed=failed,
            cancelled=cancelled,
            torn_lines=torn,
        )
