"""Batched multi-tenant simulation service: runs as *requests*, not processes.

Every other entry point in the tree keeps the reference's main()-per-run
shape — one board, one process, exit. This package is the first subsystem
that amortizes compilation and dispatch across many independent requests
(SURVEY layers L3-L6):

- ``jobs``      — the ``Job`` record, its QUEUED -> ... -> DONE state
                  machine, and a crash-safe append-only journal so a
                  restarted server replays unfinished work (composing with
                  the ``gol_tpu/resilience`` auto-resume story);
- ``batcher``   — groups compatible jobs into padding buckets and drives
                  the batched engine entry (``engine.simulate_batch``'s
                  runner): one compiled program per bucket, cached for the
                  life of the server;
- ``scheduler`` — admission control, priority/deadline-aware dispatch,
                  flush-on-size-or-age batch forming, graceful drain, and
                  RetryPolicy-wrapped dispatch for transient device errors;
- ``server``    — a stdlib-only HTTP JSON API over the scheduler;
- ``metrics``   — the counters/gauges/latency histograms behind
                  ``GET /metrics`` (JSON and Prometheus text).

Import layering: ``jobs`` and ``metrics`` are numpy/stdlib-only; the
jax-heavy engine is pulled in by ``batcher`` at dispatch time.
"""
