"""Device-resident ring lanes: the serve hot path's mega-batch engine.

The pipelined scheduler (gol_tpu/serve/scheduler.py, ``pipeline_depth >=
2``) overlaps host staging with device compute, but still pays one Python
jit dispatch — operand transfer, program launch, scalar sync — per batch.
This module removes that per-batch tax: each padding bucket gets a
**ResidentLane**, a ring of R slots bound to ONE compiled drain program
(``engine.make_ring_runner``). The dispatcher stages batches into slots —
each slot's operand is ``device_put`` at submit time, so the host-to-device
transfer runs while an earlier drain computes — and a drain of up to R
batches dispatches as a single program, every slot's output aliased over
its input buffer (donation across the ring).

This is the reference's ``src/game_mpi_async.c`` iwrite/Wait discipline
pushed one level further down: where PR 5's pipeline posted one async
*dispatch* per batch and waited at the next boundary, the resident lane
posts one async *drain* per R batches and the in-XLA fori over slots is
the wait-free inner loop. The ``pipeline/inflight.Handoff`` window still
carries the per-batch flights between the scheduler's threads; the lane
sits underneath it, deciding when staged slots become a drain:

- **ring full** — R slots staged: dispatch now (the steady-state path);
- **rung change** — a staged batch padded to a different batch-size rung
  cannot share the compiled program: flush the open slots first;
- **completion demand** — the completer reached a flight whose slot is
  staged but not dispatched: flush immediately (waiting could deadlock —
  the dispatcher may have nothing more to stage). Under backlog the
  completer is busy finalizing earlier drains while slots accumulate, so
  this path naturally fires with a fuller ring the heavier the load.

Observability (the obs default registry, so ``GET /debug/trace``, the
flight recorder, and ``gol trace-report`` all see it):

- ``serve.resident_loop`` span per drain readback (bucket, filled, ring);
- ``dispatch_gap_seconds`` histogram — host-observed device idle between a
  drain finishing and the next dispatch (0 when the next drain was already
  queued behind it, the closed-gap case);
- ``ring_slot_occupancy`` gauge — filled/ring at each dispatch;
- a ``resident_rings`` flight-recorder state provider (per-lane open slot
  and unresolved-drain counts), so a crash dump shows what was mid-ring.

Exactly-once is untouched: the lane never journals — the scheduler's
completer journals per batch from drain results, and a SIGKILL mid-ring
replays the unfinished jobs from the journal exactly as the classic lanes
do (test-pinned, tests/test_megabatch.py).
"""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp

from gol_tpu import engine
from gol_tpu.obs import (
    recorder as obs_recorder,
    registry as obs_registry,
    trace as obs_trace,
)
from gol_tpu.serve import batcher
from gol_tpu.serve.batcher import BucketKey, StagedServeBatch
from gol_tpu.serve.jobs import Job, JobResult

STATE_PROVIDER = "resident_rings"


class RingTicket:
    """One staged batch's claim on a ring slot (the lane's flight handle)."""

    __slots__ = ("key", "jobs", "staged", "lane", "drain", "slot")

    def __init__(self, sstaged: StagedServeBatch, lane: "ResidentLane"):
        self.key = sstaged.key
        self.jobs = sstaged.jobs
        self.staged = sstaged.staged  # engine.StagedBatch (retained host side)
        self.lane = lane
        self.drain: _Drain | None = None  # set when the slot's drain dispatches
        self.slot = -1


class _Drain:
    """One dispatched ring program; resolved (readback) exactly once."""

    def __init__(self, lane: "ResidentLane", tickets: list[RingTicket],
                 inflight: engine.InflightRing):
        self._lane = lane
        self._tickets = tickets
        self._inflight = inflight
        self._lock = threading.Lock()
        self._results = None
        self._error: Exception | None = None

    def resolve(self, slot: int):
        """Per-slot results; the first caller blocks on the device readback
        (under the drain's own lock), later callers get the cached lists."""
        with self._lock:
            if self._results is None and self._error is None:
                try:
                    with obs_trace.span(
                        "serve.resident_loop",
                        bucket=self._lane.key.label(),
                        filled=len(self._tickets), ring=self._lane.ring,
                    ):
                        self._results = engine.complete_ring(self._inflight)
                except Exception as err:  # noqa: BLE001 - carried per ticket
                    self._error = err
                finally:
                    self._lane._drain_finished()
            if self._error is not None:
                # Every ticket of a failed drain surfaces the same error; the
                # scheduler's retry policy classifies it per batch and
                # re-dispatches from that batch's retained staging.
                raise self._error
            return self._results[slot]


class ResidentLane:
    """One bucket's ring: staged slots, at most one open (undispatched) set."""

    def __init__(self, key: BucketKey, ring: int, clock=time.perf_counter):
        self.key = key
        self.ring = ring
        self._clock = clock
        self._cv = threading.Condition()
        self._open: list[RingTicket] = []
        self._device_slots: list = []
        self._open_rung: int | None = None
        self._unresolved = 0  # dispatched drains not yet read back
        self._last_drain_end: float | None = None
        self.drains_total = 0

    def submit(self, sstaged: StagedServeBatch) -> RingTicket:
        """Stage a batch into the open ring.

        The drain policy is self-clocking (the iwrite half of the
        discipline): with no drain in flight the slot dispatches
        immediately — an idle device must never wait for a fuller ring —
        while a busy device lets slots accumulate until the ring fills or
        the in-flight drain resolves (``_drain_finished``), whichever comes
        first. Under light load this degenerates to per-batch dispatch;
        under backlog drains approach ring size on their own."""
        ticket = RingTicket(sstaged, self)
        eng = sstaged.staged
        with self._cv:
            if self._open and self._open_rung != eng.total:
                # A different batch-size rung cannot share the compiled
                # program — flush the open slots ahead of it.
                self._flush_locked()
            ticket.slot = len(self._open)
            self._open.append(ticket)
            self._open_rung = eng.total
            # Refill the slot on device NOW: jax's async transfer runs while
            # the previous drain's program computes.
            self._device_slots.append(jnp.asarray(eng.operand))
            if len(self._open) >= self.ring or self._unresolved == 0:
                self._flush_locked()
        return ticket

    def complete(self, ticket: RingTicket) -> list[engine.BatchBoardResult]:
        """Block on the ticket's slot results (the deferred Wait)."""
        with self._cv:
            if ticket.drain is None:
                # Safety net: with the eager policy this only happens when
                # the ticket's slots were staged behind a still-unresolved
                # drain and that drain's resolution will come from THIS
                # call chain — dispatch now rather than deadlock.
                self._flush_locked()
        assert ticket.drain is not None
        return ticket.drain.resolve(ticket.slot)

    def _flush_locked(self) -> None:
        if not self._open:
            return
        tickets, self._open = self._open, []
        slots, self._device_slots = self._device_slots, []
        self._open_rung = None
        # Compile-for-filled: a drain of k < R slots runs the k-slot program
        # (one compiled program per filled count, at most `ring` of them per
        # bucket rung) instead of an R-slot program dragging R-k inert
        # zero-board slots through dispatch — measured ~40% overhead on
        # 1-filled drains of a 4-ring.
        staged_ring = engine.stage_ring([t.staged for t in tickets],
                                        len(tickets))
        reg = obs_registry.default()
        now = self._clock()
        if self._last_drain_end is None or self._unresolved > 0:
            # Another drain is (or was just) occupying the device stream —
            # this dispatch queues behind it, so the device sees no gap.
            gap = 0.0
        else:
            gap = max(0.0, now - self._last_drain_end)
        reg.observe("dispatch_gap_seconds", gap)
        reg.set_gauge("ring_slot_occupancy", len(tickets) / self.ring)
        inflight = engine.dispatch_ring(staged_ring, device_slots=slots)
        drain = _Drain(self, tickets, inflight)
        self._unresolved += 1
        self.drains_total += 1
        for t in tickets:
            t.drain = drain

    def _drain_finished(self) -> None:
        with self._cv:
            self._unresolved -= 1
            self._last_drain_end = self._clock()
            # The wait-at-next-boundary moment: the device just went (or is
            # about to go) idle — dispatch the slots that accumulated while
            # the drain ran BEFORE the completer journals its results, so
            # the next drain computes under the journal fsyncs.
            if self._open:
                self._flush_locked()

    def state(self) -> dict:
        with self._cv:
            return {
                "open": len(self._open),
                "ring": self.ring,
                "unresolved_drains": self._unresolved,
                "drains_total": self.drains_total,
            }


class ResidentEngine:
    """The (stage, dispatch, complete) split the pipelined scheduler mounts
    when ``resident_ring > 1`` — same contract as the per-batch batcher
    split, with ``dispatch`` feeding a per-bucket ring instead of posting
    one device program per batch."""

    def __init__(self, ring: int, clock=time.perf_counter):
        if ring < 2:
            raise ValueError(f"resident ring must be >= 2, got {ring}")
        self.ring = ring
        self._clock = clock
        self._lock = threading.Lock()
        self._lanes: dict[BucketKey, ResidentLane] = {}
        self.reopen()

    # -- the split ---------------------------------------------------------

    def stage(self, key: BucketKey, jobs: list[Job]) -> StagedServeBatch:
        return batcher.stage(key, jobs)

    def dispatch(self, sstaged: StagedServeBatch):
        # Sparse buckets have no ring lane (their tile batching lives in
        # the sparse engine): they take the plain batcher split, so a
        # resident server serves sparse jobs through the same scheduler.
        if sstaged.key.kernel == batcher.SPARSE_KERNEL:
            return batcher.dispatch(sstaged)
        return self._lane(sstaged.key).submit(sstaged)

    def complete(self, ticket) -> list[JobResult]:
        if not isinstance(ticket, RingTicket):
            return batcher.complete(ticket)
        results = ticket.lane.complete(ticket)
        return [
            JobResult(grid=r.grid, generations=r.generations,
                      exit_reason=r.exit_reason)
            for r in results
        ]

    def split(self):
        return (self.stage, self.dispatch, self.complete)

    # -- lifecycle / introspection ----------------------------------------

    def _lane(self, key: BucketKey) -> ResidentLane:
        with self._lock:
            lane = self._lanes.get(key)
            if lane is None:
                lane = self._lanes[key] = ResidentLane(
                    key, self.ring, self._clock
                )
            return lane

    def state(self) -> dict:
        """Flat per-lane snapshot (the flight-recorder state provider)."""
        with self._lock:
            lanes = list(self._lanes.values())
        out = {}
        for lane in lanes:
            for k, v in lane.state().items():
                out[f"{lane.key.label()}.{k}"] = v
        return out

    def reopen(self) -> None:
        """(Re-)register the flight-recorder state provider."""
        obs_recorder.add_state_provider(STATE_PROVIDER, self.state)

    def close(self) -> None:
        """Drop the state provider and forget the lanes (ring hygiene: no
        threads to join — all lane work runs on the scheduler's own
        dispatcher/completer threads)."""
        obs_recorder.remove_state_provider(STATE_PROVIDER)
        with self._lock:
            self._lanes.clear()
