"""Sparse tiled board: a giant torus stored as its live 256^2 tiles only.

The board is decomposed into fixed ``tile``-square tiles on a tile-grid
torus (universe extents must divide evenly into tiles). Only tiles holding
at least one live cell exist — the tile dict IS the live-occupancy index —
so a 2^16-square universe carrying five gliders costs a handful of 64 KB
tiles, not a 4 GB canvas. The dense analog of this invariant is the
reference's ``empty_all`` early exit: where the reference can skip the
whole board only when EVERYTHING is dead, per-tile elision skips every
dead tile every generation (COMPONENTS.md sparse-engine lineage).

Numpy-only on purpose (no jax import): boards are built by the CLI and the
serve admission path before any engine loads, straight from RLE token
streams (io/rle.py) — geometry-first, the full byte canvas never exists.
"""

from __future__ import annotations

import numpy as np

from gol_tpu.io import rle

# The production tile edge. 256^2 tiles are large enough that a batched
# tile-step amortizes dispatch (each tile is a 64 KB board — serving-batch
# scale) and small enough that a lone glider wakes at most 4 of them.
# gol_tpu/tune/space.py names the candidate axis (SPARSE_TILES) around
# this default; tests use small tiles to exercise boundary crossings
# cheaply (the math is tile-size-independent).
DEFAULT_TILE = 256
MIN_TILE = 4

# Dense-materialization ceiling (cells): boards above this must never be
# built as a byte canvas on the host — the guard every dense construction
# path checks BEFORE allocating (cli board construction, to_dense). 2^30
# cells is a 1 GB uint8 canvas; the dense engine carries two of them plus
# XLA workspace, the practical single-host ceiling this tree has measured.
MAX_DENSE_CELLS = 1 << 30


def dense_cells_guard(height: int, width: int, *, what: str = "board",
                      limit: int = MAX_DENSE_CELLS) -> None:
    """Raise the CLI-contract error for a dense allocation that cannot fit.

    Centralized so every dense lane fails the same way — a clear
    ``gol: <error>`` line naming the sparse lane — instead of an OOM
    traceback from inside ``np.zeros``."""
    cells = height * width
    if cells > limit:
        raise ValueError(
            f"a {height}x{width} {what} is {cells} cells "
            f"({cells / (1 << 30):.1f} GB as bytes), above the dense "
            f"engine's {limit}-cell ceiling; use the sparse lane "
            "(--pattern FILE --universe WxH [--engine sparse]) so the "
            "canvas is never materialized"
        )


class SparseBoard:
    """A ``height x width`` torus holding only its live tiles.

    ``tiles`` maps ``(ty, tx)`` tile-grid coordinates to ``(tile, tile)``
    uint8 arrays; the class invariant is that every stored tile has at
    least one live cell (all-dead tiles are elided, never stored)."""

    def __init__(self, height: int, width: int, tile: int = DEFAULT_TILE,
                 tiles: dict | None = None):
        if tile < MIN_TILE:
            raise ValueError(f"tile must be >= {MIN_TILE}, got {tile}")
        if height <= 0 or width <= 0:
            raise ValueError(
                f"universe extents must be positive, got {height}x{width}"
            )
        if height % tile or width % tile:
            raise ValueError(
                f"universe {height}x{width} does not divide into {tile}^2 "
                f"tiles; extents must be multiples of the tile size"
            )
        self.height = height
        self.width = width
        self.tile = tile
        self.tiles_y = height // tile
        self.tiles_x = width // tile
        self.tiles: dict[tuple[int, int], np.ndarray] = {}
        for coord, arr in (tiles or {}).items():
            self.set_tile(coord, arr)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_dense(cls, grid: np.ndarray, tile: int = DEFAULT_TILE
                   ) -> "SparseBoard":
        grid = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
        if grid.ndim != 2:
            raise ValueError(f"grid must be 2D, got shape {grid.shape}")
        board = cls(grid.shape[0], grid.shape[1], tile)
        t = tile
        for ty in range(board.tiles_y):
            for tx in range(board.tiles_x):
                block = grid[ty * t:(ty + 1) * t, tx * t:(tx + 1) * t]
                if block.any():
                    board.tiles[(ty, tx)] = np.ascontiguousarray(block)
        return board

    @classmethod
    def from_pattern(cls, pattern: np.ndarray, x: int, y: int,
                     height: int, width: int, tile: int = DEFAULT_TILE
                     ) -> "SparseBoard":
        """Place a dense pattern with its top-left cell at column ``x``,
        row ``y`` of an otherwise-empty universe (geometry-first: only the
        tiles the pattern touches are ever allocated)."""
        board = cls(height, width, tile)
        board.place(pattern, x, y)
        return board

    @classmethod
    def from_rle(cls, text: str, height: int | None = None,
                 width: int | None = None, tile: int = DEFAULT_TILE,
                 x: int = 0, y: int = 0, owned=None) -> "SparseBoard":
        """Build a board from an RLE document via the streaming run path —
        no dense canvas at any size. With ``height``/``width`` absent the
        RLE header's extents ARE the universe.

        ``owned`` is an optional ``(ty, tx) -> bool`` tile filter: runs are
        split across the tiles they span and only owned tiles materialize
        — the shard-worker loading path (gol_tpu/shard), where a worker
        owning one slice of a 2^20-square document must cost O(its runs)
        in memory, never the whole document's tiles. ``None`` (every other
        caller) loads everything, byte-identically to before."""
        (pw, ph), runs = rle.live_runs(text)
        if height is None or width is None:
            height, width = ph, pw
        board = cls(height, width, tile)
        # live_runs bounds content against the RLE header's own extents;
        # the placement of THOSE extents must fit this universe, or
        # _set_run would write phantom tiles outside the tile grid.
        if x < 0 or y < 0 or y + ph > height or x + pw > width:
            raise ValueError(
                f"RLE content {ph}x{pw} at ({x},{y}) does not fit the "
                f"{height}x{width} universe"
            )
        for row, col, count in runs:
            board._set_run(y + row, x + col, count, owned)
        return board

    def place(self, pattern: np.ndarray, x: int, y: int) -> None:
        """Stamp (OR) a dense pattern at column ``x``, row ``y``; the stamp
        may span any number of tile boundaries but not the universe edge."""
        pattern = np.asarray(pattern, dtype=np.uint8)
        if pattern.ndim != 2:
            raise ValueError(f"pattern must be 2D, got shape {pattern.shape}")
        ph, pw = pattern.shape
        if x < 0 or y < 0 or y + ph > self.height or x + pw > self.width:
            raise ValueError(
                f"pattern {ph}x{pw} at ({x},{y}) does not fit the "
                f"{self.height}x{self.width} universe"
            )
        for r in range(ph):
            row = pattern[r]
            for start, end in rle._row_runs(row):
                self._set_run(y + r, x + start, end - start)

    def _set_run(self, row: int, col: int, count: int, owned=None) -> None:
        """Set ``count`` cells live starting at (row, col), splitting the
        run across the tiles it spans. ``owned`` filters which tiles may
        materialize (tile-by-tile: an unowned slice of the run is skipped
        without ever allocating its tile)."""
        t = self.tile
        ty, ly = divmod(row, t)
        while count > 0:
            tx, lx = divmod(col, t)
            take = min(count, t - lx)
            if owned is None or owned((ty, tx)):
                arr = self.tiles.get((ty, tx))
                if arr is None:
                    arr = self.tiles[(ty, tx)] = np.zeros((t, t), np.uint8)
                arr[ly, lx:lx + take] = 1
            col += take
            count -= take

    def set_tile(self, coord: tuple[int, int], arr: np.ndarray) -> None:
        """Install one tile (elided when all-dead — the class invariant)."""
        ty, tx = coord
        if not (0 <= ty < self.tiles_y and 0 <= tx < self.tiles_x):
            raise ValueError(
                f"tile {coord} outside the {self.tiles_y}x{self.tiles_x} grid"
            )
        arr = np.ascontiguousarray(np.asarray(arr, dtype=np.uint8))
        if arr.shape != (self.tile, self.tile):
            raise ValueError(
                f"tile {coord} has shape {arr.shape}; need "
                f"({self.tile}, {self.tile})"
            )
        if arr.any():
            self.tiles[coord] = arr
        else:
            self.tiles.pop(coord, None)

    # -- views -------------------------------------------------------------

    @property
    def live_tiles(self) -> int:
        return len(self.tiles)

    def occupancy(self) -> float:
        """Live tiles over total tiles — the sparsity the engine exploits."""
        return len(self.tiles) / (self.tiles_y * self.tiles_x)

    def population(self) -> int:
        return int(sum(int(a.sum()) for a in self.tiles.values()))

    def to_dense(self, limit: int = MAX_DENSE_CELLS) -> np.ndarray:
        """Materialize the full canvas (guarded — giant boards refuse)."""
        dense_cells_guard(self.height, self.width, what="dense view",
                          limit=limit)
        grid = np.zeros((self.height, self.width), np.uint8)
        t = self.tile
        for (ty, tx), arr in self.tiles.items():
            grid[ty * t:(ty + 1) * t, tx * t:(tx + 1) * t] = arr
        return grid

    def to_rle(self, comments: tuple[str, ...] = ()) -> str:
        """The whole universe as one RLE document — O(live runs), rendered
        through the same emitter as the dense codec (io/rle.encode_rows)."""
        t = self.tile

        def rows():
            by_row: dict[int, list[tuple[int, np.ndarray]]] = {}
            for (ty, tx), arr in self.tiles.items():
                by_row.setdefault(ty, []).append((tx, arr))
            for ty in sorted(by_row):
                strip = sorted(by_row[ty])
                for ly in range(t):
                    runs: list[tuple[int, int]] = []
                    for tx, arr in strip:
                        base = tx * t
                        for start, end in rle._row_runs(arr[ly]):
                            if runs and runs[-1][1] == base + start:
                                runs[-1] = (runs[-1][0], base + end)
                            else:
                                runs.append((base + start, base + end))
                    if runs:
                        yield ty * t + ly, runs

        return rle.encode_rows(rows(), self.width, self.height, comments)

    def __eq__(self, other) -> bool:
        if not isinstance(other, SparseBoard):
            return NotImplemented
        return (
            self.height == other.height
            and self.width == other.width
            and self.tile == other.tile
            and self.tiles.keys() == other.tiles.keys()
            and all(
                np.array_equal(a, other.tiles[c])
                for c, a in self.tiles.items()
            )
        )

    def __repr__(self) -> str:
        return (
            f"SparseBoard({self.height}x{self.width}, tile={self.tile}, "
            f"live_tiles={self.live_tiles}, population={self.population()})"
        )
