"""Tile-result memoization: the PR-9 CAS machinery keyed at tile granularity.

A tile step is a pure function of its halo-extended block — convention,
generation limit, and similarity settings never reach it (they live in the
sparse host loop) — so its result is memoizable under a content key alone.
The key reuses the result cache's collision-hardened digest
(``cache/fingerprint.board_digest``: the checkpoint identity's positional
limb math + a CRC fold) over the ``(tile+2)^2`` block, scoped by a schema
tag and the tile size; the store reuses the PR-9 tiers verbatim —
``cache.store.MemoryLRU`` and, when a directory is given, the CRC-verified
``DiskCAS`` (text payload: tiles are not always word-packable widths).

What this buys: repeated tile content — still-life blocks, repeated
pattern stamps, any two tiles anywhere on the board (or in any two jobs on
the same server) whose block bytes match — costs one digest + one dict
hit instead of a kernel dispatch. The flags ride the entry's
``generations`` field as a bit pack, so the CAS CRC gate covers them the
same way it covers the cells.
"""

from __future__ import annotations

import dataclasses
import logging

import numpy as np

from gol_tpu.cache.fingerprint import board_digest
from gol_tpu.cache.store import CacheEntry, DiskCAS, MemoryLRU
from gol_tpu.obs import registry as obs_registry

logger = logging.getLogger(__name__)

SCHEMA_VERSION = 1

# Flag bits packed into CacheEntry.generations (covered by the CAS CRC).
_ALIVE = 1
_CHANGED = 2

_EXIT_TAG = "tile"  # exit_reason marker: this entry is a tile step, not a job


@dataclasses.dataclass
class TileStep:
    """One memoized tile-step outcome."""

    interior: np.ndarray  # (tile, tile) uint8 — the next interior
    alive: bool
    changed: bool


# The memory tier's grid-byte budget: 8192 entries of 256^2-tile interiors
# would be half a GB resident, so the entry count alone is not a memory
# bound — the byte cap is what actually limits a worker's footprint under
# sustained varied sparse traffic (128 MiB holds ~2048 production tiles).
DEFAULT_MEMO_BYTES = 128 << 20


class TileMemo:
    """Tiered block-digest -> next-interior store (memory LRU over an
    optional on-disk CAS). Misses/hits feed the process obs registry
    (``sparse_memo_hits_total`` / ``sparse_memo_misses_total``)."""

    def __init__(self, entries: int = 8192, cas_dir: str | None = None,
                 max_bytes: int = DEFAULT_MEMO_BYTES):
        self.memory = MemoryLRU(entries, max_bytes=max_bytes)
        self.cas = (
            DiskCAS(cas_dir, payload="text", on_evict=self._on_evict)
            if cas_dir else None
        )

    @staticmethod
    def key(block: np.ndarray, tile: int) -> str:
        """The tile-step fingerprint of one halo-extended block."""
        return f"t{SCHEMA_VERSION}-{board_digest(block)}-{tile}"

    def _on_evict(self, fp: str, reason: str) -> None:
        obs_registry.default().inc("sparse_memo_corrupt_evictions_total")

    def get(self, key: str) -> TileStep | None:
        reg = obs_registry.default()
        entry = self.memory.get(key)
        if entry is None and self.cas is not None:
            try:
                entry = self.cas.get(key)
            except OSError as err:
                logger.warning("tile memo CAS read failed for %s: %s: %s",
                               key, type(err).__name__, err)
                entry = None
            if entry is not None:
                self.memory.put(key, entry)
        if entry is None:
            reg.inc("sparse_memo_misses_total")
            return None
        reg.inc("sparse_memo_hits_total")
        flags = int(entry.generations)
        return TileStep(
            interior=entry.grid,
            alive=bool(flags & _ALIVE),
            changed=bool(flags & _CHANGED),
        )

    def put(self, key: str, step: TileStep) -> None:
        flags = (_ALIVE if step.alive else 0) | (_CHANGED if step.changed else 0)
        entry = CacheEntry(
            grid=np.ascontiguousarray(step.interior, dtype=np.uint8),
            generations=flags,
            exit_reason=_EXIT_TAG,
        )
        self.memory.put(key, entry)
        if self.cas is not None:
            try:
                self.cas.put(key, entry)
            except OSError as err:
                logger.warning(
                    "tile memo CAS write failed for %s (memo still serves "
                    "from memory): %s: %s", key, type(err).__name__, err,
                )
