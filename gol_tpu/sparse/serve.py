"""The sparse job lane of the serving stack.

Sparse jobs arrive through the same ``POST /jobs`` contract as dense ones
(``rle`` + universe extents instead of ``cells``), land in a dedicated
bucket (``batcher.SPARSE_KERNEL``), and ride every scheduler lane —
classic worker, pipelined dispatcher/completer, resident servers —
through the same stage/dispatch/complete split the batcher exposes. The
difference is WHERE the batching happens: a dense bucket batches boards
into one compiled program; a sparse job batches its own active TILES
through the bucket ladder inside ``sparse.engine``, so the split here is
thin — stage validates membership, dispatch is a pass-through (the sparse
loop needs the host, there is nothing to launch asynchronously), and
complete runs the simulations (idempotent, so the scheduler's retry
policy applies unchanged).

Tile memoization is process-global on purpose: every sparse job on a
worker shares one ``TileMemo``, so repeated tile content ACROSS jobs
(the same pattern resubmitted, common still-life debris) hits without any
job-level fingerprint — the sparse counterpart of the PR-9 result cache,
which sparse jobs deliberately do not enter (their answer is the memo'd
tile work itself; ``scheduler.submit`` skips the consult for them).
"""

from __future__ import annotations

import logging

from gol_tpu.obs import trace as obs_trace
from gol_tpu.sparse.board import SparseBoard
from gol_tpu.sparse.engine import simulate_sparse
from gol_tpu.sparse.memo import TileMemo

logger = logging.getLogger(__name__)

_MEMO: TileMemo | None = None
_MEMO_ENTRIES = 8192


def memo() -> TileMemo:
    """The worker-wide tile memo (built on first sparse dispatch)."""
    global _MEMO
    if _MEMO is None:
        _MEMO = TileMemo(entries=_MEMO_ENTRIES)
    return _MEMO


def configure(entries: int | None = None, cas_dir: str | None = None) -> None:
    """Rebuild the worker-wide memo (tests, and servers mounting a CAS
    tier beside their journal partition)."""
    global _MEMO
    _MEMO = TileMemo(entries=entries or _MEMO_ENTRIES, cas_dir=cas_dir)


def board_for(job) -> SparseBoard:
    """A job's initial occupancy index, straight from its journaled spec
    (geometry-first — the dense canvas never exists)."""
    return SparseBoard.from_pattern(
        job.pattern, job.place_x, job.place_y,
        job.height, job.width, job.tile,
    )


def run_batch(key, jobs) -> list:
    """Run a sparse bucket's claimed jobs, in order (the sparse analog of
    ``batcher.run_batch``; per-job tile batching happens inside the sparse
    engine). Pure function of the specs — safe to re-run on retry."""
    from gol_tpu.macro import serve as macro_serve
    from gol_tpu.serve.jobs import JobResult

    out = []
    for job in jobs:
        if getattr(job, "macro", False):
            # Macro jobs share the sparse bucket (same input form, same
            # scheduler lanes); only the engine differs — and its results
            # are byte-identical by contract, just reached in O(log) jumps.
            out.append(macro_serve.run_job(job))
            continue
        with obs_trace.span("sparse.job", job=job.id,
                            universe=f"{job.height}x{job.width}",
                            tile=job.tile):
            result = simulate_sparse(board_for(job), job.config, memo())
        out.append(JobResult(
            grid=None,
            generations=result.generations,
            exit_reason=result.exit_reason,
            rle=result.board.to_rle(),
            population=result.board.population(),
            universe=(job.height, job.width),
            tiles_simulated=result.stats.tiles_active,
            cell_updates=result.stats.cell_updates(job.tile),
            occupancy=result.board.occupancy(),
        ))
    return out


def stage(key, jobs):
    """Membership-validated no-op staging (there is no host stacking to
    overlap — tile staging happens per generation inside the engine)."""
    from gol_tpu.serve import batcher

    if not jobs:
        raise ValueError("cannot stage an empty batch")
    for job in jobs:
        jk = batcher.bucket_for(job)
        if jk != key:
            raise ValueError(
                f"job {job.id} belongs to bucket {jk.label()}, "
                f"not {key.label()}"
            )
    return batcher.StagedServeBatch(key=key, jobs=list(jobs), staged=None)


def dispatch(staged):
    """Pass-through: the sparse loop is host-driven, so the work runs at
    complete() on the completer/worker thread (retries re-run it whole)."""
    from gol_tpu.serve import batcher

    return batcher.InflightServeBatch(
        key=staged.key, jobs=staged.jobs, inflight=None
    )


def complete(inflight) -> list:
    return run_batch(inflight.key, inflight.jobs)
