"""The sparse tiled engine: O(live-area) simulation of giant universes.

Every dense lane (solo, batched, resident, packed-wire) costs
O(width x height) per generation no matter how dead the board is; this
engine costs O(active tiles). Per generation:

1. **Activation** — the active set is every live tile plus, for each live
   tile whose outermost ring holds a live cell, its 8 tile-grid neighbors
   (torus wrap at the universe edge). A dead tile outside this set cannot
   gain a live cell (all of its halo is dead), so it is elided entirely —
   the per-tile generalization of the reference's whole-board
   ``empty_all`` early exit.
2. **Halo assembly** — each active tile becomes a ``(tile+2)^2`` block:
   interior from the occupancy index, halo ring gathered from the 8
   neighbors (the per-step halo exchange of the distributed lanes, at
   tile granularity, on the host).
3. **Memo consult** — the block's content digest is looked up in the tile
   memo (gol_tpu/sparse/memo.py — the PR-9 CAS keyed at tile
   granularity); hits skip the kernel entirely.
4. **Batched step** — misses are batched through the serve batcher's
   padding ladder (``batcher.pad_batch`` — tiles ARE a bucket, so a tile
   size compiles at most one program per ladder rung) into
   ``engine.make_tile_step_runner``, one generation per dispatch.
5. **Rebuild** — tiles whose next interior is all-dead are dropped from
   the index; the per-tile ``changed`` flags fold into the global
   similarity answer (the universe is unchanged iff no active tile
   changed — inactive tiles are unchanged by construction).

The loop accounting around those steps reproduces both reference
conventions exactly (gol_tpu/oracle.py is the semantics contract), so the
sparse lane is byte-identical to the dense engine — cells, generation
count, and exit reason — on every shape both accept (test-pinned).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from gol_tpu.config import Convention, DEFAULT_CONFIG, GameConfig
from gol_tpu.obs import registry as obs_registry, trace as obs_trace
from gol_tpu.sparse.board import SparseBoard
from gol_tpu.sparse.memo import TileMemo, TileStep

# Above this universe area the CLI's auto lane prefers the sparse engine.
# The shipped default is the MEASURED dense/sparse crossover, not a guess:
# BENCH_r14 has dense still winning at 4096^2 = 2^24 (ratio 0.81) and
# losing 4.7x at 8192^2 = 2^26 — dense cost grows linearly with area while
# sparse stays flat at the live tiles, so the crossover sits near the
# geometric middle, 2^25. The value is plan-cached per machine:
# ``gol tune --sparse-crossover`` measures THIS host's crossover and
# persists it (tune.select.sparse_auto_area consults it; this constant is
# the bundled-default/last-resort fallback, kept equal to
# default_plans.json's entry).
SPARSE_AUTO_AREA = 1 << 25

EXIT_GEN_LIMIT = "gen_limit"
EXIT_EMPTY = "empty"
EXIT_SIMILAR = "similar"


@dataclasses.dataclass
class SparseStats:
    """Work accounting of one sparse run (feeds the obs registry and the
    serve metrics: the sparse lane's achieved work is tiles, not canvas)."""

    generations: int = 0
    tiles_active: int = 0  # active-tile steps, summed over generations
    tiles_computed: int = 0  # kernel-dispatched steps (memo misses)
    memo_hits: int = 0

    def cell_updates(self, tile: int) -> int:
        """Actual cells stepped: active tiles x tile area (the number the
        dense engine would report as height x width x generations)."""
        return self.tiles_active * tile * tile

    def tiles_per_generation(self) -> float:
        return self.tiles_active / self.generations if self.generations else 0.0


@dataclasses.dataclass
class SparseResult:
    """Final state of a sparse run (the EngineResult analog)."""

    board: SparseBoard
    generations: int
    exit_reason: str
    stats: SparseStats


def auto_engine(height: int, width: int, tile: int,
                area_threshold: int | None = None) -> str:
    """The auto lane's dense/sparse pick for a universe: sparse above the
    area threshold when the extents tile evenly, dense otherwise.

    The threshold is the tuned/plan-cached crossover when one exists
    (``gol tune --sparse-crossover`` measures it; absent or unreadable
    cache degrades to the bundled default — the usual plan-cache
    contract), or ``area_threshold`` when the caller pins one."""
    if area_threshold is None:
        try:
            from gol_tpu.tune import select

            area_threshold = select.sparse_auto_area(SPARSE_AUTO_AREA)
        except Exception:  # noqa: BLE001 - cache trouble = default
            area_threshold = SPARSE_AUTO_AREA
    if height * width >= area_threshold and height % tile == 0 \
            and width % tile == 0:
        return "sparse"
    return "dense"


def ring_live(arr: np.ndarray) -> bool:
    """True when a tile's outermost ring holds a live cell — the condition
    under which its neighbors activate (and, in the shard lanes, the
    condition under which its ring must cross the wire)."""
    return bool(arr[0].any() or arr[-1].any()
                or arr[:, 0].any() or arr[:, -1].any())


def _ghost_live(ring) -> bool:
    """Ring-liveness of a ghost entry (see ``step_tiles`` for the ghost
    protocol). An all-dead ghost ring activates nothing — exactly like an
    absent tile, which it is indistinguishable from."""
    return bool(ring.top.any() or ring.bottom.any()
                or ring.left.any() or ring.right.any())


def _active_set(board: SparseBoard, ghost=None,
                owned=None) -> set[tuple[int, int]]:
    """Live tiles plus halo-activated neighbors of ring-live tiles.

    ``ghost`` extends ring-liveness to remote tiles (their neighbors
    activate here too); ``owned`` filters the result to this worker's
    ownership slice — a tile another worker owns is stepped there, never
    here. Both default to None: the solo path is byte-identical."""
    active = set(board.tiles)
    ty_n, tx_n = board.tiles_y, board.tiles_x
    seeds = [coord for coord, arr in board.tiles.items() if ring_live(arr)]
    if ghost:
        seeds.extend(c for c, ring in ghost.items() if _ghost_live(ring))
    for ty, tx in seeds:
        for dy in (-1, 0, 1):
            for dx in (-1, 0, 1):
                if dy or dx:
                    active.add(((ty + dy) % ty_n, (tx + dx) % tx_n))
    if owned is not None:
        active = {coord for coord in active if owned(coord)}
    return active


def _assemble_block(board: SparseBoard, coord: tuple[int, int],
                    ghost=None) -> np.ndarray:
    """One tile's ``(tile+2)^2`` halo-extended block, ring gathered from
    its 8 torus neighbors (self-wrap on 1-tile-wide grids is the universe
    torus, so a single-tile universe assembles its own torus halo).

    A neighbor absent from the board may be present in ``ghost`` — a
    remote tile's ring, received over the halo wire. Only the ring cells
    a block ever reads exist there: edge rows/cols and corners."""
    t = board.tile
    ty, tx = coord
    ty_n, tx_n = board.tiles_y, board.tiles_x
    tiles = board.tiles
    ghost = ghost or {}
    up, down = (ty - 1) % ty_n, (ty + 1) % ty_n
    left, right = (tx - 1) % tx_n, (tx + 1) % tx_n
    block = np.zeros((t + 2, t + 2), np.uint8)
    center = tiles.get(coord)
    if center is not None:
        block[1:-1, 1:-1] = center
    n = tiles.get((up, tx))
    if n is not None:
        block[0, 1:-1] = n[-1]
    elif (g := ghost.get((up, tx))) is not None:
        block[0, 1:-1] = g.bottom
    s = tiles.get((down, tx))
    if s is not None:
        block[-1, 1:-1] = s[0]
    elif (g := ghost.get((down, tx))) is not None:
        block[-1, 1:-1] = g.top
    w = tiles.get((ty, left))
    if w is not None:
        block[1:-1, 0] = w[:, -1]
    elif (g := ghost.get((ty, left))) is not None:
        block[1:-1, 0] = g.right
    e = tiles.get((ty, right))
    if e is not None:
        block[1:-1, -1] = e[:, 0]
    elif (g := ghost.get((ty, right))) is not None:
        block[1:-1, -1] = g.left
    nw = tiles.get((up, left))
    if nw is not None:
        block[0, 0] = nw[-1, -1]
    elif (g := ghost.get((up, left))) is not None:
        block[0, 0] = g.bottom[-1]
    ne = tiles.get((up, right))
    if ne is not None:
        block[0, -1] = ne[-1, 0]
    elif (g := ghost.get((up, right))) is not None:
        block[0, -1] = g.bottom[0]
    sw = tiles.get((down, left))
    if sw is not None:
        block[-1, 0] = sw[0, -1]
    elif (g := ghost.get((down, left))) is not None:
        block[-1, 0] = g.top[-1]
    se = tiles.get((down, right))
    if se is not None:
        block[-1, -1] = se[0, 0]
    elif (g := ghost.get((down, right))) is not None:
        block[-1, -1] = g.top[0]
    return block


def _step(board: SparseBoard, memo: TileMemo | None, stats: SparseStats,
          ghost=None, owned=None) -> tuple[SparseBoard, bool]:
    """One global generation: ``(next_board, changed_any)``."""
    import jax
    import jax.numpy as jnp

    from gol_tpu import engine
    from gol_tpu.serve import batcher

    t = board.tile
    active = sorted(_active_set(board, ghost, owned))
    stats.tiles_active += len(active)
    results: dict[tuple[int, int], TileStep] = {}
    # Each miss is (key, block, [coords]): with a memo, identical blocks
    # WITHIN one generation dedupe onto one kernel slot too (two stamps
    # of the same pattern cost one stamp's dispatches even on their first
    # generation — the repeated-content claim at its strongest).
    misses: list[list] = []
    pending: dict[str, list] = {}
    for coord in active:
        block = _assemble_block(board, coord, ghost)
        if memo is not None:
            key = TileMemo.key(block, t)
            hit = memo.get(key)
            if hit is not None:
                results[coord] = hit
                stats.memo_hits += 1
                continue
            dup = pending.get(key)
            if dup is not None:
                dup[2].append(coord)
                stats.memo_hits += 1
                continue
            entry = [key, block, [coord]]
            pending[key] = entry
            misses.append(entry)
        else:
            misses.append([None, block, [coord]])
    # Batched through the padding-bucket ladder: request counts round up
    # the serve batcher's rungs (a tuned ladder applies here too), so one
    # tile size compiles at most one program per rung for the process's
    # life — the per-bucket compiled-program invariant, with the operand
    # donated exactly as every batch lane donates its canvas.
    for lo in range(0, len(misses), batcher.MAX_BATCH):
        chunk = misses[lo:lo + batcher.MAX_BATCH]
        rung = batcher.pad_batch(len(chunk))
        runner = engine.make_tile_step_runner(t, rung)
        operand = np.zeros((rung, t + 2, t + 2), np.uint8)
        for i, (_, block, _) in enumerate(chunk):
            operand[i] = block
        interiors, alive, changed = runner(jnp.asarray(operand))
        interiors = np.asarray(jax.device_get(interiors), dtype=np.uint8)
        alive = np.asarray(jax.device_get(alive))
        changed = np.asarray(jax.device_get(changed))
        stats.tiles_computed += len(chunk)
        for i, (key, _, coords) in enumerate(chunk):
            step = TileStep(
                interior=interiors[i].copy(),
                alive=bool(alive[i]),
                changed=bool(changed[i]),
            )
            for coord in coords:
                results[coord] = step
            if memo is not None and key is not None:
                memo.put(key, step)
    new_board = SparseBoard(board.height, board.width, t)
    changed_any = False
    for coord, step in results.items():
        changed_any = changed_any or step.changed
        if step.alive:
            # Invariant holds by the flag: only live interiors are stored.
            new_board.tiles[coord] = step.interior
    return new_board, changed_any


def step_tiles(board: SparseBoard, memo: TileMemo | None, stats: SparseStats,
               *, ghost=None, owned=None) -> tuple[SparseBoard, bool]:
    """One super-step over an ownership slice: ``(next_board, changed)``.

    The shard worker's entry point (gol_tpu/shard/worker.py). ``board``
    holds only the tiles this worker owns; ``ghost`` maps remote neighbor
    coords to ring views — objects with ``top``/``bottom``/``left``/
    ``right`` length-``tile`` uint8 arrays (gol_tpu/shard/halo.Ring),
    received as packed frames from the tiles' owners; ``owned`` is the
    partition's membership predicate. Because a tile's step reads ONLY its
    neighbors' outermost ring, and a tile with an all-dead ring is
    indistinguishable from an absent one (it activates nothing and
    contributes nothing), the union of every worker's ``step_tiles``
    result equals one solo ``_step`` — byte-exactly, the property the
    shard byte-gates pin. With both None this IS the solo step."""
    return _step(board, memo, stats, ghost=ghost, owned=owned)


def _run_c(board, config, memo, stats):
    """C-convention accounting (oracle._run_c, engine._simulate_c)."""
    generation = 1
    counter = 0
    while board.tiles and generation <= config.gen_limit:
        new_board, changed_any = _step(board, memo, stats)
        stats.generations += 1
        if config.check_similarity:
            counter += 1
            if counter == config.similarity_frequency:
                if not changed_any:
                    return SparseResult(new_board, generation - 1,
                                        EXIT_SIMILAR, stats)
                counter = 0
        board = new_board
        generation += 1
    reason = EXIT_GEN_LIMIT if board.tiles else EXIT_EMPTY
    return SparseResult(board, generation - 1, reason, stats)


def _run_cuda(board, config, memo, stats):
    """CUDA-convention accounting (oracle._run_cuda): similarity checked
    before emptiness, the break precedes the swap — an empty exit keeps
    the last non-empty generation."""
    generation = 0
    counter = 0
    reason = EXIT_GEN_LIMIT
    while generation < config.gen_limit:
        new_board, changed_any = _step(board, memo, stats)
        stats.generations += 1
        if config.check_similarity:
            counter += 1
            if counter == config.similarity_frequency:
                if not changed_any:
                    reason = EXIT_SIMILAR
                    break
                counter = 0
        if not new_board.tiles:
            reason = EXIT_EMPTY
            break
        board = new_board
        generation += 1
    return SparseResult(board, generation, reason, stats)


def simulate_sparse(
    board: SparseBoard,
    config: GameConfig = DEFAULT_CONFIG,
    memo: TileMemo | None = None,
) -> SparseResult:
    """Run a full sparse simulation.

    Byte-identical to the dense engine (and the oracle) on any universe
    both accept, for both conventions, including all three exit reasons —
    with or without a ``memo`` (memoization changes dispatch counts,
    never bytes)."""
    reg = obs_registry.default()
    with obs_trace.span("sparse.simulate",
                        shape=f"{board.height}x{board.width}",
                        tile=board.tile, live_tiles=board.live_tiles,
                        convention=config.convention):
        stats = SparseStats()
        run = _run_cuda if config.convention == Convention.CUDA else _run_c
        result = run(board, config, memo, stats)
    reg.inc("sparse_runs_total")
    reg.inc("sparse_generations_total", stats.generations)
    reg.inc("sparse_tiles_simulated_total", stats.tiles_active)
    reg.inc("sparse_tiles_computed_total", stats.tiles_computed)
    reg.set_gauge("sparse_tiles_per_generation", stats.tiles_per_generation())
    reg.set_gauge("sparse_occupancy", result.board.occupancy())
    return result
