"""Sparse tiled engine: O(live-area) simulation for giant universes.

- ``board``  — the tiled occupancy index (numpy-only, geometry-first)
- ``engine`` — the host loop: activation, halo assembly, batched tile steps
- ``memo``   — tile-result memoization on the PR-9 CAS machinery
- ``serve``  — the sparse job lane of the serving stack
"""

from gol_tpu.sparse.board import (  # noqa: F401
    DEFAULT_TILE,
    MAX_DENSE_CELLS,
    SparseBoard,
    dense_cells_guard,
)
from gol_tpu.sparse.engine import (  # noqa: F401
    SPARSE_AUTO_AREA,
    SparseResult,
    SparseStats,
    auto_engine,
    simulate_sparse,
)
from gol_tpu.sparse.memo import TileMemo  # noqa: F401
