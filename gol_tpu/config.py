"""Runtime configuration.

The reference uses a two-tier config system: argv (width, height, input path,
with 30x30 defaults — src/game.c:224-236) plus compile-time #defines requiring
recompilation (GEN_LIMIT=1000, CHECK_SIMILARITY, SIMILARITY_FREQUENCY=3 —
src/game.c:6-9, README.md:65; THREADS=4 src/game_openmp.c:11; BLOCK_SIZE=32
src/game_cuda.cu:4). Here the compile-time tier is promoted to runtime flags
with the same names and defaults.
"""

from __future__ import annotations

import dataclasses

# Reference compile-time constants (src/game.c:6-9).
GEN_LIMIT = 1000
SIMILARITY_FREQUENCY = 3

# Reference argv defaults (src/game.c:233-236).
DEFAULT_WIDTH = 30
DEFAULT_HEIGHT = 30


class Convention:
    """Loop-accounting conventions present in the reference.

    ``C``: generation counter starts at 1; emptiness is checked at the top of
    every generation on the *current* grid (src/game.c:177); the similarity
    early-exit breaks without incrementing the counter; the reported count is
    ``generation - 1`` (src/game.c:202).

    ``CUDA``: counter starts at 0 and the loop bound is exclusive
    (src/game_cuda.cu:213,222); emptiness is checked *after* evolve on the new
    grid and breaks before the buffer swap (src/game_cuda.cu:259-268), so an
    empty-exit reports one generation fewer than C and writes the last
    non-empty generation; the reported count is un-decremented
    (src/game_cuda.cu:294).
    """

    C = "c"
    CUDA = "cuda"


@dataclasses.dataclass(frozen=True)
class GameConfig:
    """Simulation parameters shared by every engine and the oracle."""

    gen_limit: int = GEN_LIMIT
    check_similarity: bool = True  # presence of #define CHECK_SIMILARITY, src/game.c:8
    similarity_frequency: int = SIMILARITY_FREQUENCY
    convention: str = Convention.C

    def __post_init__(self):
        if self.gen_limit < 0:
            raise ValueError(f"gen_limit must be >= 0, got {self.gen_limit}")
        if self.similarity_frequency <= 0:
            raise ValueError(
                f"similarity_frequency must be > 0, got {self.similarity_frequency}"
            )
        if self.convention not in (Convention.C, Convention.CUDA):
            raise ValueError(f"unknown convention: {self.convention!r}")


DEFAULT_CONFIG = GameConfig()
