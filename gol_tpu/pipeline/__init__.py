"""Async execution pipeline: overlap host work with device compute.

The reference's whole ``async`` variant (src/game_mpi_async.c) exists to
hide file I/O behind compute: ``MPI_File_iwrite_at`` is posted at a boundary
and only waited on at the *next* boundary. This package is that discipline
for the reproduction's two serial host taxes:

- ``writer.AsyncCheckpointWriter`` — ``--checkpoint-every`` saves split into
  a cheap foreground snapshot (device->host copy; ``snapshot.HostSnapshot``)
  and a background payload write; the commit (and, on multihost, every
  collective) waits at the NEXT boundary, exactly the iwrite/Wait-at-next-
  step shape. The crash-consistency contract of resilience/checkpoint.py is
  preserved verbatim: a checkpoint simply is not committed until its
  deferred barrier lands, and auto-resume falls back to the last committed
  one.
- ``inflight.Handoff`` — the dispatcher->completer handoff behind the serve
  scheduler's pipelined dispatch (``pipeline_depth`` >= 2): the device
  computes batch N while the host stages N+1 and journals N-1.

The third leg, buffer donation on the carried engine state, lives in
``ops/jit_compat.py`` (it is a property of the runners, not of this
package); the foreground snapshot here is what makes donation safe — the
writer never touches a device buffer after ``save()`` returns.

Wall-clock discipline: like serve/, obs/, and tune/, this package is
``time.perf_counter()`` only (tests/test_lint.py bans ``time.time``).
"""

from gol_tpu.pipeline.inflight import Handoff
from gol_tpu.pipeline.snapshot import HostSnapshot
from gol_tpu.pipeline.writer import AsyncCheckpointWriter

__all__ = ["AsyncCheckpointWriter", "Handoff", "HostSnapshot"]
