"""Host mirror of a (possibly sharded) device array.

The async checkpoint writer must keep NO reference to device buffers once
``save()`` returns: the segment runners donate their carried state
(ops/jit_compat.py), so the array handed to ``save`` is consumed by the
very next segment dispatch. ``HostSnapshot`` is the foreground copy that
makes this safe — and it exposes exactly the surface the payload writers
already consume from a ``jax.Array``:

- ``.shape`` / ``.dtype`` — geometry checks (packed_io, ts_store);
- ``.addressable_shards`` with per-shard ``.index`` / ``.data`` — the shard
  walk of ``io/sharded.write_sharded``, ``io/packed_io.write_packed``,
  ``io/ts_store._write_shards``, and ``resilience.checkpoint.
  _shard_checksums``, with ``.data`` now a host ndarray;
- ``.sharding`` — ts_store reads ``sharding.mesh`` to pick chunk layout
  (a Sharding is host metadata; holding it pins no device memory);
- ``__array__`` — the gather fallback (``np.asarray`` in text_grid /
  write_gathered).

Because the shard decomposition is mirrored 1:1, every payload a writer
produces from a snapshot is byte-identical to what it would have produced
from the live device array (pinned by tests/test_pipeline.py), and the
manifest's geometry-keyed CRC blocks come out identical too.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class _HostShard:
    """One shard's host copy: the two attributes every writer reads."""

    index: tuple  # tuple of slices into the global array
    data: np.ndarray


class HostSnapshot:
    """Device->host copy of an array, shard structure preserved.

    Construction BLOCKS until every shard's bytes are on the host — the
    donation-safety contract: after ``HostSnapshot(state)`` returns, the
    caller may free/donate ``state``.
    """

    def __init__(self, state):
        self.shape = tuple(int(d) for d in state.shape)
        self.dtype = np.dtype(getattr(state, "dtype", None) or np.uint8)
        # Sharding/mesh metadata only — never a device buffer.
        self.sharding = getattr(state, "sharding", None)
        shards = getattr(state, "addressable_shards", None)
        if shards is None:  # plain ndarray (or anything array-like)
            full = np.ascontiguousarray(np.asarray(state))
            self.dtype = full.dtype
            self.addressable_shards = [
                _HostShard(index=tuple(slice(None) for _ in self.shape),
                           data=full)
            ]
        else:
            self.addressable_shards = [
                _HostShard(index=shard.index,
                           data=np.ascontiguousarray(np.asarray(shard.data)))
                for shard in shards
            ]

    def __array__(self, dtype=None, copy=None):
        """Assemble the full host array (the gather-writer fallback).

        The common case — one shard spanning the whole array (single-device
        runs) — returns that shard's buffer directly: the text codec calls
        this once per checkpoint on the background writer thread, and an
        avoidable full-grid copy there is exactly the class of cost this
        package exists to remove."""
        if len(self.addressable_shards) == 1:
            only = self.addressable_shards[0]
            if only.data.shape == self.shape:
                out = (only.data if dtype is None
                       else only.data.astype(dtype, copy=False))
                # Honor an explicit copy request (NumPy 2 __array__
                # protocol): the fast path otherwise hands out the internal
                # shard buffer, which a caller must not mutate in place.
                if copy and out is only.data:
                    out = out.copy()
                return out
        full = np.zeros(self.shape, self.dtype)
        for shard in self.addressable_shards:
            full[shard.index] = shard.data
        return full if dtype is None else full.astype(dtype, copy=False)

    @property
    def nbytes(self) -> int:
        return sum(int(s.data.nbytes) for s in self.addressable_shards)
