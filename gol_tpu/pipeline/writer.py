"""Async checkpoint writer: hide payload I/O behind device compute.

The synchronous ``CheckpointManager.save`` costs the device a full stall
per boundary: device->host copy, CRC, serialize, payload write, fsync,
manifest commit — the device idles the whole time. This writer splits that
into the reference's async-variant shape (src/game_mpi_async.c posts
``MPI_File_iwrite_at`` at one boundary and ``MPI_Wait``s at the next):

- **foreground** (``save``, on the segment loop's thread): drain the
  PREVIOUS boundary's write and commit its manifest, fire the boundary
  fault probe, then take a ``HostSnapshot`` (device->host copy, the only
  part that must precede the next segment's dispatch — and the part that
  makes buffer donation on the carried state safe) and hand it to the
  writer thread. The segment loop dispatches the next segment immediately.
- **background** (one ``gol-ckpt-writer`` thread): payload write + fsync +
  per-shard CRCs from the snapshot (``CheckpointManager._write_payload`` —
  the byte-identical sync machinery, fed host shards).
- **deferred commit** (foreground, at the next boundary or at ``drain()``):
  manifest commit + GC — ``CheckpointManager._commit_manifest``. A
  checkpoint simply does not EXIST (no manifest) until its deferred wait
  lands, so the write-ahead crash contract and auto-resume ordering of
  resilience/checkpoint.py hold verbatim: a kill mid-background-write
  leaves the previous committed checkpoint as the newest durable state.

Multihost runs fall back to synchronous saves: the payload writers'
collective barriers (ts_store vote/commit) must run on the main thread in
program order, and splitting them across a worker would interleave
collectives. The commit-at-next-boundary protocol is still the right
long-term multihost shape (votes/checksum-merge/commit are already
foreground-only here); the payload write is what needs a collective-free
path first.

Observability: ``pipeline.stage`` / ``pipeline.write`` / ``pipeline.drain``
spans; ``checkpoint_write_hidden_seconds`` (write time that overlapped
compute) and ``pipeline_stalls_total`` counters plus the
``ckpt_writer_queue_depth`` gauge in the global registry; the flight
recorder's dump carries the writer-queue state via a registered state
provider (obs/recorder.py), so a post-mortem shows whether the process died
with a write in flight and for which generation.
"""

from __future__ import annotations

import logging
import threading
import time

from gol_tpu.obs import recorder, registry as obs_registry, trace as obs_trace
from gol_tpu.pipeline.snapshot import HostSnapshot
from gol_tpu.resilience import faults

logger = logging.getLogger(__name__)

_STATE_PROVIDER = "checkpoint_writer"
QUEUE_DEPTH_GAUGE = "ckpt_writer_queue_depth"


class _WriteTask:
    """One boundary's pending write: snapshot in, checksums (or error) out."""

    __slots__ = ("snapshot", "shape", "generation", "counter", "started",
                 "done", "checksums", "error", "write_seconds")

    def __init__(self, snapshot, generation: int, counter: int):
        self.snapshot = snapshot
        self.shape = snapshot.shape
        self.generation = generation
        self.counter = counter
        self.started = False
        self.done = False
        self.checksums: dict | None = None
        self.error: BaseException | None = None
        self.write_seconds = 0.0


class AsyncCheckpointWriter:
    """Pipelined front end over one ``CheckpointManager``.

    At most ONE write is in flight (the bounded window; together with the
    snapshot the consumer holds, this is the classic double buffer).
    ``save`` is called from the segment loop at each boundary; ``drain``
    commits the final pending checkpoint at the end of the run; ``close``
    joins the thread and never raises (error-path hygiene — call it in a
    ``finally``).
    """

    THREAD_NAME = "gol-ckpt-writer"

    def __init__(self, manager, registry=None):
        import jax

        self._mgr = manager
        self._reg = registry or obs_registry.default()
        self._cv = threading.Condition()
        self._task: _WriteTask | None = None
        self._thread: threading.Thread | None = None
        self._stop = False
        self._closed = False
        self._sync = jax.process_count() > 1
        if self._sync:
            logger.info(
                "async checkpoint writer: %d-process run — payload writes "
                "carry collective barriers that must stay on the main "
                "thread; saves run synchronously",
                jax.process_count(),
            )
        recorder.add_state_provider(_STATE_PROVIDER, self._state)

    # -- the foreground half -------------------------------------------------

    def save(self, state, generation: int, counter: int) -> None:
        """The boundary call: drain the previous write, snapshot, hand off.

        Returns as soon as the snapshot is on the host; the caller may
        immediately dispatch the next segment (and the engine may donate
        ``state``'s buffer — the snapshot holds no device reference).
        """
        if self._sync:
            self._mgr.save(state, generation, counter)
            return
        self.drain()  # the Wait-at-next-boundary: commit the previous write
        if self._mgr.sheds_save():
            # Disk pressure (resilience/diskguard): the same shed decision
            # the sync lane takes, after the previous write committed.
            return
        try:
            faults.on_checkpoint_boundary(generation)
            if self._mgr._already_committed(generation):
                # A resumed run re-reached a boundary it had already
                # committed; the existing checkpoint IS this state. The
                # sync lane counts this skip as a completed save (its
                # wrapper increments unconditionally on return) — count it
                # here too so the A/B lanes' metrics stay comparable.
                self._reg.inc("checkpoint_saves_total")
                return
            self._mgr._sweep_stale(generation)
            with obs_trace.span("pipeline.stage", generation=int(generation)):
                snapshot = HostSnapshot(state)
            task = _WriteTask(snapshot, int(generation), int(counter))
            with self._cv:
                if self._closed:
                    raise RuntimeError("async checkpoint writer is closed")
                self._ensure_thread()
                self._task = task
                self._reg.set_gauge(QUEUE_DEPTH_GAUGE, 1)
                self._cv.notify_all()
        except BaseException:
            # BaseException: an InjectedCrash at the boundary probe must be
            # counted like the sync path counts it.
            self._reg.inc("checkpoint_save_failures_total")
            raise

    def drain(self) -> None:
        """Wait for the in-flight payload write and COMMIT its manifest.

        Called implicitly at every boundary and explicitly at the end of the
        run (the final checkpoint's deferred wait). Raises the background
        write's error, if any — deferred exactly one boundary, like the
        ``MPI_Wait`` status of the reference's async writes."""
        if self._sync:
            return
        with self._cv:
            task = self._task
        if task is None:
            return
        with obs_trace.span("pipeline.drain", generation=task.generation):
            t0 = time.perf_counter()
            with self._cv:
                stalled = not task.done
                while not task.done:
                    self._cv.wait()
                self._task = None
                self._reg.set_gauge(QUEUE_DEPTH_GAUGE, 0)
            waited = time.perf_counter() - t0
            if stalled:
                # The segment finished before the write did: the pipeline
                # stalled on I/O (counted so BENCH runs show where depth or
                # storage is the limiter).
                self._reg.inc("pipeline_stalls_total")
            self._reg.inc(
                "checkpoint_write_hidden_seconds",
                max(0.0, task.write_seconds - waited),
            )
            try:
                if task.error is not None:
                    raise task.error
                self._mgr._commit_manifest(
                    task.shape, task.generation, task.counter,
                    task.checksums, None,
                )
                # --checkpoint-keep pruning, strictly BEHIND the deferred
                # commit (and under the manager's _io_lock, which the
                # background payload write also holds): pruning can never
                # overlap a write staging files into the same directory.
                self._mgr.prune()
            except BaseException:
                self._reg.inc("checkpoint_save_failures_total")
                raise
            self._reg.inc("checkpoint_saves_total")

    def close(self) -> None:
        """Join the writer thread. NEVER raises: safe in ``finally`` on the
        error path (a crash unwinding through the segment loop must not be
        masked by a pending write's failure — which is logged instead)."""
        with self._cv:
            self._closed = True
            self._stop = True
            self._cv.notify_all()
            thread, self._thread = self._thread, None
            task, self._task = self._task, None
        if thread is not None:
            thread.join(timeout=60)
            if thread.is_alive():  # pragma: no cover - pathological I/O hang
                logger.error("async checkpoint writer thread did not join")
        recorder.remove_state_provider(_STATE_PROVIDER)
        self._reg.set_gauge(QUEUE_DEPTH_GAUGE, 0)
        if task is not None and task.error is not None:
            logger.warning(
                "async checkpoint writer: dropping failed write for "
                "generation %d at close: %s: %s", task.generation,
                type(task.error).__name__, task.error,
            )
        elif task is not None and not task.done:
            # The run died with a write in flight: its payload (if any)
            # stays uncommitted — invisible garbage the next GC sweeps.
            logger.warning(
                "async checkpoint writer: abandoning uncommitted write for "
                "generation %d at close", task.generation,
            )

    # -- the background half -------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._worker, name=self.THREAD_NAME, daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cv:
                while not self._stop and (
                    self._task is None or self._task.started
                ):
                    self._cv.wait()
                if self._stop:
                    return
                task = self._task
                task.started = True
            t0 = time.perf_counter()
            try:
                with obs_trace.span("pipeline.write",
                                    generation=task.generation):
                    task.checksums, _ = self._mgr._write_payload(
                        task.snapshot, task.generation
                    )
            except BaseException as err:  # noqa: BLE001 - InjectedCrash too
                task.error = err
            task.write_seconds = time.perf_counter() - t0
            task.snapshot = None  # release the buffer before the next one
            with self._cv:
                task.done = True
                self._cv.notify_all()

    # -- introspection (flight recorder) ------------------------------------

    def _state(self) -> dict:
        with self._cv:
            task = self._task
            return {
                "queue_depth": 0 if task is None else 1,
                "pending_generation": None if task is None else task.generation,
                "busy": bool(task is not None and task.started and not task.done),
                "sync_fallback": self._sync,
            }
