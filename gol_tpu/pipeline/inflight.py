"""Closeable FIFO handoff between pipeline stages.

The serve scheduler's pipelined dispatch is a two-thread pipeline: a
dispatcher claims batches, stages host operands, and posts the async device
dispatch; a completer blocks on readback, journals, and finalizes jobs —
in COMPLETION order (the window accounting lives in the scheduler's own
condition variable; this class is only the ordered conduit between the two
stages). The same shape as the reference's iwrite-then-wait split, applied
to batch dispatch instead of file I/O.

Deliberately tiny: ``put`` never blocks (the scheduler bounds in-flight
work BEFORE claiming, so the queue can never exceed the window depth);
``get`` blocks until an item or close; ``close`` drains — consumers see
every item already put, then ``None``. A ``queue.Queue`` + in-band None
sentinel would cover the happy path, but here put-after-close is a LOUD
error (a dispatcher bug must not silently enqueue work no completer will
ever see) and ``None`` stays out of band — that contract is the class.
"""

from __future__ import annotations

import collections
import threading


class Handoff:
    """Unbounded closeable FIFO; ``get`` returns None once closed and empty."""

    def __init__(self):
        self._cv = threading.Condition()
        self._items: collections.deque = collections.deque()
        self._closed = False

    def put(self, item) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("handoff is closed")
            self._items.append(item)
            self._cv.notify_all()

    def get(self):
        """Next item, blocking; None when closed and drained."""
        with self._cv:
            while not self._items and not self._closed:
                self._cv.wait()
            if self._items:
                return self._items.popleft()
            return None

    def __len__(self) -> int:
        with self._cv:
            return len(self._items)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
