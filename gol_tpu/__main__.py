"""``python -m gol_tpu`` — the ``./a.out`` of the TPU build."""

import sys

from gol_tpu.cli import main

sys.exit(main())
