"""Serial NumPy oracle — the ground-truth engine every TPU path is tested against.

Plays the role of the reference's serial program (src/game.c): rule B3/S23 on a
torus (src/game.c:60-101), emptiness checked at the top of every generation
(src/game.c:177), similarity checked every SIMILARITY_FREQUENCY-th generation
by comparing the current and next generations (src/game.c:181-189), reported
count = ``generation - 1`` (src/game.c:202).

Also implements the CUDA program's divergent accounting (src/game_cuda.cu:
213-276) so the ``cuda`` variant can be differential-tested too — see
``gol_tpu.config.Convention``.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from gol_tpu.config import Convention, DEFAULT_CONFIG, GameConfig


@dataclasses.dataclass
class Result:
    """Final state of a simulation run."""

    grid: np.ndarray  # uint8 {0,1}, shape (height, width)
    generations: int  # the count the reference would print


def neighbor_counts(grid: np.ndarray) -> np.ndarray:
    """Count the 8 Moore neighbors of every cell with toroidal wrap.

    The reference wraps by per-cell index remapping (src/game.c:69-86); with
    whole-array ops the same torus is 8 shifted copies.
    """
    g = grid
    counts = np.zeros(g.shape, dtype=np.uint8)
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dy == 0 and dx == 0:
                continue
            counts += np.roll(g, (dy, dx), axis=(0, 1))
    return counts


def evolve(grid: np.ndarray) -> np.ndarray:
    """One generation of B3/S23 (src/game.c:88-98)."""
    g = np.asarray(grid, dtype=np.uint8)
    n = neighbor_counts(g)
    return ((n == 3) | ((n == 2) & (g == 1))).astype(np.uint8)


def _run_c(grid: np.ndarray, config: GameConfig) -> Result:
    """The serial/MPI loop shape (src/game.c:169-196).

    On a similarity exit the reference breaks *before* the buffer swap and
    prints the pre-swap buffer (src/game.c:183-189); the two buffers are equal
    when the check fires, so returning the new grid is byte-identical.
    """
    generation = 1
    counter = 0
    while grid.any() and generation <= config.gen_limit:
        new = evolve(grid)
        if config.check_similarity:
            counter += 1
            if counter == config.similarity_frequency:
                if np.array_equal(grid, new):
                    return Result(new, generation - 1)
                counter = 0
        grid = new
        generation += 1
    return Result(grid, generation - 1)


def _run_cuda(grid: np.ndarray, config: GameConfig) -> Result:
    """The CUDA loop shape (src/game_cuda.cu:222-276).

    Differences vs ``_run_c``: no emptiness test before the first evolve; the
    emptiness test runs on the *new* grid and breaks before the swap, so an
    empty exit keeps (and writes) the last non-empty generation; the counter
    is 0-based and printed un-decremented (src/game_cuda.cu:294).

    Deliberate divergence: the real binary's compare/empty kernels scan the
    *padded* arrays (src/game_cuda.cu:243,259) whose d_new_univ ghost ring is
    stale — the halo kernels only ever run on d_univ (src/game_cuda.cu:
    224-231) — so live leftover border bytes can delay its early exits by a
    generation when death/stabilization coincides with earlier live borders.
    This build checks the interior only (exits are never later than the
    binary's); reproducing the stale-memory artifact is a non-goal.
    """
    generation = 0
    counter = 0
    while generation < config.gen_limit:
        new = evolve(grid)
        if config.check_similarity:
            counter += 1
            if counter == config.similarity_frequency:
                if np.array_equal(grid, new):
                    break
                counter = 0
        if not new.any():
            break
        grid = new
        generation += 1
    return Result(grid, generation)


def run(grid: np.ndarray, config: GameConfig = DEFAULT_CONFIG) -> Result:
    """Run a full simulation on the host, returning final grid + count."""
    grid = np.ascontiguousarray(np.asarray(grid, dtype=np.uint8))
    if grid.ndim != 2:
        raise ValueError(f"grid must be 2D, got shape {grid.shape}")
    if config.convention == Convention.CUDA:
        return _run_cuda(grid, config)
    return _run_c(grid, config)
