"""The serve-side background sampler: SLO ticks + the dispatch-gap monitor.

BENCH_r08 proved the value of decomposing serve throughput into "what the
compiled kernels can do" (the marginal kernel rate) vs "what the service
achieves" — once, offline. This module makes that decomposition continuous:
one thread (``gol-serve-sampler``) ticks every ``interval`` seconds and

1. **evaluates the SLO engine** (obs/slo.py) so ``GET /slo`` and the
   shedding decision read a fresh cache instead of evaluating inline;
2. **monitors the dispatch gap**: the scheduler feeds per-bucket
   ``serve_cell_updates_total_<bucket>`` counters (actual board cells times
   generations really run); the sampler differentiates them per tick into
   achieved cell-updates/s and — when the tuned plan recorded a marginal
   kernel rate for the bucket (``gol tune --serve-board`` persists it,
   ``tune.select.marginal_rates`` serves it) — exports the live BENCH_r08
   gap ratio as gauges:

   - ``bucket_cell_updates_per_sec_<bucket>``   achieved, per bucket
   - ``dispatch_gap_ratio_<bucket>``            achieved / marginal
   - ``serve_cell_updates_per_sec``             achieved, whole service
   - ``dispatch_gap_ratio``                     achieved / roofline, where
     the roofline is the work-weighted combination of the known marginal
     rates (exactly BENCH_r08's ``marginal_rate_combined`` arithmetic,
     applied to the last tick's work mix)

   Gauges update only on ticks that saw new work — an idle service keeps
   its last ratio instead of decaying to a meaningless 0.

Clock discipline: ``time.perf_counter()`` only (tests/test_lint.py bans the
wall clock from this package); bucket names ride through the one
``registry.metric_label`` sanitizer so writer and reader agree.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger(__name__)

THREAD_NAME = "gol-serve-sampler"
_BUCKET_PREFIX = "serve_cell_updates_total_"
_TOTAL_COUNTER = "serve_cell_updates_total"


class ServeSampler:
    """Periodic SLO evaluation + dispatch-gap gauges over one registry.

    ``slo`` may be None (gap monitoring only). ``marginal_rates`` maps
    sanitized bucket labels to tuned marginal kernel cell-updates/s; absent
    or empty, achieved-rate gauges still export and the gap ratios simply
    don't. ``start()`` spawns the daemon thread; ``tick()`` is public so
    tests (and embedders without a thread) can drive it deterministically.
    """

    def __init__(
        self,
        registry,
        slo=None,
        interval: float = 1.0,
        marginal_rates: dict[str, float] | None = None,
        history=None,
        clock=time.perf_counter,
    ):
        if interval <= 0:
            raise ValueError(f"interval must be > 0, got {interval}")
        self.registry = registry
        self.slo = slo
        self.interval = interval
        self.marginal_rates = dict(marginal_rates or {})
        # Durable metrics history (obs/history.py HistoryWriter) or None
        # (the default — no history object means zero per-tick cost).
        self.history = history
        # Per-tick hooks (the storage-lifecycle tick rides here: disk-guard
        # watermarks, journal-bytes gauges, idle-time compaction). Run
        # after the gap sample and BEFORE the history append, so gauges a
        # hook sets land in the same durable record; a raising hook is
        # logged and skipped, never kills the sampler thread.
        self._hooks: list = []
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last: dict[str, tuple[float, float]] = {}  # counter -> (t, v)

    def add_hook(self, hook) -> None:
        """Register a zero-arg callable to run every tick."""
        self._hooks.append(hook)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=THREAD_NAME, daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)
            if thread.is_alive():
                logger.warning("%s did not stop within %.1fs",
                               THREAD_NAME, timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - a bad tick must not kill it
                logger.exception("serve sampler tick failed")

    # -- one tick ----------------------------------------------------------

    def tick(self) -> None:
        if self.slo is not None:
            self.slo.evaluate()
        self._sample_gap()
        for hook in self._hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 - a bad hook must not kill it
                logger.exception("serve sampler hook failed")
        if self.history is not None:
            # One snapshot per tick into the durable ring: taken AFTER the
            # gap sample (and the hooks) so the freshly-set gauges ride
            # the same record.
            self.history.append(self.registry.snapshot())

    def _sample_gap(self) -> None:
        now = self._clock()
        counters = self.registry.snapshot()["counters"]
        ideal_seconds = 0.0  # marginal-known work at the tuned rates
        unknown_cells = 0.0  # this tick's work in buckets with NO marginal
        for name, value in counters.items():
            if not name.startswith(_BUCKET_PREFIX):
                continue
            bucket = name[len(_BUCKET_PREFIX):]
            delta, dt = self._delta(name, now, value)
            if delta is None or delta <= 0:
                continue
            rate = delta / dt
            self.registry.set_gauge(
                f"bucket_cell_updates_per_sec_{bucket}", rate
            )
            marginal = self.marginal_rates.get(bucket)
            if marginal and marginal > 0:
                self.registry.set_gauge(
                    f"dispatch_gap_ratio_{bucket}", rate / marginal
                )
                ideal_seconds += delta / marginal
            else:
                unknown_cells += delta
        total = counters.get(_TOTAL_COUNTER)
        if total is not None:
            delta, dt = self._delta(_TOTAL_COUNTER, now, total)
            if delta is not None and delta > 0:
                self.registry.set_gauge(
                    "serve_cell_updates_per_sec", delta / dt
                )
                if ideal_seconds > 0 and unknown_cells == 0:
                    # achieved/roofline over the tick: the work took dt of
                    # wall time that the marginal kernels would have done in
                    # ideal_seconds (BENCH_r08's combined-rate rule, live).
                    # Only when EVERY bucket that produced work this tick
                    # has a tuned marginal: with unknown-bucket work in dt
                    # but not in ideal_seconds the ratio would sag on a
                    # perfectly healthy service — a standing false alarm.
                    # Per-bucket ratios above still export regardless.
                    self.registry.set_gauge(
                        "dispatch_gap_ratio", ideal_seconds / dt
                    )

    def _delta(self, name: str, now: float, value: float):
        """(delta, dt) since this counter's previous tick, None first time."""
        prev = self._last.get(name)
        self._last[name] = (now, value)
        if prev is None:
            return None, 0.0
        dt = now - prev[0]
        if dt <= 0:
            return None, 0.0
        return value - prev[1], dt


__all__ = ["ServeSampler", "THREAD_NAME"]
