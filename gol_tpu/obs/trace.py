"""Span-based structured tracing over ``perf_counter``.

The reference's observability is three phase printfs (``Reading file`` /
``Execution time`` / ``Writing file``, include/timestamp.h); this module is
the structured replacement: any layer wraps a region in

    with trace.span("halo_exchange", gen=g):
        ...

and the finished span (name, start, duration, thread, nesting depth,
attributes) lands in a bounded thread-safe ring buffer, exportable as
Chrome trace-event JSON (loadable in Perfetto / chrome://tracing) and
dumpable post-mortem by the flight recorder (obs/recorder.py).

Cost discipline — the engine's hot paths call ``span`` unconditionally:

- **Off by default, zero-allocation when disabled**: ``span()`` returns a
  module-level no-op singleton (no object is constructed, ``__enter__`` /
  ``__exit__`` are constant methods) after one module-attribute check.
  ``bench.py --suite default`` with tracing disabled is pinned to < 2% of
  the pre-obs baseline (ISSUE 4 acceptance).
- Enabled, a span costs two ``perf_counter`` calls, one small object, and
  one deque append under a lock.

Clock discipline: every duration and ordering decision uses
``time.perf_counter()`` — monotonic, never stepped by NTP; the wall clock
is banned from this package by tests/test_lint.py. The ONE exception, by
design, is a single per-process wall-clock **anchor** (``time.time_ns()``,
captured once at ``enable()``): it never enters any duration or timestamp
arithmetic inside the process — it is exported as trace metadata so traces
from different processes (a pod, a server fleet) can be aligned on one
wall-clock axis after the fact.
"""

from __future__ import annotations

import json
import threading
import time

_DEFAULT_RING = 4096  # finished spans retained (most recent)


class Span:
    """One finished (or in-flight) traced region."""

    __slots__ = ("name", "start", "duration", "tid", "thread_name", "depth",
                 "attrs")

    def __init__(self, name, start, tid, thread_name, depth, attrs):
        self.name = name
        self.start = start  # perf_counter seconds
        self.duration = 0.0  # filled at __exit__
        self.tid = tid
        self.thread_name = thread_name
        self.depth = depth
        self.attrs = attrs

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "start_s": self.start,
            "duration_s": self.duration,
            "tid": self.tid,
            "thread": self.thread_name,
            "depth": self.depth,
            "attrs": self.attrs,
        }


class _SpanContext:
    """Context manager recording one span into the tracer's ring."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer, span):
        self._tracer = tracer
        self._span = span

    def __enter__(self):
        self._span.start = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb):
        span = self._span
        span.duration = time.perf_counter() - span.start
        if exc_type is not None:
            span.attrs = dict(span.attrs or ())
            span.attrs["error"] = exc_type.__name__
        self._tracer._record(span)
        return False


class _NoopSpan:
    """The disabled-path singleton: entering yields None, exiting records
    nothing. One instance serves every call site — ``span()`` while disabled
    allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return False


_NOOP = _NoopSpan()


class Tracer:
    """Ring buffer of finished spans + per-thread nesting state."""

    def __init__(self, ring_size: int = _DEFAULT_RING):
        import collections

        self.enabled = False
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=ring_size)
        self._local = threading.local()
        self._dropped = 0
        # Anchors are set at enable(); zero until then.
        self.anchor_perf = 0.0
        self.anchor_unix_ns = 0

    # -- lifecycle ---------------------------------------------------------

    def enable(self, ring_size: int | None = None) -> None:
        import collections

        with self._lock:
            if ring_size is not None and ring_size != self._ring.maxlen:
                self._ring = collections.deque(self._ring, maxlen=ring_size)
            if not self.enabled:
                # The single wall-clock read in the package (see module
                # docstring): a cross-process alignment anchor, exported as
                # metadata, never used in timestamp/duration arithmetic.
                self.anchor_perf = time.perf_counter()
                self.anchor_unix_ns = time.time_ns()
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._dropped = 0

    # -- recording ---------------------------------------------------------

    def span(self, name: str, **attrs):
        """A context manager tracing ``name``; no-op singleton when disabled."""
        if not self.enabled:
            return _NOOP
        thread = threading.current_thread()
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        return _SpanContext(
            self, Span(name, 0.0, thread.ident, thread.name, depth,
                       attrs or None)
        )

    def event(self, name: str, **attrs) -> None:
        """An instant (zero-duration) event; dropped when disabled."""
        if not self.enabled:
            return
        thread = threading.current_thread()
        span = Span(name, time.perf_counter(), thread.ident, thread.name,
                    getattr(self._local, "depth", 0), attrs or None)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(span)

    def flow(self, name: str, flow_id, phase: str, **attrs) -> None:
        """A Chrome *flow* point: ``phase`` is ``"s"`` (start), ``"t"``
        (step), or ``"f"`` (finish). Flows with one id draw an arrow chain
        across threads in Perfetto — the serving layer uses them to tie a
        job's lifecycle (submit -> claim -> finish) to the ``serve.batch`` /
        ``serve.resident_loop`` spans it rode through. Dropped (no
        allocation past the enabled check) while tracing is disabled."""
        if not self.enabled:
            return
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be s/t/f, got {phase!r}")
        self.event(name, flow_phase=phase, flow_id=str(flow_id), **attrs)

    def _record(self, span: Span) -> None:
        self._local.depth = max(0, getattr(self._local, "depth", 1) - 1)
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(span)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """The retained spans, oldest first, as JSON-able dicts."""
        with self._lock:
            spans = list(self._ring)
        return [s.to_dict() for s in spans]

    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def metadata(self) -> dict:
        import os

        return {
            "pid": os.getpid(),
            "anchor_perf_s": self.anchor_perf,
            "anchor_unix_ns": self.anchor_unix_ns,
            "dropped_spans": self.dropped(),
        }

    def chrome_events(self) -> list[dict]:
        """The ring as Chrome trace events: ``ph:"X"`` complete events for
        spans, plus ``ph:"s"/"t"/"f"`` flow events for ``flow()`` points
        (the arrow chains tying job lifecycles to batch spans in Perfetto).

        Timestamps are microseconds since the process anchor — relative, as
        the trace-event format allows; the absolute anchor rides in the
        ``otherData`` metadata of ``export_chrome``. Sorted by ``ts`` so
        consumers (and tests/test_obs.py) see monotonic timestamps.
        """
        import os

        pid = os.getpid()
        events = []
        for s in self.snapshot():
            attrs = dict(s["attrs"] or {})
            phase = attrs.pop("flow_phase", None)
            ts = (s["start_s"] - self.anchor_perf) * 1e6
            if phase in ("s", "t", "f"):
                ev = {
                    "name": s["name"],
                    "cat": "flow",
                    "ph": phase,
                    "id": attrs.pop("flow_id", "0"),
                    "ts": ts,
                    "pid": pid,
                    "tid": s["tid"],
                }
                if phase == "f":
                    # Bind the finish to the enclosing slice, the Chrome
                    # trace format's rule for flows that END inside a span.
                    ev["bp"] = "e"
                if attrs:
                    ev["args"] = attrs
                events.append(ev)
                continue
            events.append({
                "name": s["name"],
                "ph": "X",
                "ts": ts,
                "dur": s["duration_s"] * 1e6,
                "pid": pid,
                "tid": s["tid"],
                "args": dict(attrs, depth=s["depth"]),
            })
        events.sort(key=lambda e: e["ts"])
        return events

    def export_chrome(self, path: str) -> str:
        """Write the ring as a Chrome trace JSON object to ``path``."""
        payload = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
            "otherData": self.metadata(),
        }
        with open(path, "w", encoding="utf-8") as f:
            json.dump(payload, f)
            f.write("\n")
        return path


# The process-global tracer every library call site records into. Like the
# registry singleton, a plain module global: `trace.span(...)` in a hot loop
# must be one attribute load + one bool check when disabled.
_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def enabled() -> bool:
    return _TRACER.enabled


def enable(ring_size: int | None = None) -> None:
    _TRACER.enable(ring_size)


def disable() -> None:
    _TRACER.disable()


def clear() -> None:
    _TRACER.clear()


def span(name: str, **attrs):
    """``with trace.span("halo_exchange", gen=g): ...`` — the library-wide
    tracing entry point (no-op singleton while tracing is disabled)."""
    if not _TRACER.enabled:
        return _NOOP
    return _TRACER.span(name, **attrs)


def event(name: str, **attrs) -> None:
    _TRACER.event(name, **attrs)


def flow(name: str, flow_id, phase: str, **attrs) -> None:
    """Record a flow point (``phase`` in s/t/f); no-op while disabled."""
    if not _TRACER.enabled:
        return
    _TRACER.flow(name, flow_id, phase, **attrs)


def snapshot() -> list[dict]:
    return _TRACER.snapshot()


def export_chrome(path: str) -> str:
    return _TRACER.export_chrome(path)
