"""Flight recorder: the last N spans/events, dumped when something dies.

The reference leaves NOTHING behind when a run hangs or crashes — four
numbers print at the end or never. This module gives every entry point a
post-mortem story: armed with ``install(dir)``, the process dumps its recent
trace ring (obs/trace.py) plus the global metrics registry (obs/registry.py)
as one JSONL file

- on **crash** — an uncaught exception reaching ``sys.excepthook``
  (including ``resilience.faults.InjectedCrash``, which no library layer
  may catch);
- on **fault-injection trigger** — ``resilience/faults.py`` calls
  ``trigger()`` right before it kills the process (covers ``kill_mode=
  sigkill``, where no Python unwinding ever happens);
- on **SIGUSR1** — a live, non-fatal dump: ``kill -USR1 <pid>`` answers
  "what is that hung server doing?" without stopping it.

Dump format (one JSON object per line, torn-tail tolerant like the job
journal): a header record ``{"record": "header", ...}`` with the reason and
the tracer anchors, one ``{"record": "span", ...}`` per retained span, one
``{"record": "state", "name": ..., ...}`` per registered state provider
(live subsystem snapshots — e.g. the async checkpoint writer's queue, so a
post-mortem shows whether a payload write was in flight), and a final
``{"record": "registry", ...}`` carrying the counter snapshot.
``gol trace-report`` renders these files directly.

File naming is wall-clock-free (the package-wide lint ban): ``flight-<pid>-
<seq>.jsonl``, the sequence a process-local counter — repeated SIGUSR1
dumps of one process never overwrite each other.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading

from gol_tpu.obs import registry, trace

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_dir: str | None = None
_seq = 0
_prev_excepthook = None
# Hook installation is tracked separately from arming: uninstall() only
# disarms (_dir = None) and leaves the hooks chained — a re-install that
# keyed "first" off _dir would chain sys.excepthook to ITSELF, and the next
# uncaught exception would recurse through the hook dumping files forever.
_hooks_installed = False
# Live-state providers: name -> zero-arg callable returning a JSON-able
# dict, snapshotted into every dump (each guarded — a provider that raises
# mid-crash is skipped, never allowed to abort the dump documenting the
# crash). Subsystems with in-flight state the registry's scalars cannot
# carry (the async checkpoint writer's pending generation) register here.
_state_providers: dict[str, object] = {}


def add_state_provider(name: str, fn) -> None:
    """Register ``fn`` to contribute a ``{"record": "state"}`` line to every
    dump. Last registration under a name wins (a fresh writer replaces a
    stale one's entry)."""
    with _lock:
        _state_providers[name] = fn


def remove_state_provider(name: str) -> None:
    with _lock:
        _state_providers.pop(name, None)


def armed() -> bool:
    return _dir is not None


def install(directory: str) -> None:
    """Arm the recorder: dumps land in ``directory``; the excepthook chain
    and (when possible) the SIGUSR1 handler are installed once per process
    (re-arming after ``uninstall`` just updates the directory)."""
    global _dir, _prev_excepthook, _hooks_installed
    os.makedirs(directory, exist_ok=True)
    with _lock:
        _dir = directory
        if _hooks_installed:
            return
        _hooks_installed = True
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    try:
        import signal

        # Only the main thread may install signal handlers; embedders that
        # arm the recorder from a worker just do without the SIGUSR1 lane.
        signal.signal(signal.SIGUSR1, _on_sigusr1)
    except (ValueError, OSError, AttributeError):  # non-main thread / platform
        logger.debug("flight recorder: SIGUSR1 handler not installed")


def uninstall() -> None:
    """Disarm (tests). The excepthook chain stays; it no-ops unarmed."""
    global _dir
    with _lock:
        _dir = None


def trigger(reason: str) -> str | None:
    """Dump now (fault-injection trigger, or any caller-decided moment).
    Returns the dump path, or None when unarmed. Never raises: a failing
    dump must not mask the crash it is trying to document."""
    global _seq
    with _lock:
        directory = _dir
        if directory is None:
            return None
        _seq += 1
        path = os.path.join(directory, f"flight-{os.getpid()}-{_seq}.jsonl")
    try:
        return _dump(path, reason)
    except Exception as err:  # noqa: BLE001 - the crash path must survive us
        logger.error("flight recorder dump failed: %s: %s",
                     type(err).__name__, err)
        return None


def _dump(path: str, reason: str) -> str:
    t = trace.tracer()
    with open(path, "w", encoding="utf-8") as f:
        header = {
            "record": "header",
            "reason": reason,
            **t.metadata(),
        }
        f.write(json.dumps(header) + "\n")
        for span in t.snapshot():
            f.write(json.dumps({"record": "span", **span}) + "\n")
        with _lock:
            providers = dict(_state_providers)
        for name, fn in providers.items():
            try:
                f.write(json.dumps(
                    {"record": "state", "name": name, **fn()}) + "\n")
            except Exception:  # noqa: BLE001 - a provider must not kill a dump
                logger.debug("flight recorder: state provider %r failed", name)
        f.write(json.dumps({
            "record": "registry",
            **registry.default().snapshot(),
        }) + "\n")
        f.flush()
        os.fsync(f.fileno())
    logger.warning("flight recorder: dumped %s (%s)", path, reason)
    return path


def _excepthook(exc_type, exc, tb):
    if armed() and exc_type is not SystemExit:
        trigger(f"crash: {exc_type.__name__}: {exc}")
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _on_sigusr1(signum, frame):
    trigger("SIGUSR1")


def read_dump(path: str) -> list[dict]:
    """Parse a flight-recorder JSONL file, dropping a torn tail line (the
    dump may itself have died mid-write — the journal's leniency rule)."""
    records = []
    with open(path, "rb") as f:
        for line in f.read().split(b"\n"):
            if not line:
                continue
            try:
                records.append(json.loads(line.decode("utf-8")))
            except (ValueError, UnicodeDecodeError):
                continue
    return records
