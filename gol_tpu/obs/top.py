"""``gol top``: a live ANSI terminal dashboard over /metrics + /slo.

One screen, refreshed in place, answering the operator's standing questions
without curl loops: is the queue backing up, are the rings full, where are
the latency percentiles, is any SLO burning, and how close to the tuned
roofline is the service running (the live BENCH_r08 dispatch-gap ratio).

Pure rendering here — ``render_frame`` maps the two JSON payloads (the
``/metrics?format=json`` snapshot, whose ``process`` section carries the
process-global registry, and the ``/slo`` status) to one string; the CLI
owns polling and the terminal. Keeping it pure keeps it testable and keeps
this package free of HTTP concerns.
"""

from __future__ import annotations

CLEAR = "\x1b[2J\x1b[H"  # clear screen + cursor home
_RESET = "\x1b[0m"
_COLORS = {"ok": "\x1b[32m", "warning": "\x1b[33m", "critical": "\x1b[31m"}


def _color(status: str, text: str, ansi: bool) -> str:
    if not ansi:
        return text
    return _COLORS.get(status, "") + text + _RESET


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        if v != 0 and (abs(v) >= 1e5 or abs(v) < 1e-3):
            return f"{v:.3e}"
        return f"{v:.3f}".rstrip("0").rstrip(".") or "0"
    return str(v)


def _bytes_h(v) -> str:
    """Human byte figure for the storage row (None renders as '-')."""
    if v is None:
        return "-"
    v = float(v)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return (f"{v:.0f}{unit}" if unit == "B"
                    else f"{v:.1f}{unit}")
        v /= 1024
    return f"{v:.1f}TiB"


def _bar(frac: float, width: int = 20) -> str:
    frac = max(0.0, min(1.0, frac))
    filled = round(frac * width)
    return "[" + "#" * filled + "." * (width - filled) + "]"


def render_frame(metrics: dict, slo: dict | None, *, ansi: bool = True,
                 title: str = "gol top") -> str:
    """One dashboard frame from the two polled payloads (either may be an
    empty dict when its endpoint was unreachable — the frame says so
    instead of dying, because `gol top` outliving a crashing server is the
    point of a dashboard)."""
    counters = metrics.get("counters") or {}
    gauges = metrics.get("gauges") or {}
    hists = metrics.get("histograms") or {}
    process = metrics.get("process") or {}
    pgauges = process.get("gauges") or {}
    phists = process.get("histograms") or {}

    overall = (slo or {}).get("status", "?")
    fleet = metrics.get("fleet") or {}
    lines = [
        f"{title} — SLO {_color(overall, overall.upper(), ansi)}"
        + ("" if metrics else "   [/metrics unreachable]")
        + ("" if slo else "   [/slo unreachable]"),
    ]
    if fleet:
        # A fleet router's payload: the merged series render below exactly
        # as a single worker's would; this line says what they sum over.
        lines.append(
            f"fleet: {int(fleet.get('workers', 0))} workers, "
            f"{int(fleet.get('healthy', 0))} healthy, "
            f"{int(fleet.get('backpressured', 0))} backpressured, "
            f"{int(fleet.get('restarts', 0))} restart(s)"
            + (f", {int(fleet['retiring'])} retiring"
               if fleet.get("retiring") else "")
            + ("   DRAINING" if fleet.get("draining") else "")
        )
    autoscaler = (fleet or {}).get("autoscaler") or {}
    if autoscaler.get("enabled"):
        # The elastic-fleet panel: target vs actual N inside the
        # [min..max] band, plus the signal behind the last decision — the
        # one-line answer to "why is the fleet this size right now".
        last = autoscaler.get("last_decision") or {}
        target = autoscaler.get("target")
        action = last.get("action", "-")
        status = ("warning" if autoscaler.get("scaling")
                  else "ok" if action == "hold" else "warning")
        line = (
            f"autoscale: {int(autoscaler.get('workers', 0))} workers"
            f" (target {int(target) if target is not None else '-'},"
            f" min {int(autoscaler.get('min', 0))}"
            f" max {int(autoscaler.get('max', 0))})"
            + ("   SCALING" if autoscaler.get("scaling") else "")
        )
        if last:
            line += (
                f"   sat {_fmt(last.get('saturation'))}"
                f" occ {_fmt(last.get('occupancy'))}"
                f" burn {_fmt(last.get('burn'))}"
            )
            if last.get("action") not in (None, "hold") or last.get("reason"):
                line += f"   last: {action}"
                if last.get("reason"):
                    line += f" ({last['reason']})"
        lines.append(_color(status, line, ansi) if action != "hold"
                     else line)
    router_reg = (fleet or {}).get("router") or {}
    rcounters = router_reg.get("counters") or {}
    rgauges = router_reg.get("gauges") or {}
    rhists = router_reg.get("histograms") or {}
    owned = {k[len("shard_tiles_owned_"):]: v for k, v in rgauges.items()
             if k.startswith("shard_tiles_owned_")}
    if rcounters.get("shard_jobs_total") or owned:
        # The sharded-universe panel: one giant board split across the
        # fleet. The durable super-step is the replay floor — a SIGKILLed
        # worker rewinds to it, nobody else moves past it un-checkpointed.
        ss = rhists.get("shard_superstep_seconds") or {}
        lines.append(
            f"shard: jobs {int(rcounters.get('shard_jobs_total', 0))}"
            f"  done {int(rcounters.get('shard_jobs_done_total', 0))}"
            f"  failed {int(rcounters.get('shard_jobs_failed_total', 0))}"
            f"   durable step {int(rgauges.get('shard_durable_step', 0))}"
            f"   recoveries {int(rcounters.get('shard_recoveries_total', 0))}"
            f"   superstep p50 {_fmt(ss.get('p50'))}s"
            f" p95 {_fmt(ss.get('p95'))}s"
        )
        if owned:
            lines.append("  tiles: " + "  ".join(
                f"{wid} {int(n)}" for wid, n in sorted(owned.items())))
    lines.append("")

    # -- queue / flow -------------------------------------------------------
    lines.append("queue")
    depth = gauges.get("queue_depth", 0)
    lines.append(
        f"  depth {int(depth):>6}   inflight {int(gauges.get('inflight_batches', 0)):>3}"
        f"   journal-q {int(gauges.get('journal_queue_depth', 0)):>3}"
        f"   boards/s {_fmt(gauges.get('boards_per_sec'))}"
    )
    lines.append(
        f"  jobs: accepted {int(counters.get('jobs_accepted_total', 0))}"
        f"  done {int(counters.get('jobs_completed_total', 0))}"
        f"  failed {int(counters.get('jobs_failed_total', 0))}"
        f"  rejected {int(counters.get('jobs_rejected_total', 0))}"
        f"  shed {int(counters.get('jobs_shed_total', 0))}"
        f"  batches {int(counters.get('batches_total', 0))}"
    )
    # Result cache (only when a cache is mounted — the counters exist then).
    # The ratio is "consults that avoided an engine run": coalesced
    # submissions are counted inside misses (every tier missed) AND here,
    # so (hits + coalesced) / (hits + misses) is well-formed.
    hits = counters.get("cache_hits_total")
    misses = counters.get("cache_misses_total")
    if hits is not None or misses is not None:
        hits, misses = hits or 0, misses or 0
        coalesced = counters.get("cache_inflight_coalesced_total", 0)
        consults = hits + misses
        ratio = (hits + coalesced) / consults if consults else 0.0
        lines.append(
            f"  cache: hit ratio {_bar(ratio)} {ratio:.2f}"
            f"   hits {int(hits)} (mem {int(counters.get('cache_hits_total_memory', 0))}"
            f"/disk {int(counters.get('cache_hits_total_disk', 0))})"
            f"  coalesced {int(coalesced)}  misses {int(misses)}"
        )
    # Storage lifecycle (only when a journal/guard exports the gauges):
    # durable footprint, compaction count, and the watchdog's pressure
    # level — the answer to "is any partition about to fill".
    jbytes = gauges.get("journal_bytes")
    free = gauges.get("disk_free_bytes")
    if jbytes is not None or free is not None:
        level = int(gauges.get("disk_pressure_level", 0))
        level_names = ("ok", "shed-cas", "shed-ckpt", "REFUSING")
        level_name = (level_names[level] if 0 <= level < len(level_names)
                      else str(level))
        status = "ok" if level == 0 else ("critical" if level >= 3
                                          else "warning")
        line = (
            f"  storage: journal {_bytes_h(jbytes)}"
            f" (segs {int(gauges.get('journal_segments', 0))},"
            f" compactions {int(counters.get('compactions_total', 0))})"
            f"   cas {_bytes_h(gauges.get('cas_bytes'))}"
            f"   free {_bytes_h(free)}   guard {level_name}"
        )
        shed = counters.get("cas_writes_shed_total", 0)
        refused = counters.get("jobs_refused_disk_total", 0)
        if shed or refused:
            line += (f"   (shed {int(shed)} cas write(s),"
                     f" refused {int(refused)} job(s))")
        lines.append(_color(status, line, ansi) if level else line)
    # Sparse lane (only when sparse jobs have run — the counters exist
    # then): tile-steps executed and the last universe's live-tile
    # occupancy, the numbers that say how much dead area was elided.
    sparse_tiles = counters.get("sparse_tiles_simulated_total")
    if sparse_tiles is not None:
        occ = gauges.get("sparse_occupancy", 0.0)
        lines.append(
            f"  sparse: tiles {int(sparse_tiles)}"
            f"   occupancy {_bar(occ)} {occ:.4f}"
        )

    # -- rings / dispatch gap ----------------------------------------------
    ring_occ = pgauges.get("ring_slot_occupancy")
    gap = gauges.get("dispatch_gap_ratio")
    if ring_occ is not None or gap is not None:
        lines.append("")
        lines.append("device")
        if ring_occ is not None:
            lines.append(f"  ring occupancy {_bar(ring_occ)} {_fmt(ring_occ)}")
        if gap is not None:
            lines.append(
                f"  dispatch gap   {_bar(gap)} {_fmt(gap)} of tuned roofline"
                f"   ({_fmt(gauges.get('serve_cell_updates_per_sec'))} cells/s)"
            )
        gap_hist = phists.get("dispatch_gap_seconds")
        if gap_hist:
            lines.append(
                f"  device idle between drains: p50 {_fmt(gap_hist.get('p50'))}s"
                f"  p99 {_fmt(gap_hist.get('p99'))}s"
                f"  (n={gap_hist.get('count')})"
            )

    # -- latency percentiles ------------------------------------------------
    rows = [
        (name, hists[name]) for name in (
            "queue_latency_seconds", "run_latency_seconds",
            "job_latency_seconds", "job_latency_seconds_high",
            "job_latency_seconds_normal", "job_latency_seconds_low",
        ) if name in hists
    ]
    if rows:
        lines.append("")
        lines.append(f"  {'latency (s)':<28} {'p50':>10} {'p95':>10} "
                     f"{'p99':>10} {'count':>8}")
        for name, h in rows:
            lines.append(
                f"  {name:<28} {_fmt(h.get('p50')):>10} "
                f"{_fmt(h.get('p95')):>10} {_fmt(h.get('p99')):>10} "
                f"{h.get('count', 0):>8}"
            )

    # -- SLO burn rates -----------------------------------------------------
    objectives = (slo or {}).get("objectives") or []
    if objectives:
        windows = [f"{w}s" for w in (slo.get("windows_s") or [])]
        lines.append("")
        header = f"  {'objective':<24} {'status':>9}"
        for w in windows:
            header += f" {'burn@' + w:>11}"
        lines.append(header)
        for r in objectives:
            row = f"  {r['name']:<24} " + _color(
                r["status"], f"{r['status']:>9}", ansi
            )
            for w in windows:
                win = (r.get("windows") or {}).get(w) or {}
                row += f" {win.get('burn', 0.0):>11.3f}"
            lines.append(row)

    # -- per-bucket achieved rates -----------------------------------------
    buckets = sorted(
        (name[len("bucket_cell_updates_per_sec_"):], value)
        for name, value in gauges.items()
        if name.startswith("bucket_cell_updates_per_sec_")
    )
    if buckets:
        lines.append("")
        lines.append("  bucket throughput (cell-updates/s)")
        for bucket, rate in buckets:
            ratio = gauges.get(f"dispatch_gap_ratio_{bucket}")
            extra = f"   gap {_fmt(ratio)}" if ratio is not None else ""
            lines.append(f"    {bucket:<28} {_fmt(rate):>12}{extra}")

    # -- per-worker columns (fleet router payloads only) --------------------
    workers = metrics.get("workers") or {}
    if workers:
        slo_workers = (slo or {}).get("workers") or {}
        # Circuit-breaker column (PR 14): present only when the router
        # runs breakers — the header stays byte-identical otherwise.
        breakers = (fleet or {}).get("breakers")
        brk_head = f" {'brk':>9}" if breakers is not None else ""
        lines.append("")
        lines.append(
            f"  {'worker':<8} {'state':<13}{brk_head} {'queue':>6} "
            f"{'inflight':>8} "
            f"{'done':>9} {'failed':>7} {'boards/s':>10} {'slo':>12}"
        )
        for wid in sorted(workers):
            snap = workers[wid] or {}
            health = snap.get("health") or {}
            if snap.get("unreachable"):
                state, state_status = "unreachable", "critical"
            elif not health.get("healthy", True):
                state, state_status = "unhealthy", "critical"
            elif health.get("backpressure"):
                state, state_status = "backpressured", "warning"
            else:
                state, state_status = "ok", "ok"
            brk_cell = ""
            if breakers is not None:
                brk = breakers.get(wid, "closed")
                brk_status = {"closed": "ok", "half-open": "warning",
                              "open": "critical"}.get(brk, "warning")
                brk_cell = " " + _color(brk_status, f"{brk:>9}", ansi)
            wg = snap.get("gauges") or {}
            wc = snap.get("counters") or {}
            wslo = (slo_workers.get(wid) or {}).get("status", "-")
            lines.append(
                f"  {wid:<8} "
                + _color(state_status, f"{state:<13}", ansi)
                + brk_cell
                + f" {int(wg.get('queue_depth', 0)):>6}"
                f" {int(wg.get('inflight_batches', 0)):>8}"
                f" {int(wc.get('jobs_completed_total', 0)):>9}"
                f" {int(wc.get('jobs_failed_total', 0)):>7}"
                f" {_fmt(wg.get('boards_per_sec')):>10} "
                + _color(wslo, f"{wslo:>12}", ansi)
            )

    # -- router replicas (PR 16: horizontal control plane) ------------------
    # Present only when the answering router advertises a replica roster;
    # every older payload skips the panel byte-identically. "(this view)"
    # names the replica whose scrape built THIS frame — under --servers
    # failover the dashboard may follow a different replica next frame.
    routers = (fleet or {}).get("routers") or []
    if routers:
        me = (fleet or {}).get("router_id")
        lines.append("")
        lines.append(f"  {'router':<8} {'state':<8} {'pid':>7}  url")
        for r in routers:
            alive = bool(r.get("alive"))
            state = "alive" if alive else "gone"
            marker = ""
            if r.get("id") == me:
                marker = (" (this view, leader)" if (fleet or {}).get("leader")
                          else " (this view)")
            lines.append(
                f"  {str(r.get('id', '?')):<8} "
                + _color("ok" if alive else "critical", f"{state:<8}", ansi)
                + f" {int(r.get('pid') or 0):>7}  {r.get('url', '')}"
                f"{marker}"
            )

    return "\n".join(lines) + "\n"


__all__ = ["CLEAR", "render_frame"]
