"""Service-level objectives over rolling registry windows.

The metrics registry answers "what happened"; nothing in the tree answers
"is the service HEALTHY" — the standing question a fleet operator (and the
ROADMAP's scale-out item, whose worker health checks ride the obs registry)
needs a machine-checkable answer to. This module is that answer:

- an ``Objective`` declares a target over a registry series — per-priority
  p99 latency (``job_latency_seconds_<class>`` histograms), error rate
  (failed/accepted counter deltas), queue saturation (gauge over capacity);
- ``SloEngine`` keeps a rolling deque of timestamped registry snapshots
  (``time.perf_counter()`` only — the wall clock is banned from this
  package) and evaluates every objective over **multiple windows** (default
  60 s and 300 s), reporting a *burn rate* per window: observed / target,
  i.e. how many times faster than allowed the error budget is burning;
- an objective is ``warning`` when its burn clears ``warn_burn`` on every
  window and ``critical`` when it clears ``critical_burn`` on every window
  — the classic multi-window rule: the short window proves the problem is
  happening *now*, the long window that it is *sustained*, so a single
  slow batch cannot page anyone;
- the overall status is the worst objective's, served at ``GET /slo``,
  summarized by ``gol slo-report``, snapshotted into flight-recorder dumps
  via a state provider, and — only when explicitly enabled
  (``--slo-shed``; observe-only is the test-pinned default) — feeding
  admission control: a critical burn sheds new jobs with 429 + Retry-After.

Window semantics per objective kind:

- ``error_rate``: counter deltas between the newest snapshot and the newest
  snapshot at least one window old (falling back to the oldest sample while
  the engine is younger than the window); no traffic in the window = burn 0.
- ``saturation``: the max gauge/capacity seen across the window's samples.
- ``latency``: the histogram reservoir IS the rolling sample set (the
  registry keeps the most recent observations); a window with no new
  observations (count delta 0) reports burn 0, so p99 of stale traffic
  cannot hold an alert up after the problem stops.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time

logger = logging.getLogger(__name__)

OK = "ok"
WARNING = "warning"
CRITICAL = "critical"
_RANK = {OK: 0, WARNING: 1, CRITICAL: 2}

DEFAULT_WINDOWS = (60.0, 300.0)
STATE_PROVIDER = "slo"


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective over a registry series.

    ``kind`` selects the evaluation rule:

    - ``latency``    — ``source`` is a histogram; observed = its
      ``quantile`` (p99 by default); burn = observed / target seconds.
    - ``error_rate`` — ``source`` is the bad-event counter, ``total`` the
      traffic counter; observed = bad delta / total delta over the window;
      burn = observed / target ratio.
    - ``saturation`` — ``source`` is a gauge; observed = max(gauge) /
      ``capacity`` over the window; burn = observed / target fraction.
    """

    name: str
    kind: str  # "latency" | "error_rate" | "saturation"
    target: float
    source: str
    total: str = ""  # error_rate denominator counter
    capacity: float = 1.0  # saturation denominator
    quantile: float = 0.99

    def __post_init__(self):
        if self.kind not in ("latency", "error_rate", "saturation"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if self.target <= 0:
            raise ValueError(f"objective {self.name}: target must be > 0")
        if self.kind == "error_rate" and not self.total:
            raise ValueError(
                f"objective {self.name}: error_rate needs a total counter"
            )
        if self.kind == "saturation" and self.capacity <= 0:
            raise ValueError(
                f"objective {self.name}: saturation needs capacity > 0"
            )


def default_objectives(
    max_queue_depth: int,
    latency_target_s: float = 60.0,
    error_budget: float = 0.01,
    queue_target: float = 0.8,
) -> list[Objective]:
    """The serving defaults: p99 end-to-end latency per priority class,
    failed-over-accepted error rate, and queue-depth saturation — every
    series the scheduler already feeds its Metrics registry."""
    objectives = [
        Objective(
            name=f"latency_p99_{cls}",
            kind="latency",
            target=latency_target_s,
            source=f"job_latency_seconds_{cls}",
        )
        for cls in ("high", "normal", "low")
    ]
    objectives.append(Objective(
        name="error_rate",
        kind="error_rate",
        target=error_budget,
        source="jobs_failed_total",
        total="jobs_accepted_total",
    ))
    objectives.append(Objective(
        name="queue_saturation",
        kind="saturation",
        target=queue_target,
        source="queue_depth",
        capacity=float(max_queue_depth),
    ))
    return objectives


class SloEngine:
    """Rolling-window evaluation of objectives over one registry."""

    def __init__(
        self,
        objectives,
        registry,
        windows=DEFAULT_WINDOWS,
        warn_burn: float = 1.0,
        critical_burn: float = 2.0,
        shed: bool = False,
        retry_after_s: float = 5.0,
        clock=time.perf_counter,
    ):
        if not objectives:
            raise ValueError("need at least one objective")
        self.objectives = list(objectives)
        self.registry = registry
        self.windows = tuple(sorted(float(w) for w in windows))
        if not self.windows or self.windows[0] <= 0:
            raise ValueError(f"windows must be positive, got {windows}")
        self.warn_burn = warn_burn
        self.critical_burn = critical_burn
        self.shed = shed
        self.retry_after_s = retry_after_s
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque()  # (t, snap)
        self._last: dict | None = None
        self._last_at: float | None = None
        self._was_critical: set[str] = set()

    # -- sampling ----------------------------------------------------------

    def sample(self) -> None:
        """Append a timestamped registry snapshot and prune beyond the
        longest window (keeping one older sample as the window baseline)."""
        now = self._clock()
        snap = self.registry.snapshot()
        horizon = now - self.windows[-1]
        with self._lock:
            self._samples.append((now, snap))
            # Keep exactly one sample at-or-older than the horizon: it is
            # the baseline of the longest window's delta.
            while (len(self._samples) >= 2
                   and self._samples[1][0] <= horizon):
                self._samples.popleft()

    def _window_bounds(self, samples, now: float, window: float):
        """(baseline, newest) snapshots for one window: the newest sample at
        least ``window`` old, or the oldest available while the engine is
        younger than the window."""
        target = now - window
        baseline = samples[0]
        for t, snap in samples:
            if t <= target:
                baseline = (t, snap)
            else:
                break
        return baseline, samples[-1]

    # -- evaluation --------------------------------------------------------

    def _eval_objective(self, obj: Objective, samples, now: float) -> dict:
        windows = {}
        burns = []
        for window in self.windows:
            (t0, base), (t1, newest) = self._window_bounds(
                samples, now, window
            )
            in_window = [s for s in samples if s[0] >= t0]
            observed, burn = self._observe(obj, base, newest, in_window)
            burns.append(burn)
            windows[f"{int(window)}s"] = {
                "observed": observed,
                "burn": round(burn, 4),
                "span_s": round(t1 - t0, 3),
            }
        # Multi-window rule: alert only when EVERY window burns past the
        # threshold (min across windows is the binding burn).
        binding = min(burns) if burns else 0.0
        if binding >= self.critical_burn:
            status = CRITICAL
        elif binding >= self.warn_burn:
            status = WARNING
        else:
            status = OK
        return {
            "name": obj.name,
            "kind": obj.kind,
            "target": obj.target,
            "status": status,
            "burn": round(binding, 4),
            "windows": windows,
        }

    def _observe(self, obj: Objective, base: dict, newest: dict, in_window):
        """(observed, burn) of one objective over one window's snapshots."""
        if obj.kind == "error_rate":
            bad = (newest["counters"].get(obj.source, 0)
                   - base["counters"].get(obj.source, 0))
            total = (newest["counters"].get(obj.total, 0)
                     - base["counters"].get(obj.total, 0))
            if total <= 0:
                return None, 0.0
            ratio = max(0.0, bad) / total
            return round(ratio, 6), ratio / obj.target
        if obj.kind == "saturation":
            # Max over the window's samples, not just the endpoints: a
            # queue that spiked and drained still burned budget.
            frac = newest["gauges"].get(obj.source, 0.0) / obj.capacity
            for t, snap in in_window:
                g = snap["gauges"].get(obj.source)
                if g is not None:
                    frac = max(frac, g / obj.capacity)
            return round(frac, 6), frac / obj.target
        # latency: the reservoir is the rolling sample set; no NEW
        # observations in this window means nothing recent to judge.
        hist = newest["histograms"].get(obj.source)
        if not hist or not hist.get("count"):
            return None, 0.0
        base_hist = base["histograms"].get(obj.source) or {}
        if hist["count"] - base_hist.get("count", 0) <= 0:
            return None, 0.0
        q = hist.get(f"p{int(obj.quantile * 100)}")
        if q is None:
            return None, 0.0
        return q, q / obj.target

    def evaluate(self) -> dict:
        """Sample now and evaluate every objective; caches the result."""
        self.sample()
        now = self._clock()
        with self._lock:
            samples = list(self._samples)
        results = [
            self._eval_objective(obj, samples, now) for obj in self.objectives
        ]
        overall = OK
        for r in results:
            if _RANK[r["status"]] > _RANK[overall]:
                overall = r["status"]
        out = {
            "status": overall,
            "windows_s": [int(w) for w in self.windows],
            "warn_burn": self.warn_burn,
            "critical_burn": self.critical_burn,
            "shed": {
                "enabled": self.shed,
                "active": self.shed and overall == CRITICAL,
                "retry_after_s": self.retry_after_s,
            },
            "objectives": results,
        }
        critical_now = {r["name"] for r in results if r["status"] == CRITICAL}
        # Log on EDGES only (an alert that fires once per tick is noise):
        # observe-only mode's entire output is these two lines.
        for name in sorted(critical_now - self._was_critical):
            logger.warning(
                "SLO %s burn is CRITICAL%s", name,
                " — shedding new jobs" if self.shed else " (observe-only)",
            )
        for name in sorted(self._was_critical - critical_now):
            logger.warning("SLO %s recovered", name)
        self._was_critical = critical_now
        with self._lock:
            self._last = out
            self._last_at = now
        return out

    def status(self, max_age: float = 1.0) -> dict:
        """The last evaluation, re-evaluated when older than ``max_age``
        seconds (the sampler thread keeps it fresh; callers without one —
        tests, a sampler-less embedder — transparently evaluate inline)."""
        with self._lock:
            last, last_at = self._last, self._last_at
        if last is not None and self._clock() - last_at <= max_age:
            return last
        return self.evaluate()

    def should_shed(self) -> tuple[bool, float]:
        """(shed?, Retry-After seconds) for the admission path. Never
        evaluates inline with a cold cache older than 2 s — admission
        latency must not pay an SLO evaluation per request."""
        if not self.shed:
            return False, 0.0
        status = self.status(max_age=2.0)
        return status["shed"]["active"], self.retry_after_s

    # -- flight-recorder state provider ------------------------------------

    def state(self) -> dict:
        """Compact snapshot for flight dumps: overall status plus each
        objective's binding burn — what was the service's health the moment
        it died."""
        status = self._last
        if status is None:
            return {"status": "never-evaluated"}
        return {
            "status": status["status"],
            "shed_enabled": status["shed"]["enabled"],
            "shed_active": status["shed"]["active"],
            **{f"burn.{r['name']}": r["burn"]
               for r in status["objectives"]},
        }


def render_status(status: dict) -> str:
    """``gol slo-report``: one table from a ``GET /slo`` payload (or the
    ``slo`` state record of a flight dump rendered via ``state`` keys)."""
    lines = [f"SLO status: {status.get('status', '?')}"]
    objectives = status.get("objectives")
    if not objectives:
        # A flight-dump state record: shedding is flattened into
        # shed_enabled/shed_active (see ``SloEngine.state``) and burns into
        # burn.* keys — a post-mortem must still answer "was the server
        # rejecting traffic when it died".
        lines.append(
            "shedding: "
            + ("enabled" if status.get("shed_enabled") else "observe-only")
            + (" (ACTIVE)" if status.get("shed_active") else "")
        )
        for key in sorted(k for k in status if k.startswith("burn.")):
            lines.append(f"  {key[5:]}: burn {status[key]}")
        return "\n".join(lines) + "\n"
    shed = status.get("shed") or {}
    lines.append(
        f"shedding: {'enabled' if shed.get('enabled') else 'observe-only'}"
        + (" (ACTIVE)" if shed.get("active") else "")
    )
    windows = [f"{w}s" for w in status.get("windows_s", [])]
    header = f"{'objective':<24} {'kind':<11} {'target':>10} {'status':>9}"
    for w in windows:
        header += f" {'burn@' + w:>11}"
    lines += ["", header, "-" * len(header)]
    for r in objectives:
        row = (f"{r['name']:<24} {r['kind']:<11} {r['target']:>10g} "
               f"{r['status']:>9}")
        for w in windows:
            win = (r.get("windows") or {}).get(w) or {}
            row += f" {win.get('burn', 0.0):>11.3f}"
        lines.append(row)
    return "\n".join(lines) + "\n"


__all__ = [
    "CRITICAL", "OK", "WARNING", "DEFAULT_WINDOWS", "STATE_PROVIDER",
    "Objective", "SloEngine", "default_objectives", "render_status",
]
