"""Per-job timelines: the milestone/segment vocabulary of the serving path.

The reference instruments whole-program phases (include/timestamp.h wraps
read/execute/write once per run); PR 4's spans instrument *regions* of the
server. Neither answers the operator's question for ONE request: *where did
this job's latency go?* This module defines the causal decomposition every
``Job`` carries from ``POST /jobs`` to its journaled DONE:

milestones (``time.perf_counter()`` stamps, process-local, stamped by the
scheduler identically across the classic depth-1, pipelined
(``--pipeline-depth``), and resident-ring lanes)::

    accepted        admission succeeded (journal submit record durable)
    claimed         a forming batch took the job (batch formation ended)
    stage_start     host staging began (stack + packbits)
    staged          host staging done
    dispatched      async device dispatch posted
    readback_start  the completer began blocking on device results
    completed       device results fetched and cropped
    done            job transitioned DONE (results visible to clients)
    journaled       the terminal journal record hit disk (may trail ``done``
                    in resident mode, where journaling rides a writer thread)

segments are the gaps between consecutive *present* milestones — jobs on an
injected ``run_batch`` (no stage/dispatch split) simply have fewer — so the
segment sum from ``accepted`` to ``done`` equals the measured end-to-end
latency *exactly*, by construction (test-pinned). The ``journal`` segment
sits past ``done`` and is reported separately as ``journal_lag_seconds``.

Served as ``GET /jobs/<id>/timeline``, printed by ``gol submit`` on
completion, and (with tracing on) mirrored into the Chrome export as flow
events (``obs.trace.flow``) tying each job to the batch spans it rode.
"""

from __future__ import annotations

# Milestone order IS the contract: stamps must be monotonic along this list
# (a retry re-stamps its dispatch/readback milestones, still before `done`).
MILESTONES = (
    "accepted",
    "claimed",
    "stage_start",
    "staged",
    "dispatched",
    "readback_start",
    "completed",
    "done",
    "journaled",
)

# The segment *ending* at each milestone (the time since the previous
# present milestone). Names follow the ISSUE's decomposition: queue-wait,
# batch-formation wait, stage, dispatch, device, readback, finalize, journal.
SEGMENT_ENDING_AT = {
    "claimed": "queue_wait",
    "stage_start": "batch_form",
    "staged": "stage",
    "dispatched": "dispatch",
    "readback_start": "device",
    "completed": "readback",
    "done": "finalize",
    "journaled": "journal",
}


def segments(timeline: dict) -> dict[str, float]:
    """Decompose a milestone dict into named segments (seconds).

    Only consecutive *present* milestones produce a segment, so partial
    timelines (in-flight jobs, injected engines with no split) stay
    well-formed and the sum of the segments up to ``done`` always equals
    ``done - accepted``.
    """
    out: dict[str, float] = {}
    prev = None
    for name in MILESTONES:
        t = timeline.get(name)
        if t is None:
            continue
        if prev is not None:
            out[SEGMENT_ENDING_AT[name]] = t - prev
        prev = t
    return out


def summary(timeline: dict) -> dict:
    """The JSON-able view ``GET /jobs/<id>/timeline`` serves.

    Milestones are reported relative to ``accepted`` (perf_counter values
    are process-local and meaningless on the wire); ``total_seconds`` is the
    end-to-end latency (accepted -> done) and ``journal_lag_seconds`` how
    far the durable done record trailed it (0 inline, > 0 on the resident
    lanes' journal writer thread)."""
    t0 = timeline.get("accepted")
    out: dict = {
        "milestones": (
            {n: timeline[n] - t0 for n in MILESTONES if n in timeline}
            if t0 is not None
            else {}
        ),
        "segments": segments(timeline),
    }
    done = timeline.get("done")
    if t0 is not None and done is not None:
        out["total_seconds"] = done - t0
    journaled = timeline.get("journaled")
    if done is not None and journaled is not None:
        out["journal_lag_seconds"] = max(0.0, journaled - done)
    return out
